"""CLI: ``python -m hack.dfanalyze [options] [package_dir]``.

Exit 0 only when every pass is clean: zero unallowlisted findings, no
stale allowlist entries, no malformed allowlist lines. ``--json`` emits
the machine-readable report on stdout (CI and hack/lint.sh consume it).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from . import DEFAULT_PACKAGE, render_text, run, to_json
from .passes import ALL_PASSES


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dfanalyze",
        description="project-wide static analysis for dragonfly2_tpu",
    )
    ap.add_argument(
        "package_dir", nargs="?", default=str(DEFAULT_PACKAGE),
        help="package to analyze (default: the repo's dragonfly2_tpu/)",
    )
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument(
        "--pass", dest="passes", action="append", metavar="ID",
        help="run only this pass (repeatable); default: all",
    )
    ap.add_argument(
        "--witness-report", metavar="FILE",
        help="cross-check a lock-witness dump (DF_LOCK_WITNESS run) against"
        " the static lock graph",
    )
    ap.add_argument(
        "--jit-witness-report", metavar="FILE",
        help="cross-check a jit-witness dump (DF_JIT_WITNESS run) against"
        " the static jit sites: retrace storms, wrapper churn, implicit"
        " transfers in device-hot modules",
    )
    ap.add_argument(
        "--update-mypy-baseline", action="store_true",
        help="rewrite the typecheck baseline from a fresh mypy run",
    )
    ap.add_argument("--list-passes", action="store_true")
    args = ap.parse_args(argv)

    if args.list_passes:
        for p in ALL_PASSES:
            print(f"{p.id:12s} {p.description}")
        return 0
    if args.update_mypy_baseline:
        from .passes import typecheck

        n = typecheck.update_baseline(Path(args.package_dir))
        print(f"dfanalyze[typecheck]: baseline rewritten with {n} violation(s)")
        return 0

    report = run(
        package_dir=Path(args.package_dir),
        pass_ids=args.passes,
        witness_report=Path(args.witness_report) if args.witness_report else None,
        jit_witness_report=(
            Path(args.jit_witness_report) if args.jit_witness_report else None
        ),
    )
    if args.json:
        print(to_json(report))
    else:
        print(render_text(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
