"""Runtime jit witness: record what actually compiled and what actually
transferred, so dispatch regressions the AST can't see (shape churn from
real data, a numpy array slipping into a jitted call three frames down)
still get caught — the XLA-plane twin of the lock witness.

``install()`` hooks three seams, all before the package imports:

- **compile logging** — jax logs every XLA compilation through
  ``jax._src.interpreters.pxla`` ("Compiling <fn> with global shapes and
  types [...]"); a handler on that logger records, per wrapped-function
  name, the distinct argument signatures compiled. No global jax flag is
  touched: the record is emitted at DEBUG when ``jax_log_compiles`` is
  off, so the witness captures it without turning the WARNING firehose
  on for the whole run.
- **``jax.jit`` itself** — replaced with a factory that (a) records the
  construction site when the caller is package code (a site constructing
  many wrappers is a per-call rebuild: each wrapper carries its own
  compile cache), and (b) wraps the returned callable to record an
  *implicit-transfer site* whenever a numpy leaf is passed straight into
  a jitted call from package code — on a real device link that is a
  silent H2D per call. Explicit conversions (``jnp.asarray`` /
  ``device_put`` at the boundary) produce jax Arrays and don't trip it.
- **``jax.device_put``** — recorded as *explicit* transfer sites, so the
  report can show sanctioned transfers next to the silent ones.

``jax.transfer_guard`` is the enforcement escalation: set
``DF_JIT_WITNESS_GUARD=log`` (C++ prints every implicit transfer's aval
to stderr) or ``=disallow`` (every implicit transfer raises at its exact
site) and ``install()`` applies it process-wide. The JSON dump stays the
witness's own record either way — the guard's log lands in C++ stderr
where Python can't join it.

Opt-in: ``DF_JIT_WITNESS=1`` makes ``tests/conftest.py`` call
``install()`` and dump to ``DF_JIT_WITNESS_OUT`` (default
``dfanalyze-jit-witness.json``) at session end, for
``python -m hack.dfanalyze --jit-witness-report <dump>``.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time

_state_lock = threading.Lock()

_installed = False
_package_roots: tuple[str, ...] = ()
_raw_jit = None
_raw_device_put = None
_handler = None
_logger_prev: tuple | None = None  # (level, propagate)

# fn name -> {"count": total compiles, "signatures": set of sig strings}
_compiles: dict[str, dict] = {}
# ("file:line", wrapped fn name) -> wrappers built. Keyed by target TOO:
# a shared memoization helper (utils.jitcache.jit_once) constructs many
# DISTINCT functions' wrappers at one line, one each — site-only keying
# would sum them into a false churn verdict against the helper itself
_wrapper_sites: dict[tuple[str, str], int] = {}
# (file, fn, line, target, explicit, thread) -> count. Thread names are
# part of the record so the report can enforce WHERE a transfer ran —
# the ingest pipeline's contract is that every device feed lives on the
# dedicated transfer stage, never the packing thread (ISSUE 15)
_transfers: dict[tuple, int] = {}

# a function compiled for hundreds of shapes only needs enough recorded
# signatures to prove the storm; cap the per-function set
_MAX_SIGS_KEPT = 64

_PXLA_LOGGER = "jax._src.interpreters.pxla"


def _note_compile(name: str, sig: str) -> None:
    with _state_lock:
        info = _compiles.setdefault(name, {"count": 0, "signatures": set()})
        info["count"] += 1
        if len(info["signatures"]) < _MAX_SIGS_KEPT:
            info["signatures"].add(sig)


class _CompileLogHandler(logging.Handler):
    """Parses pxla's per-compilation record. Message shape (stable since
    the pjit unification): ``Compiling <name> with global shapes and
    types [<avals>]. Argument mapping: ...``."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # a mis-formatted record must never kill the run
            return
        if not msg.startswith("Compiling "):
            return
        rest = msg[len("Compiling "):]
        name, sep, tail = rest.partition(" with global shapes and types ")
        if not sep:
            return
        sig = tail.split(". Argument mapping", 1)[0]
        _note_compile(name, sig)


def _rel_site(filename: str, lineno: int) -> str | None:
    for root in _package_roots:
        if root in filename:
            rel = root + filename.rsplit(root, 1)[1]
            return f"{rel}:{lineno}"
    return None


def _package_frame() -> tuple[str, str, int] | None:
    """(relpath, function, line) of the nearest package frame, skipping
    this module's own frames. None when no package code is on the stack
    (a test or tool driving jax directly is not the package's bug)."""
    f = sys._getframe(2)
    depth = 0
    while f is not None and depth < 30:
        fn = f.f_code.co_filename
        if fn != __file__:
            site = _rel_site(fn, f.f_lineno)
            if site is not None:
                rel, _, line = site.rpartition(":")
                return rel, f.f_code.co_name, int(line)
        f = f.f_back
        depth += 1
    return None


def _note_transfer(target: str, explicit: bool) -> None:
    frame = _package_frame()
    if frame is None:
        return
    rel, fn, line = frame
    key = (rel, fn, line, target, explicit, threading.current_thread().name)
    with _state_lock:
        _transfers[key] = _transfers.get(key, 0) + 1


def _has_host_leaf(tree) -> bool:
    import numpy as np

    from jax import tree_util

    for leaf in tree_util.tree_leaves(tree):
        if isinstance(leaf, np.ndarray):
            return True
    return False


class _WitnessJit:
    """Transparent proxy over the real jit wrapper: records implicit
    host-leaf feeds, forwards everything else (lower/clear_cache/attrs)."""

    __slots__ = ("_fn", "_target")

    def __init__(self, fn, target: str):
        self._fn = fn
        self._target = target

    def __call__(self, *args, **kwargs):
        if _has_host_leaf((args, kwargs)):
            _note_transfer(self._target, explicit=False)
        return self._fn(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __repr__(self) -> str:
        return f"<WitnessJit {self._target} {self._fn!r}>"


def _direct_package_frame() -> tuple[str, str, int] | None:
    """Like ``_package_frame`` but only accepts the IMMEDIATE caller
    (first frame outside this module): jax-internal machinery (pallas,
    custom-call lowering) constructs jits of its own with package code
    further up-stack, and charging those to the package would read as
    wrapper churn the package can't fix."""
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return None
    site = _rel_site(f.f_code.co_filename, f.f_lineno)
    if site is None:
        return None
    rel, _, line = site.rpartition(":")
    return rel, f.f_code.co_name, int(line)


def _witness_jit(fun=None, **kwargs):
    if fun is None:
        # functools.partial(jax.jit, static_argnames=...) applied later
        import functools

        return functools.partial(_witness_jit, **kwargs)
    wrapped = _raw_jit(fun, **kwargs)
    frame = _direct_package_frame()
    if frame is None:
        return wrapped  # not package code: hand back the raw wrapper
    rel, _, line = frame
    target = getattr(fun, "__name__", repr(fun))
    with _state_lock:
        key = (f"{rel}:{line}", target)
        _wrapper_sites[key] = _wrapper_sites.get(key, 0) + 1
    return _WitnessJit(wrapped, target)


def _witness_device_put(x, *args, **kwargs):
    if _has_host_leaf(x):
        _note_transfer("device_put", explicit=True)
    return _raw_device_put(x, *args, **kwargs)


def install(package_roots: tuple[str, ...] = ("dragonfly2_tpu/",)) -> None:
    """Patch the jax seams. Requires jax importable; call BEFORE the
    package imports so module-level jit constructions are witnessed."""
    global _installed, _package_roots, _raw_jit, _raw_device_put
    global _handler, _logger_prev
    if _installed:
        return
    import jax

    _package_roots = tuple(package_roots)
    _raw_jit = jax.jit
    _raw_device_put = jax.device_put
    jax.jit = _witness_jit
    jax.device_put = _witness_device_put

    lg = logging.getLogger(_PXLA_LOGGER)
    _logger_prev = (lg.level, lg.propagate)
    _handler = _CompileLogHandler(level=logging.DEBUG)
    lg.addHandler(_handler)
    lg.setLevel(logging.DEBUG)
    # DEBUG spam from pxla must not leak into pytest's captured logs or
    # stderr — the witness is the only consumer of these records
    lg.propagate = False

    guard = os.environ.get("DF_JIT_WITNESS_GUARD", "")
    if guard:
        jax.config.update("jax_transfer_guard", guard)
    _installed = True


def uninstall() -> None:
    global _installed, _handler, _logger_prev
    if not _installed:
        return
    import jax

    jax.jit = _raw_jit
    jax.device_put = _raw_device_put
    lg = logging.getLogger(_PXLA_LOGGER)
    if _handler is not None:
        lg.removeHandler(_handler)
        _handler = None
    if _logger_prev is not None:
        lg.setLevel(_logger_prev[0])
        lg.propagate = _logger_prev[1]
        _logger_prev = None
    _installed = False


def active() -> bool:
    return _installed


def reset() -> None:
    with _state_lock:
        _compiles.clear()
        _wrapper_sites.clear()
        _transfers.clear()


def snapshot() -> dict:
    with _state_lock:
        return {
            "compiles": {
                n: {"count": v["count"], "signatures": sorted(v["signatures"])}
                for n, v in sorted(_compiles.items())
            },
            "wrapper_sites": [
                {"site": site, "target": target, "count": n}
                for (site, target), n in sorted(_wrapper_sites.items())
            ],
            "transfers": [
                {
                    "file": rel,
                    "fn": fn,
                    "line": line,
                    "target": target,
                    "explicit": explicit,
                    "thread": thread,
                    "count": n,
                }
                for (rel, fn, line, target, explicit, thread), n in sorted(
                    _transfers.items()
                )
            ],
        }


def dump(path: str | None = None) -> str:
    path = path or os.environ.get(
        "DF_JIT_WITNESS_OUT", "dfanalyze-jit-witness.json"
    )
    with open(path, "w") as f:
        json.dump(snapshot(), f, indent=2, sort_keys=True)
    return path


# -- bench taps --------------------------------------------------------------
# Lightweight context managers for bench.py's jit-hygiene keys: count
# compiles and host→device conversions over a measured region without
# installing the full witness (no jax.jit patch, no site attribution).


class compile_tap:
    """``with compile_tap() as t: ...`` → ``t.count`` XLA compilations
    observed in the region (any function, any thread)."""

    def __init__(self):
        self.count = 0
        self.names: list[str] = []

    def __enter__(self):
        outer = self

        class _H(logging.Handler):
            def emit(self, record):
                try:
                    msg = record.getMessage()
                except Exception:
                    return
                if msg.startswith("Compiling "):
                    outer.count += 1
                    outer.names.append(msg[len("Compiling "):].split(" ", 1)[0])
                    _metric_inc("jit_recompiles")

        self._h = _H(level=logging.DEBUG)
        lg = logging.getLogger(_PXLA_LOGGER)
        self._prev = (lg.level, lg.propagate)
        lg.addHandler(self._h)
        lg.setLevel(logging.DEBUG)
        lg.propagate = False
        return self

    def __exit__(self, *exc):
        lg = logging.getLogger(_PXLA_LOGGER)
        lg.removeHandler(self._h)
        # another tap/witness may still be live on this logger: only
        # restore when ours was the last handler standing
        if not lg.handlers:
            lg.setLevel(self._prev[0])
            lg.propagate = self._prev[1]


class transfer_tap:
    """``with transfer_tap() as t: ...`` → ``t.h2d`` host→device
    conversions (``jax.device_put`` / ``jnp.asarray`` called with a
    numpy array) in the region — the H2D count as the package dispatches
    it, one increment per superbatch on the steady-state single-device
    ingest path, one per DEVICE SHARD on the mesh path (the
    per-device sharded put). ``t.by_thread`` attributes each conversion
    to the thread that issued it, so the multichip harness can pin
    the no-device-work-on-the-packing-thread contract."""

    def __init__(self):
        self.h2d = 0
        self.by_thread: dict[str, int] = {}

    def _note(self):
        self.h2d += 1
        name = threading.current_thread().name
        self.by_thread[name] = self.by_thread.get(name, 0) + 1

    def __enter__(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        outer = self
        self._jax, self._jnp = jax, jnp
        self._raw_put = jax.device_put
        self._raw_asarray = jnp.asarray
        # jnp.asarray lands on the public jax.device_put internally —
        # without a reentrancy guard every conversion double-counts
        tls = self._tls = threading.local()

        def put(x, *a, **kw):
            if getattr(tls, "depth", 0) == 0 and _any_np(x, np):
                outer._note()
                _metric_inc("h2d_transfers")
                t0 = time.perf_counter()
                try:
                    return outer._raw_put(x, *a, **kw)
                finally:
                    _phase_observe("device_transfer", time.perf_counter() - t0)
            return outer._raw_put(x, *a, **kw)

        def asarray(x, *a, **kw):
            timed = isinstance(x, np.ndarray)
            if timed:
                outer._note()
                _metric_inc("h2d_transfers")
                t0 = time.perf_counter()
            tls.depth = getattr(tls, "depth", 0) + 1
            try:
                return outer._raw_asarray(x, *a, **kw)
            finally:
                tls.depth -= 1
                if timed:
                    _phase_observe("device_transfer", time.perf_counter() - t0)

        jax.device_put = put
        jnp.asarray = asarray
        return self

    def __exit__(self, *exc):
        self._jax.device_put = self._raw_put
        self._jnp.asarray = self._raw_asarray


def _any_np(tree, np) -> bool:
    from jax import tree_util

    return any(isinstance(l, np.ndarray) for l in tree_util.tree_leaves(tree))


def _metric_inc(kind: str) -> None:
    """Feed the live trainer series when the package is importable —
    the witness's counts double as scrapeable counters (census-covered
    in trainer/metrics.py)."""
    try:
        from dragonfly2_tpu.trainer import metrics as M
    except Exception:
        return
    if kind == "jit_recompiles":
        M.JIT_RECOMPILES_TOTAL.inc()
        # count-marker in the dfprof ledger: a moving trainer.jit_compile
        # count mid-fit IS the retrace storm, visible on /debug/prof
        _phase_observe("jit_compile", 0.0)
    else:
        M.H2D_TRANSFERS_TOTAL.inc()


def _phase_observe(kind: str, seconds: float) -> None:
    """Attribute device-side time into the dfprof phase ledger
    (trainer.device_transfer timed per conversion, trainer.jit_compile
    a count marker) while a tap is armed."""
    try:
        from dragonfly2_tpu.trainer import metrics as M
    except Exception:
        return
    ph = M.PH_DEVICE_TRANSFER if kind == "device_transfer" else M.PH_JIT_COMPILE
    ph.observe(seconds)
