"""dfanalyze — pluggable static analysis for the dragonfly2_tpu package.

Grown out of ``hack/check_metrics.py`` (now one pass here) after three
rounds of review-time tax on defects a tool should catch: PR 2's ABBA
deadlock between ``_flush_lock`` and ``_lock`` in ``topology/engine.py``,
and repeated hand-hoisting of per-call imports out of the schedule hot
path. The reference tree leans on Go's race detector and ``go vet`` for
this class of bug; this is our equivalent, AST-shaped for lock-heavy
threaded Python.

Passes (see ``hack/dfanalyze/passes/``):

- ``lock-order``   — per-module lock-acquisition graph; ABBA cycles and
                     plain-Lock re-entry fail.
- ``blocking``     — gRPC calls, file/socket I/O, queue waits,
                     ``time.sleep`` and jax dispatch while a lock is held.
- ``hygiene``      — hot-path lints: function-local imports in modules
                     tagged ``# dfanalyze: hot``, bare ``except: pass``
                     in loops, fire-and-forget ContextVar ``set()``.
- ``jaxhygiene``   — XLA-dispatch hygiene: host-sync/side-effect/branch
                     constructs inside jit-traced functions, per-call
                     jit-wrapper construction and whole-array host pulls
                     in ``# dfanalyze: device-hot`` modules, unstable
                     static args.
- ``metrics``      — the metric/event/fault-point census (the absorbed
                     check_metrics).
- ``typecheck``    — mypy with a checked-in baseline (skips cleanly when
                     mypy isn't installed in the image).

Audited exceptions live in ``hack/dfanalyze/allowlist.txt``; every entry
needs a justifying comment, and entries no pass matches fail the run
(stale allowlists rot into blanket mufflers otherwise). The runtime
lock-witness (``hack/dfanalyze/witness.py``, armed via
``DF_LOCK_WITNESS=1`` through ``tests/conftest.py``) records the orders
the AST can't see and ``--witness-report`` cross-checks them against the
static graph; the jit witness (``hack/dfanalyze/jitwitness.py``, armed
via ``DF_JIT_WITNESS=1``) records what actually compiled/transferred and
``--jit-witness-report`` joins that onto the static jit sites.

Run ``python -m hack.dfanalyze`` (or ``--json`` for machines).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
DEFAULT_PACKAGE = REPO_ROOT / "dragonfly2_tpu"
ALLOWLIST_PATH = Path(__file__).resolve().parent / "allowlist.txt"

# Witness cross-checks run over whatever the DYNAMIC run happened to
# cover — a subset pytest run legitimately exercises none of the
# allowlisted storms/orders, and even a full tier-1's coverage varies
# with timing and skips, so staleness cannot be decided mechanically
# from any one run. Witness-pass entries are therefore exempt from the
# stale-entry rule; pruning them is a REVIEW job — each entry's
# mandatory `# why` names the code it excuses, so delete the entry when
# that code changes (e.g. the make_epoch_fn per-fit wrapper gets
# memoized → drop its jit-rewrap entry in the same PR).
DYNAMIC_PASSES = frozenset({"lock-witness", "jit-witness"})


@dataclass
class Finding:
    pass_id: str
    key: str  # stable allowlist key — no spaces, no line numbers
    file: str
    line: int
    message: str
    allowlisted: bool = False

    def as_dict(self) -> dict:
        return {
            "pass": self.pass_id,
            "key": self.key,
            "file": self.file,
            "line": self.line,
            "message": self.message,
            "allowlisted": self.allowlisted,
        }


@dataclass
class PassResult:
    pass_id: str
    findings: list[Finding] = field(default_factory=list)
    skipped: str = ""  # non-empty = skip reason (e.g. "mypy not installed")


@dataclass
class Allowlist:
    entries: dict[tuple[str, str], str] = field(default_factory=dict)  # (pass,key)->comment
    used: set = field(default_factory=set)
    errors: list[str] = field(default_factory=list)

    @classmethod
    def load(cls, path: Path = ALLOWLIST_PATH) -> "Allowlist":
        al = cls()
        if not path.is_file():
            return al
        for i, raw in enumerate(path.read_text().splitlines(), 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if " # " not in line:
                al.errors.append(
                    f"allowlist.txt:{i}: entry has no ' # why' comment —"
                    " audited exceptions must say why they are safe"
                )
                continue
            body, comment = line.split(" # ", 1)
            parts = body.split()
            if len(parts) != 2 or not comment.strip():
                al.errors.append(
                    f"allowlist.txt:{i}: expected '<pass-id> <key>  # why'"
                )
                continue
            al.entries[(parts[0], parts[1])] = comment.strip()
        return al

    def match(self, f: Finding) -> bool:
        k = (f.pass_id, f.key)
        if k in self.entries:
            self.used.add(k)
            return True
        return False

    def stale(self, ran_passes: set[str]) -> list[str]:
        out = []
        for (pass_id, key) in sorted(self.entries):
            if pass_id in ran_passes and (pass_id, key) not in self.used:
                out.append(f"{pass_id} {key}")
        return out


def run(
    package_dir: Path | None = None,
    pass_ids: list[str] | None = None,
    allowlist: Allowlist | None = None,
    witness_report: Path | None = None,
    jit_witness_report: Path | None = None,
) -> dict:
    """Run the selected passes; returns the machine-readable report.
    ``report["ok"]`` is the exit condition: no unallowlisted findings, no
    stale allowlist entries, no malformed allowlist lines."""
    from .passes import ALL_PASSES  # late: passes import this module

    package_dir = Path(package_dir or DEFAULT_PACKAGE)
    allowlist = allowlist or Allowlist.load()
    errors: list[str] = []
    selected = [
        p for p in ALL_PASSES if pass_ids is None or p.id in pass_ids
    ]
    if pass_ids is not None:
        # a typo'd --pass must FAIL, not silently select nothing and
        # report the repo clean forever
        known = {p.id for p in ALL_PASSES}
        for pid in pass_ids:
            if pid not in known:
                errors.append(
                    f"unknown pass id {pid!r} (known: {sorted(known)})"
                )
    results: list[PassResult] = []
    for p in selected:
        results.append(p.run(package_dir))
    if witness_report is not None:
        from .passes import lockorder

        if not Path(witness_report).is_file():
            # an explicit cross-check request with no dump is an error —
            # a cwd/path mismatch must not read as "zero inversions"
            errors.append(
                f"witness report not found: {witness_report} (run the"
                " suite with DF_LOCK_WITNESS=1 first; the dump lands in"
                " the pytest cwd or DF_LOCK_WITNESS_OUT)"
            )
        else:
            results.append(
                lockorder.witness_crosscheck(package_dir, Path(witness_report))
            )
    if jit_witness_report is not None:
        from .passes import jaxhygiene

        if not Path(jit_witness_report).is_file():
            # same contract as the lock witness: an explicit cross-check
            # request with no dump must fail, not read as "zero storms"
            errors.append(
                f"jit-witness report not found: {jit_witness_report} (run the"
                " suite with DF_JIT_WITNESS=1 first; the dump lands in the"
                " pytest cwd or DF_JIT_WITNESS_OUT)"
            )
        else:
            results.append(
                jaxhygiene.witness_crosscheck(
                    package_dir, Path(jit_witness_report)
                )
            )

    unallowlisted = 0
    for r in results:
        for f in r.findings:
            f.allowlisted = allowlist.match(f)
            if not f.allowlisted:
                unallowlisted += 1
    stale = allowlist.stale(
        {r.pass_id for r in results if not r.skipped} - DYNAMIC_PASSES
    )
    report = {
        "package": str(package_dir),
        "passes": [
            {
                "id": r.pass_id,
                "status": (
                    "skipped"
                    if r.skipped
                    else ("findings" if any(not f.allowlisted for f in r.findings) else "ok")
                ),
                "skipped": r.skipped,
                "findings": [f.as_dict() for f in r.findings],
            }
            for r in results
        ],
        "summary": {
            "findings": sum(len(r.findings) for r in results),
            "unallowlisted": unallowlisted,
            "allowlisted": sum(
                1 for r in results for f in r.findings if f.allowlisted
            ),
            "stale_allowlist": stale,
            "allowlist_errors": allowlist.errors,
            "errors": errors,
        },
    }
    report["ok"] = (
        unallowlisted == 0 and not stale and not allowlist.errors and not errors
    )
    return report


def render_text(report: dict) -> str:
    lines = []
    for p in report["passes"]:
        if p["skipped"]:
            lines.append(f"dfanalyze[{p['id']}]: SKIPPED — {p['skipped']}")
            continue
        shown = 0
        for f in p["findings"]:
            if f["allowlisted"]:
                continue
            shown += 1
            lines.append(
                f"dfanalyze[{p['id']}]: {f['file']}:{f['line']}: {f['message']}"
            )
            lines.append(f"    allowlist key: {p['id']} {f['key']}")
        allowed = sum(1 for f in p["findings"] if f["allowlisted"])
        status = "OK" if shown == 0 else f"{shown} finding(s)"
        extra = f" ({allowed} allowlisted)" if allowed else ""
        lines.append(f"dfanalyze[{p['id']}]: {status}{extra}")
    s = report["summary"]
    for e in s.get("errors", ()):
        lines.append(f"dfanalyze: ERROR: {e}")
    for e in s["allowlist_errors"]:
        lines.append(f"dfanalyze: {e}")
    for e in s["stale_allowlist"]:
        lines.append(
            f"dfanalyze: stale allowlist entry (matched nothing): {e}"
        )
    if report["ok"]:
        verdict = "OK"
    else:
        parts = []
        if s["unallowlisted"]:
            parts.append(f"{s['unallowlisted']} unallowlisted finding(s)")
        if s["stale_allowlist"]:
            parts.append(f"{len(s['stale_allowlist'])} stale allowlist entr(ies)")
        n_err = len(s.get("errors", ())) + len(s["allowlist_errors"])
        if n_err:
            parts.append(f"{n_err} error(s)")
        verdict = "FAILED: " + ", ".join(parts)
    lines.append(f"dfanalyze: {verdict} over {report['package']}")
    return "\n".join(lines)


def to_json(report: dict) -> str:
    return json.dumps(report, indent=2, sort_keys=True)
