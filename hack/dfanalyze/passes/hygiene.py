"""hygiene: hot-path lints the last three rounds paid review tax for.

- **function-local imports in hot modules** — modules carrying a
  ``# dfanalyze: hot`` marker are on a per-call path (schedule ops,
  per-RPC wrappers, per-piece accounting); an ``import`` inside one of
  their functions is a dict lookup + lock in the steady state and a
  filesystem walk on the first call, both of which PRs 2–3 repeatedly
  hand-hoisted. Deliberate lazy imports (heavy deps like jax behind a
  backend switch, true import cycles) get allowlisted with the reason.
- **bare ``except: pass`` in loops** — a loop that swallows every
  exception silently is how a dead socket spins a core or a poison item
  recirculates forever; name the exception or log it.
- **fire-and-forget ContextVar ``set()``** — a ``var.set(...)`` whose
  token is discarded can never be ``reset()``; on a pooled thread the
  value leaks into whatever request the worker picks up next (the bug
  class the tracing layer's ``use_span`` exists to prevent).
"""

from __future__ import annotations

import ast
from pathlib import Path

from .. import Finding, PassResult

ID = "hygiene"

HOT_MARKER = "dfanalyze: hot"


def _is_except_pass(handler: ast.ExceptHandler) -> bool:
    if not (len(handler.body) == 1 and isinstance(handler.body[0], ast.Pass)):
        return False
    t = handler.type
    if t is None:
        return True
    return isinstance(t, ast.Name) and t.id in ("Exception", "BaseException")


def _module_findings(tree: ast.Module, rel: str, text: str) -> list[Finding]:
    findings: list[Finding] = []
    hot = HOT_MARKER in text

    def walk(node: ast.AST, qual: str, in_fn: bool, loop_depth: int, ordinal: dict):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{qual}.{child.name}" if qual else child.name
                walk(child, q, True, 0, ordinal)
                continue
            if isinstance(child, ast.ClassDef):
                q = f"{qual}.{child.name}" if qual else child.name
                walk(child, q, in_fn, loop_depth, ordinal)
                continue
            if isinstance(child, (ast.Import, ast.ImportFrom)) and in_fn and hot:
                if isinstance(child, ast.Import):
                    mods = [a.name for a in child.names]
                else:
                    mods = [child.module or "."]
                for mod in mods:
                    findings.append(
                        Finding(
                            ID,
                            f"import:{rel}:{qual}:{mod}",
                            rel,
                            child.lineno,
                            f"function-local import of {mod} in {qual}() —"
                            " module is tagged hot; hoist to module scope"
                            " (or allowlist a deliberate lazy import)",
                        )
                    )
            if isinstance(child, ast.ExceptHandler) and loop_depth > 0:
                if _is_except_pass(child):
                    tname = (
                        "bare"
                        if child.type is None
                        else child.type.id  # type: ignore[union-attr]
                    )
                    n = ordinal.get((qual, tname), 0)
                    ordinal[(qual, tname)] = n + 1
                    suffix = f":{n}" if n else ""
                    findings.append(
                        Finding(
                            ID,
                            f"except-pass:{rel}:{qual}:{tname}{suffix}",
                            rel,
                            child.lineno,
                            f"`except {'' if child.type is None else tname}:"
                            f" pass` inside a loop in {qual}() swallows"
                            " every failure silently — narrow it or log",
                        )
                    )
            next_loop = loop_depth + (
                1 if isinstance(child, (ast.For, ast.While, ast.AsyncFor)) else 0
            )
            walk(child, qual, in_fn, next_loop, ordinal)

    walk(tree, "", False, 0, {})

    # ContextVar discipline: find module-level ContextVars, then flag
    # set() calls whose token is dropped, and vars set but never reset
    cvars: set[str] = set()
    for node in tree.body:
        targets: list = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if (
            isinstance(value, ast.Call)
            and isinstance(value.func, (ast.Name, ast.Attribute))
        ):
            chain = value.func.attr if isinstance(value.func, ast.Attribute) else value.func.id
            if chain == "ContextVar":
                for t in targets:
                    if isinstance(t, ast.Name):
                        cvars.add(t.id)
    if cvars:
        has_reset: set[str] = set()
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "reset"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in cvars
            ):
                has_reset.add(node.func.value.id)
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "set"
                and isinstance(node.value.func.value, ast.Name)
                and node.value.func.value.id in cvars
            ):
                continue
            var = node.value.func.value.id
            findings.append(
                Finding(
                    ID,
                    f"contextvar:{rel}:{var}:discarded",
                    rel,
                    node.lineno,
                    f"ContextVar {var}.set() discards its token — the value"
                    " can never be reset() and leaks across pooled-thread"
                    " reuse",
                )
            )
        for var in sorted(cvars - has_reset):
            sets = [
                n.lineno
                for n in ast.walk(tree)
                if isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "set"
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == var
            ]
            if sets:
                findings.append(
                    Finding(
                        ID,
                        f"contextvar:{rel}:{var}:noreset",
                        rel,
                        sets[0],
                        f"ContextVar {var} is set() but never reset() in this"
                        " module — pooled threads keep the stale value",
                    )
                )
    return findings


def run(package_dir: Path) -> PassResult:
    findings: list[Finding] = []
    root = package_dir.parent
    for path in sorted(package_dir.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        text = path.read_text()
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue
        findings.extend(
            _module_findings(tree, path.relative_to(root).as_posix(), text)
        )
    return PassResult(ID, findings)
