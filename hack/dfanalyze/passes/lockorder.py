"""lock-order: ABBA-cycle detection over the static lock-acquisition graph.

Builds the per-module lock model (``lockmodel``), merges every module's
``A held while acquiring B`` edges into one directed graph, and fails on

- **cycles** — two locks acquired in both orders somewhere in the package
  (the PR 2 shape: ``flush`` took ``_flush_lock -> _lock`` while
  ``export_records`` took ``_lock -> flush() -> _flush_lock``), and
- **plain-Lock re-entry** — ``with self._lock`` reached again (directly
  or via a same-class call chain) while already held, on a
  non-reentrant ``threading.Lock``.

Cycle findings carry every participating edge with its site and the call
chain (``via``) that created it. Allowlist keys are canonical node
sequences, no line numbers, so they survive unrelated edits.

``witness_crosscheck`` is the dynamic half: it loads a lock-witness
report (``hack/dfanalyze/witness.py`` dumps observed acquisition orders,
keyed by lock *creation site*), maps observed locks onto static nodes by
creation site, and re-runs cycle detection over the union graph — orders
only runtime can see (callbacks, plugin code, cross-object nesting)
still get caught.
"""

from __future__ import annotations

import json
from pathlib import Path

from .. import Finding, PassResult
from ..lockmodel import Edge, build_package_model

ID = "lock-order"


def _canonical_cycle(nodes: list[str]) -> str:
    """Rotate the cycle so the lexicographically smallest node leads —
    one stable key per cycle regardless of discovery order."""
    i = nodes.index(min(nodes))
    rot = nodes[i:] + nodes[:i]
    return "->".join(rot + [rot[0]])


def _find_cycles(graph: dict[str, set[str]]) -> list[list[str]]:
    """Simple-cycle enumeration, bounded: lock graphs here are tiny
    (tens of nodes). Returns each elementary cycle once."""
    cycles: list[list[str]] = []
    seen_keys: set[str] = set()

    def dfs(start: str, node: str, path: list[str], visited: set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) > 1:
                key = _canonical_cycle(path)
                if key not in seen_keys:
                    seen_keys.add(key)
                    cycles.append(list(path))
            elif nxt not in visited and nxt > start:
                # only enumerate cycles whose minimum node is `start`
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles


def _graph_findings(
    edges: list[Edge],
    kinds: dict[str, str],
    pass_id: str,
    extra_note: str = "",
) -> list[Finding]:
    graph: dict[str, set[str]] = {}
    evidence: dict[tuple[str, str], Edge] = {}
    findings: list[Finding] = []
    for e in edges:
        if e.src == e.dst:
            # re-entry: fatal on a plain Lock, by-design on an RLock
            if kinds.get(e.src) == "lock":
                key = f"self:{e.src}"
                if all(f.key != key for f in findings):
                    via = f" via {e.via}()" if e.via else ""
                    findings.append(
                        Finding(
                            pass_id,
                            key,
                            e.file,
                            e.line,
                            f"non-reentrant Lock {_short(e.src)} re-acquired while"
                            f" held{via} — self-deadlock",
                        )
                    )
            continue
        graph.setdefault(e.src, set()).add(e.dst)
        evidence.setdefault((e.src, e.dst), e)
    for cyc in _find_cycles(graph):
        key = f"cycle:{_canonical_cycle(cyc)}"
        pairs = list(zip(cyc, cyc[1:] + cyc[:1]))
        detail = "; ".join(
            f"{_short(a)}->{_short(b)} at {ev.file}:{ev.line}"
            + (f" via {ev.via}()" if ev.via else "")
            for a, b in pairs
            for ev in [evidence[(a, b)]]
        )
        first = evidence[pairs[0]]
        findings.append(
            Finding(
                pass_id,
                key,
                first.file,
                first.line,
                f"ABBA lock-order cycle{extra_note}: "
                + " -> ".join(_short(n) for n in cyc + [cyc[0]])
                + f" ({detail})",
            )
        )
    return findings


def _short(node: str) -> str:
    return node.rsplit("::", 1)[-1]


def run(package_dir: Path) -> PassResult:
    models = build_package_model(package_dir)
    edges: list[Edge] = []
    kinds: dict[str, str] = {}
    for m in models:
        edges.extend(m.edges)
        for n, d in m.locks.items():
            kinds[n] = d.kind
    return PassResult(ID, _graph_findings(edges, kinds, ID))


# -- witness cross-check -----------------------------------------------------

WITNESS_ID = "lock-witness"


def witness_crosscheck(package_dir: Path, report_path: Path) -> PassResult:
    """Union the witnessed (dynamic) acquisition orders with the static
    graph and re-run cycle detection. Dynamic locks map onto static nodes
    by creation site; a site the static registry doesn't know keeps its
    ``file:line`` identity so the finding still names a real place."""
    if not report_path.is_file():
        return PassResult(
            WITNESS_ID, skipped=f"no witness report at {report_path}"
        )
    data = json.loads(report_path.read_text())
    models = build_package_model(package_dir)
    kinds: dict[str, str] = {}
    by_site: dict[tuple[str, int], str] = {}
    edges: list[Edge] = []
    for m in models:
        edges.extend(m.edges)
        for n, d in m.locks.items():
            kinds[n] = d.kind
            by_site[(d.file, d.line)] = n

    def site_node(site: str) -> str:
        # witness sites are "<abspath-or-relpath>:<line>". Normalize on
        # the LAST "dragonfly2_tpu/" occurrence — a checkout whose
        # ancestor directory is itself named dragonfly2_tpu must not
        # unjoin every dynamic lock from its static node
        path, _, line = site.rpartition(":")
        rel = path
        if "dragonfly2_tpu/" in path:
            rel = "dragonfly2_tpu/" + path.rsplit("dragonfly2_tpu/", 1)[1]
        try:
            return by_site.get((rel, int(line)), f"{rel}::{line}")
        except ValueError:
            return site

    for entry in data.get("edges", []):
        src = site_node(entry["from"])
        dst = site_node(entry["to"])
        if src == dst:
            # one instance: RLock re-entry (by design) or impossible for
            # a plain Lock (acquire would have deadlocked, not recorded);
            # two instances at one site: the cross-instance loop below
            continue
        f, _, ln = entry["from"].rpartition(":")
        edges.append(Edge(src, dst, f, int(ln or 0), "witness"))
    findings = _graph_findings(
        edges, kinds, WITNESS_ID, extra_note=" (static+witnessed)"
    )
    # same-site cross-instance nesting: report separately (an RLock does
    # NOT make this safe — distinct instances are distinct locks)
    for entry in data.get("edges", []):
        if not entry.get("same_site"):
            continue
        node = site_node(entry["from"])
        key = f"cross-instance:{node}"
        if all(x.key != key for x in findings):
            f, _, ln = entry["from"].rpartition(":")
            findings.append(
                Finding(
                    WITNESS_ID,
                    key,
                    f,
                    int(ln or 0),
                    f"witness saw two instances of {_short(node)} nested —"
                    " cross-instance ordering needs an audited hierarchy",
                )
            )
    return PassResult(WITNESS_ID, findings)
