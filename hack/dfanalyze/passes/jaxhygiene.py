"""jaxhygiene: XLA-dispatch hygiene for the jitted hot paths.

The north star moves the Trainer fit, topology kernels, and scheduler
evaluator onto a resident XLA path, and the two regressions that class
of code grows are *silent recompiles* (a fresh ``jax.jit`` wrapper per
call compiles per call; an unstable static arg retraces per value) and
*silent host round-trips* (``float(tracer)``, ``.item()``, a whole-array
``np.asarray`` to read one element). Both are invisible in review and
expensive on a real device link — this pass makes them lint failures,
the same way ABBA lock cycles became one.

Two scopes, by construction:

- **jit-traced functions** — defs decorated ``@jax.jit`` /
  ``@functools.partial(jax.jit, ...)`` or wrapped via ``jax.jit(f)``
  anywhere in the module. Inside their (traced) bodies the pass flags
  host-sync constructs (``float``/``int``/``bool`` on non-constants,
  ``.item()``/``.tolist()``, numpy ops on traced values), branching on
  non-static parameters (a data-dependent ``if`` either crashes under
  trace or silently bakes one branch in), and Python side effects
  (``print``, logging, ``time.*``, host randomness — they run at trace
  time, not per step).
- **device-hot modules** — modules carrying a ``# dfanalyze: device-hot``
  marker (the per-dispatch analogue of ``# dfanalyze: hot``). Anywhere
  in them the pass flags jit-wrapper construction inside functions
  (``jax.jit(...)``, ``functools.partial(jax.jit, ...)`` or a bare
  ``@jax.jit`` on a nested def — one wrapper per enclosing call = one
  compile cache per call), ``block_until_ready`` outside allowlisted
  timing/confirmation sites, and the whole-array host pull
  ``np.asarray(x)[i]``. Construction inside a loop is flagged
  package-wide. The one audited escape hatch: a construction whose
  enclosing function stores into a ``*cache*``-named subscript
  (``_step_cache[key] = ...``) is a memoized factory and exempt.

Static-arg stability: a jitted function whose ``static_argnums``/
``static_argnames`` parameter defaults to — or is called with — a
list/dict/set literal (or a fresh ``np.array``) either crashes on
hashing or retraces per call; both ends are flagged.

The runtime half (``hack/dfanalyze/jitwitness.py``, armed via
``DF_JIT_WITNESS=1``) records what actually compiled and transferred;
``witness_crosscheck`` joins that dump back onto the static jit sites
here and fails on retrace storms, per-call wrapper churn, and implicit
transfers feeding jits from device-hot modules.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path

from .. import Finding, PassResult

ID = "jaxhygiene"

DEVICE_HOT_MARKER = "dfanalyze: device-hot"

# host-sync builtins: on a traced value these force device→host (or
# crash under trace); on a constant they're pointless but harmless
_SYNC_BUILTINS = ("float", "int", "bool")
_SYNC_ATTRS = ("item", "tolist")
_LOGGERISH = ("logger", "log", "logging")


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jax_jit(node: ast.AST) -> bool:
    return isinstance(node, (ast.Name, ast.Attribute)) and _dotted(node) in (
        "jax.jit",
        "jit",
        "pjit",
        "jax.pjit",
    )


def _jit_construction(node: ast.AST) -> ast.Call | None:
    """The Call that builds a jit wrapper: ``jax.jit(...)`` or
    ``functools.partial(jax.jit, ...)``. Returns the call carrying the
    jit kwargs (the partial itself for the partial form)."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jax_jit(node.func):
        return node
    if _dotted(node.func) in ("functools.partial", "partial") and node.args:
        if _is_jax_jit(node.args[0]):
            return node
    return None


def _static_params(call: ast.Call | None, fn: ast.FunctionDef | None) -> set[str]:
    """Parameter names pinned static by static_argnums/static_argnames
    on the jit construction ``call`` wrapping ``fn``."""
    out: set[str] = set()
    if call is None:
        return out
    argnames = [a.arg for a in (fn.args.posonlyargs + fn.args.args)] if fn else []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for v in _const_strs(kw.value):
                out.add(v)
        elif kw.arg == "static_argnums" and fn is not None:
            for i in _const_ints(kw.value):
                if 0 <= i < len(argnames):
                    out.add(argnames[i])
    return out


def _const_strs(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


def _const_ints(node: ast.AST) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        ]
    return []


def _nonhashable_literal(node: ast.AST) -> str | None:
    if isinstance(node, ast.List):
        return "list"
    if isinstance(node, ast.Dict):
        return "dict"
    if isinstance(node, ast.Set):
        return "set"
    if isinstance(node, ast.Call) and _dotted(node.func) in (
        "np.array",
        "np.asarray",
        "numpy.array",
        "numpy.asarray",
    ):
        return "ndarray"
    return None


class _Jitted:
    """One jit-wrapped function: the def, its static params, and the
    name call sites use (the decorated name, or the assigned alias for
    ``g = jax.jit(f, ...)``)."""

    def __init__(self, fn, static, call_name, construction):
        self.fn = fn
        self.static = static
        self.call_name = call_name
        self.construction = construction  # the jit Call (kwargs live here)


class _ModuleScan:
    def __init__(self, tree: ast.Module, rel: str, text: str):
        self.tree = tree
        self.rel = rel
        self.hot = DEVICE_HOT_MARKER in text
        self.findings: list[Finding] = []
        self._seen_keys: set[str] = set()
        # bare name -> FunctionDef, module-wide (nested defs included):
        # jax.jit(f) resolution is by name, heuristic like the lockmodel
        self.defs: dict[str, ast.FunctionDef] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, node)
        self.jitted: list[_Jitted] = []
        # every wrapped-function NAME with a jit site here, including
        # jax.jit(f) where f's def lives in another module (the traced
        # body can't be analyzed, but the runtime witness joins compile
        # counts by this name)
        self.jit_names: list[tuple[str, int]] = []
        self._collect_jitted()

    # -- collection --------------------------------------------------------
    def _collect_jitted(self) -> None:
        marked: set = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    call = _jit_construction(dec)
                    if call is not None or _is_jax_jit(dec):
                        self.jit_names.append((node.name, node.lineno))
                        if node not in marked:
                            marked.add(node)
                            self.jitted.append(
                                _Jitted(
                                    node,
                                    _static_params(call, node),
                                    node.name,
                                    call,
                                )
                            )
            call = _jit_construction(node) if isinstance(node, ast.Call) else None
            if call is not None and call is node and _is_jax_jit(call.func):
                # jax.jit(f, ...): resolve f by name when it's a def here
                if call.args and isinstance(call.args[0], ast.Name):
                    self.jit_names.append((call.args[0].id, call.lineno))
                    fn = self.defs.get(call.args[0].id)
                    if fn is not None and fn not in marked:
                        marked.add(fn)
                        self.jitted.append(
                            _Jitted(fn, _static_params(call, fn), fn.name, call)
                        )
                elif call.args and isinstance(call.args[0], ast.Attribute):
                    # jax.jit(mod.fn): the compile log names the bare fn
                    self.jit_names.append((call.args[0].attr, call.lineno))
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and "jit" in node.func.id.lower()
                and node.args
            ):
                # the memoized-helper idiom (_jit_once(score_parents)):
                # the helper's own jax.jit(fn) sees only a parameter, so
                # the NAME join happens at the helper's call sites
                a = node.args[0]
                if isinstance(a, ast.Name):
                    self.jit_names.append((a.id, node.lineno))
                elif isinstance(a, ast.Attribute):
                    self.jit_names.append((a.attr, node.lineno))

    # -- emission ----------------------------------------------------------
    def _add(self, key: str, line: int, message: str) -> None:
        if key in self._seen_keys:
            return  # one finding (the first site) per stable key
        self._seen_keys.add(key)
        self.findings.append(Finding(ID, key, self.rel, line, message))

    # -- traced-body analysis ----------------------------------------------
    def scan_traced_bodies(self) -> None:
        for j in self.jitted:
            static = j.static
            params = {
                a.arg for a in j.fn.args.posonlyargs + j.fn.args.args + j.fn.args.kwonlyargs
            }
            traced = params - static
            qual = j.fn.name
            for node in ast.walk(j.fn):
                self._scan_traced_node(node, qual, traced)
            # unstable static arg, declaration side: a static param whose
            # default is non-hashable can never produce a cache hit
            defaults = j.fn.args.defaults
            argnames = [a.arg for a in j.fn.args.posonlyargs + j.fn.args.args]
            for name, d in zip(argnames[len(argnames) - len(defaults):], defaults):
                lit = _nonhashable_literal(d)
                if name in static and lit is not None:
                    self._add(
                        f"unstable-static:{self.rel}:{qual}:{name}",
                        d.lineno,
                        f"static arg {name!r} of jitted {qual}() defaults to a"
                        f" {lit} — non-hashable statics crash the jit cache or"
                        " retrace every call",
                    )

    def _scan_traced_node(self, node: ast.AST, qual: str, traced: set[str]) -> None:
        if isinstance(node, ast.Call):
            chain = _dotted(node.func)
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in _SYNC_BUILTINS
                and node.args
                and not isinstance(node.args[0], ast.Constant)
            ):
                self._add(
                    f"host-sync:{self.rel}:{qual}:{node.func.id}",
                    node.lineno,
                    f"{node.func.id}() on a traced value inside jitted {qual}()"
                    " forces a device→host sync (or a trace-time crash) —"
                    " keep the value on device or hoist out of the jit",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_ATTRS
                and not node.args
            ):
                self._add(
                    f"host-sync:{self.rel}:{qual}:{node.func.attr}",
                    node.lineno,
                    f".{node.func.attr}() inside jitted {qual}() is a"
                    " device→host sync under trace",
                )
            elif chain is not None and chain.split(".")[0] in ("np", "numpy"):
                root2 = ".".join(chain.split(".")[:2])
                if root2 in ("np.random", "numpy.random"):
                    self._add(
                        f"side-effect:{self.rel}:{qual}:{chain}",
                        node.lineno,
                        f"host randomness {chain}() inside jitted {qual}() runs"
                        " ONCE at trace time, then is baked constant — use"
                        " jax.random with an explicit key",
                    )
                elif not _all_const_args(node):
                    self._add(
                        f"host-sync:{self.rel}:{qual}:{chain}",
                        node.lineno,
                        f"numpy op {chain}() on a traced value inside jitted"
                        f" {qual}() pulls the array to host mid-trace — use"
                        " the jnp twin",
                    )
            elif isinstance(node.func, ast.Name) and node.func.id == "print":
                self._add(
                    f"side-effect:{self.rel}:{qual}:print",
                    node.lineno,
                    f"print() inside jitted {qual}() runs at trace time only"
                    " — use jax.debug.print for per-step output",
                )
            elif chain is not None and (
                chain.split(".")[0] in _LOGGERISH or chain.startswith("time.")
                or chain.split(".")[0] == "random"
            ):
                self._add(
                    f"side-effect:{self.rel}:{qual}:{chain}",
                    node.lineno,
                    f"{chain}() inside jitted {qual}() is a Python side effect"
                    " under trace — it fires once at compile, never per step",
                )
        elif isinstance(node, (ast.If, ast.While)):
            names = {
                n.id for n in ast.walk(node.test) if isinstance(n, ast.Name)
            }
            hit = sorted(names & traced)
            if hit:
                self._add(
                    f"traced-branch:{self.rel}:{qual}:{hit[0]}",
                    node.lineno,
                    f"branch on traced value {hit[0]!r} inside jitted {qual}()"
                    " — data-dependent Python control flow either crashes"
                    " under trace or bakes one branch in; use lax.cond/where,"
                    " or pin the arg static",
                )

    # -- call-site static-arg stability -------------------------------------
    def scan_static_callsites(self) -> None:
        by_name = {j.call_name: j for j in self.jitted if j.static}
        # g = jax.jit(f, static_...): calls go through g, not f
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                call = _jit_construction(node.value)
                if call is not None and call.args and isinstance(call.args[0], ast.Name):
                    fn = self.defs.get(call.args[0].id)
                    if fn is not None:
                        statics = _static_params(call, fn)
                        if statics:
                            by_name[node.targets[0].id] = _Jitted(
                                fn, statics, node.targets[0].id, call
                            )
        if not by_name:
            return
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
                continue
            j = by_name.get(node.func.id)
            if j is None:
                continue
            argnames = [a.arg for a in j.fn.args.posonlyargs + j.fn.args.args]
            for i, a in enumerate(node.args):
                lit = _nonhashable_literal(a)
                if lit is not None and i < len(argnames) and argnames[i] in j.static:
                    self._add(
                        f"unstable-static:{self.rel}:{j.call_name}:{argnames[i]}",
                        a.lineno,
                        f"call passes a {lit} for static arg {argnames[i]!r} of"
                        f" jitted {j.call_name}() — non-hashable statics crash"
                        " the jit cache or retrace every call",
                    )
            for kw in node.keywords:
                lit = _nonhashable_literal(kw.value)
                if lit is not None and kw.arg in j.static:
                    self._add(
                        f"unstable-static:{self.rel}:{j.call_name}:{kw.arg}",
                        kw.value.lineno,
                        f"call passes a {lit} for static arg {kw.arg!r} of"
                        f" jitted {j.call_name}() — non-hashable statics crash"
                        " the jit cache or retrace every call",
                    )

    # -- construction sites & device-hot module rules -----------------------
    def scan_constructions(self) -> None:
        self._walk_ctx(self.tree, qual="", in_fn=False, loop=0, memo=False)

    def _fn_is_memoized(self, fn: ast.AST) -> bool:
        """A function storing into a ``*cache*``-named subscript is a
        memoized factory — its jit constructions run once per config,
        not once per call (the ``_step_cache[key] = ...`` idiom)."""
        for node in ast.walk(fn):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            for t in targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and "cache" in t.value.id.lower()
                ):
                    return True
        return False

    def _walk_ctx(self, node, qual: str, in_fn: bool, loop: int, memo: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{qual}.{child.name}" if qual else child.name
                child_memo = memo or self._fn_is_memoized(child)
                if in_fn and not child_memo:
                    # a jit decorator on a def nested inside a function
                    # builds a fresh wrapper per enclosing call
                    for dec in child.decorator_list:
                        if _is_jax_jit(dec) or _jit_construction(dec) is not None:
                            self._flag_construction(qual or child.name, dec.lineno, loop)
                # walk the BODY only: decorators were just handled, and
                # walking them again through the generic Call branch would
                # double-flag every decorated nested def
                body = ast.Module(body=list(child.body), type_ignores=[])
                self._walk_ctx(body, q, True, 0, child_memo)
                continue
            if isinstance(child, ast.ClassDef):
                q = f"{qual}.{child.name}" if qual else child.name
                self._walk_ctx(child, q, in_fn, loop, memo)
                continue
            if isinstance(child, ast.Call):
                if _jit_construction(child) is not None and in_fn:
                    if not memo or loop > 0:
                        self._flag_construction(qual, child.lineno, loop)
                self._scan_hot_call(child, qual)
            if isinstance(child, ast.Subscript) and self.hot:
                v = child.value
                if isinstance(v, ast.Call) and _dotted(v.func) in (
                    "np.asarray",
                    "np.array",
                    "numpy.asarray",
                    "numpy.array",
                ):
                    self._add(
                        f"host-pull:{self.rel}:{qual or '<module>'}:{_dotted(v.func)}",
                        child.lineno,
                        f"{_dotted(v.func)}(...)[...] in {qual or self.rel} pulls"
                        " the WHOLE array device→host to read a slice — keep a"
                        " host copy at the producer, or index on device",
                    )
            nxt = loop + (
                1 if isinstance(child, (ast.For, ast.While, ast.AsyncFor)) else 0
            )
            self._walk_ctx(child, qual, in_fn, nxt, memo)

    def _flag_construction(self, qual: str, line: int, loop: int) -> None:
        q = qual or "<module>"
        if loop > 0:
            self._add(
                f"jit-in-loop:{self.rel}:{q}",
                line,
                f"jax.jit wrapper constructed inside a loop in {q}() — a"
                " fresh wrapper per iteration compiles per iteration; hoist"
                " the construction out of the loop",
            )
        elif self.hot:
            self._add(
                f"jit-per-call:{self.rel}:{q}",
                line,
                f"jax.jit wrapper constructed inside {q}() in a device-hot"
                " module — a fresh wrapper per call compiles per call; hoist"
                " to module scope or store it in a *cache*-named dict the"
                " analyzer can see",
            )

    def _scan_hot_call(self, call: ast.Call, qual: str) -> None:
        if not self.hot:
            return
        chain = _dotted(call.func)
        if chain == "jax.block_until_ready" or (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "block_until_ready"
        ):
            desc = chain or "?.block_until_ready"
            q = qual or "<module>"
            self._add(
                f"block-until-ready:{self.rel}:{q}:{desc}",
                call.lineno,
                f"{desc}() in {q}() in a device-hot module blocks the host on"
                " the device pipeline — sanctioned timing/confirmation sites"
                " get allowlisted with why; anything else is a stall",
            )


def _all_const_args(call: ast.Call) -> bool:
    return all(isinstance(a, ast.Constant) for a in call.args) and all(
        isinstance(k.value, ast.Constant) for k in call.keywords
    )


def _scan_module(path: Path, rel: str) -> _ModuleScan | None:
    text = path.read_text()
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return None
    scan = _ModuleScan(tree, rel, text)
    scan.scan_traced_bodies()
    scan.scan_static_callsites()
    scan.scan_constructions()
    return scan


def run(package_dir: Path) -> PassResult:
    findings: list[Finding] = []
    root = package_dir.parent
    for path in sorted(package_dir.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        scan = _scan_module(path, path.relative_to(root).as_posix())
        if scan is not None:
            findings.extend(scan.findings)
    return PassResult(ID, findings)


# -- static facts the witness join needs -------------------------------------


def collect_jit_sites(package_dir: Path) -> dict[str, list[tuple[str, int]]]:
    """Wrapped-function name → [(relpath, line)] for every jit site the
    AST can see — the join key for the runtime witness's per-function
    compile counts (the compile log names the wrapped function)."""
    root = package_dir.parent
    out: dict[str, list[tuple[str, int]]] = {}
    for path in sorted(package_dir.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        text = path.read_text()
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue
        rel = path.relative_to(root).as_posix()
        scan = _ModuleScan(tree, rel, text)
        for name, line in scan.jit_names:
            out.setdefault(name, []).append((rel, line))
    return out


def device_hot_files(package_dir: Path) -> set[str]:
    root = package_dir.parent
    out = set()
    for path in sorted(package_dir.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        if DEVICE_HOT_MARKER in path.read_text():
            out.add(path.relative_to(root).as_posix())
    return out


# -- witness cross-check -----------------------------------------------------

WITNESS_ID = "jit-witness"

# distinct compiled signatures one function may accumulate across a
# witnessed run before it reads as a retrace storm. Shape-diverse-by-
# design functions (static capacity args that grow) get allowlisted
# with the reason, same as every other audited exception.
MAX_SIGNATURES = 8
# jit-wrapper constructions one site may perform: memoized factories
# build one wrapper per *config*, not per call, so a handful is normal —
# dozens means a per-call rebuild
MAX_WRAPPERS = 8


def witness_crosscheck(package_dir: Path, report_path: Path) -> PassResult:
    """Join a jit-witness dump (``DF_JIT_WITNESS=1`` run) onto the static
    jit sites: fail on retrace storms (one function, many compiled
    signatures), wrapper churn (one construction site, many wrappers),
    and implicit host→device transfers feeding jits from device-hot
    modules. Compile counts for functions with no static jit site in the
    package (jax-internal eager ops, test-defined jits) are ignored —
    the join is what scopes the witness to our code."""
    if not report_path.is_file():
        return PassResult(WITNESS_ID, skipped=f"no witness report at {report_path}")
    data = json.loads(report_path.read_text())
    sites = collect_jit_sites(package_dir)
    hot = device_hot_files(package_dir)
    findings: list[Finding] = []

    for name, info in sorted(data.get("compiles", {}).items()):
        where = sites.get(name)
        if not where:
            continue
        sigs = info.get("signatures", [])
        if len(sigs) > MAX_SIGNATURES:
            file, line = where[0]
            findings.append(
                Finding(
                    WITNESS_ID,
                    f"retrace:{name}",
                    file,
                    line,
                    f"jitted {name}() compiled {len(sigs)} distinct signatures"
                    f" ({info.get('count', len(sigs))} compiles) — a retrace"
                    f" storm past the {MAX_SIGNATURES}-signature warmup"
                    " allowance; stabilize shapes/static args or allowlist"
                    " the by-design shape diversity with why",
                )
            )

    for rec in data.get("wrapper_sites", []):
        n = rec.get("count", 0)
        target = rec.get("target", "?")
        if n <= MAX_WRAPPERS:
            continue
        file, _, line = rec.get("site", "").rpartition(":")
        findings.append(
            Finding(
                WITNESS_ID,
                f"jit-rewrap:{file}:{target}",
                file,
                int(line or 0),
                f"jax.jit({target}) constructed {n}× at one site — each fresh"
                " wrapper carries its own compile cache, so this recompiles"
                " per construction; memoize the wrapper",
            )
        )

    # ingest post-stream tail functions that legitimately convert on the
    # caller's thread — they run once AFTER the pipeline drained, where
    # a boundary feed cannot stall decode (named functions on purpose so
    # this exemption is exact; see trainer/ingest.py)
    _INGEST_TAIL_FNS = {"_ragged_tail", "_eval_holdout"}

    for t in data.get("transfers", []):
        # the ingest packing thread must never dispatch device work
        # itself (ISSUE 15): every per-superbatch H2D lives on the
        # dedicated transfer/step stage threads so the decode pipeline
        # never stalls behind the device link. Keyed on the RECORDED
        # THREAD (transfers carry it since this rule landed), not the
        # frame name: a regression that moves `put(arg)` back into the
        # packing loop still attributes to the `put` closure's frame,
        # but its thread is the caller's, not trainer.ingest-*.
        if (
            t.get("file", "") == "dragonfly2_tpu/trainer/ingest.py"
            and t.get("fn", "") not in _INGEST_TAIL_FNS
            and not str(t.get("thread", "")).startswith("trainer.ingest-")
        ):
            findings.append(
                Finding(
                    WITNESS_ID,
                    f"pack-transfer:{t.get('fn', '?')}:{t.get('target', '?')}",
                    t.get("file", ""),
                    int(t.get("line", 0)),
                    f"host→device transfer ({t.get('target', '?')}) witnessed"
                    f" outside the ingest stage threads"
                    f" (fn {t.get('fn', '?')}, thread {t.get('thread', '?')},"
                    f" {t.get('count', 1)}× recorded) — the device leg"
                    " belongs on the trainer.ingest-transfer/-step stages;"
                    " a put on the packing thread stalls decode behind the"
                    " device link",
                )
            )
            continue
        if t.get("explicit"):
            continue
        file = t.get("file", "")
        if file not in hot:
            continue
        findings.append(
            Finding(
                WITNESS_ID,
                f"transfer:{file}:{t.get('fn', '?')}",
                file,
                int(t.get("line", 0)),
                f"implicit host→device transfer feeding jitted"
                f" {t.get('target', '?')}() from {t.get('fn', '?')}() in a"
                f" device-hot module ({t.get('count', 1)}× witnessed) — convert"
                " explicitly at the boundary (jnp.asarray/device_put) so the"
                " transfer is visible and batchable",
            )
        )
    # one finding per stable key (a site witnessed by many tests is one fact)
    seen: set[str] = set()
    uniq = []
    for f in findings:
        if f.key not in seen:
            seen.add(f.key)
            uniq.append(f)
    return PassResult(WITNESS_ID, uniq)
