"""Pass registry — ``python -m hack.dfanalyze --list-passes``."""

from __future__ import annotations

from . import blocking, hygiene, jaxhygiene, lockorder, metrics, typecheck


class _Pass:
    def __init__(self, mod):
        self.id = mod.ID
        self.description = (mod.__doc__ or "").strip().splitlines()[0]
        self.run = mod.run


ALL_PASSES = [
    _Pass(lockorder),
    _Pass(blocking),
    _Pass(hygiene),
    _Pass(jaxhygiene),
    _Pass(metrics),
    _Pass(typecheck),
]
