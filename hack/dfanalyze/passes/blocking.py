"""blocking: calls that can wait on the outside world while a lock is held.

A critical section that sleeps, talks gRPC, waits on a queue, does
file/socket I/O, or dispatches jax work holds every other thread that
needs the lock for the full duration of that wait — the exact shape that
turned one wedged scheduler into pile-on stalls before the resilience
plane, and the reason ``topology/engine.py`` moved its kernel work
outside the query lock. Categories (see ``lockmodel.classify``):

``sleep`` ``rpc`` ``queue`` ``wait`` ``thread-join`` ``lock-acquire``
``socket`` ``file-io`` ``jax``

Calls into same-class/module helpers are followed transitively, so a
lock held around ``self._refresh()`` still surfaces the jax dispatch
inside it. Audited exceptions (e.g. a storage object whose lock exists
precisely to serialize its file I/O) go in the allowlist with a comment.
"""

from __future__ import annotations

from pathlib import Path

from .. import Finding, PassResult
from ..lockmodel import build_package_model

ID = "blocking"


def run(package_dir: Path) -> PassResult:
    findings: list[Finding] = []
    seen: set[str] = set()
    for m in build_package_model(package_dir):
        for b in m.blocking:
            lock_short = b.lock.rsplit("::", 1)[-1]
            # one finding (and one allowlist entry) per call CHAIN, not
            # per individual call inside it: auditing "flush dispatches
            # kernels under _flush_lock" covers every kernel in there
            tail = f"via.{b.via}" if b.via else b.desc
            key = f"{m.path}:{b.fn}:{lock_short}:{b.category}:{tail}"
            if key in seen:
                continue
            seen.add(key)
            via = f" (via {b.via}())" if b.via else ""
            findings.append(
                Finding(
                    ID,
                    key,
                    b.file,
                    b.line,
                    f"{b.category} call {b.desc}() while holding"
                    f" {lock_short} in {b.fn}{via}",
                )
            )
    return PassResult(ID, findings)
