"""metrics: the registration census — Prometheus series, flight-recorder
event types, and fault-injection points (the absorbed
``hack/check_metrics.py``; that script is now a thin shim over this).

Walks the package source for ``.counter(...)``/``.gauge(...)``/
``.histogram(...)`` calls with a literal name and fails on duplicates,
kind mismatches, names violating the ``dragonfly_<service>_...``
convention (counters must end ``_total``), and OpenMetrics family
collisions (``x`` next to ``x_total``). Flight events must be
``<service>.<what>``; fault points must be ``<layer>.<what>`` and be
referenced by at least one test (an unexercised injection point is dead
chaos surface). ``check()`` keeps the original string-list contract the
tier-1 test asserts on; ``run()`` adapts it to dfanalyze findings.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from .. import DEFAULT_PACKAGE, Finding, PassResult

ID = "metrics"

PACKAGE = DEFAULT_PACKAGE

# the service segment a series name must start with — one per process
# role plus the shared rpc glue, flight-recorder, fault-plane and
# resilience-layer series; "build" is the cross-service identity gauge
# (dragonfly_build_info{service,version} — every exporter carries it)
ALLOWED_SERVICES = (
    "scheduler", "trainer", "daemon", "manager", "topology", "rpc", "flight",
    "faults", "resilience", "fleet", "build", "prof", "preheat", "flow",
    "swarm",
)

# flight-recorder event names are <service>.<what>; the service segment
# is the ring category — the process roles plus the cross-layer "rpc"
# (resilience decisions: retries, breaker trips, sheds), "faults"
# (injections), and "prof" (sampler lifecycle) rings, which must not
# evict any role's own history
EVENT_SERVICES = (
    "scheduler", "trainer", "daemon", "manager", "topology", "rpc", "faults",
    "fleet", "prof", "preheat",
)

# the prof.* event namespace is reserved for the continuous profiler —
# a stray scheduler-side prof-ish event would fork the vocabulary
# dfdoctor/dfprof key on, so only this module may declare them
PROF_EVENT_MODULE = "dragonfly2_tpu/utils/profiling.py"

# the scheduler.serving_* event segment belongs to the batched scoring
# plane (ISSUE 13): the service itself plus its evaluator client — a
# serving-ish event declared elsewhere would fork the vocabulary the
# serving docs/dfdoctor flows key on (docs/serving.md)
SERVING_EVENT_MODULES = (
    "dragonfly2_tpu/scheduler/serving.py",
    "dragonfly2_tpu/scheduler/evaluator.py",
)

# the scheduler.wave_* event segment belongs to the wave-scheduling
# plane (docs/serving.md "wave scheduling"): the pack/unpack module plus
# its evaluator and scoring-service clients — a wave-ish event declared
# elsewhere would fork the vocabulary the wave census keys on
WAVE_EVENT_MODULES = (
    "dragonfly2_tpu/scheduler/wave.py",
    "dragonfly2_tpu/scheduler/evaluator.py",
    "dragonfly2_tpu/scheduler/serving.py",
)

# the daemon.proxy_* and daemon.object_* event segments belong to the
# registry-proxy and object-storage traffic planes (docs/observability.md
# "flow ledger"): a proxy-ish or object-ish event declared elsewhere
# would fork the vocabulary the traffic-plane census and dfdoctor key on
PROXY_EVENT_MODULE = "dragonfly2_tpu/client/proxy.py"
OBJECT_EVENT_MODULE = "dragonfly2_tpu/client/objectstorage.py"

# the preheat.* event namespace (its own flight ring) belongs to the
# predictive preheat plane: demand folding, forecasting, planning — a
# preheat-ish event declared elsewhere would fork the vocabulary the
# preheat census and docs/preheat.md key on
PREHEAT_EVENT_MODULES = (
    "dragonfly2_tpu/preheat/demand.py",
    "dragonfly2_tpu/preheat/forecast.py",
    "dragonfly2_tpu/preheat/planner.py",
)

# the scheduler.swarm_* event segment belongs to the swarm observatory
# (docs/observability.md "swarm observatory"): straggler/stuck flags are
# detected against the observatory's own snapshot state — a swarm-ish
# event declared elsewhere would fork the vocabulary dfdoctor and the
# swarm census key on
SWARM_EVENT_MODULE = "dragonfly2_tpu/scheduler/swarm.py"

# ...EXCEPT the scheduler.swarm_adopt_* sub-segment, which belongs to
# the replication plane (docs/fleet.md "failover protocol"): adoption
# verdicts (ok/refused/migrate) are decided against the replicated
# snapshot's epoch and conservation gates, which only the replicator
# sees — an adopt-ish event declared elsewhere (including swarm.py
# itself) would fork the failover timeline dfdoctor keys on
SWARM_ADOPT_EVENT_MODULE = "dragonfly2_tpu/scheduler/swarm_replication.py"

# the swarm_replication_* metric family is the replication plane's own
# census surface (journal flushes, adoption outcomes, backlog): it is
# declared in the replicator module only, so docs/metrics.md and the
# soak gates can key on one site
SWARM_REPLICATION_METRIC_MODULE = "dragonfly2_tpu/scheduler/swarm_replication.py"

# the scheduler.fleet_* event segment belongs to the membership plane:
# join/leave/reconcile transitions come from the hash-ring bookkeeping
# alone, so the transition counter and the flight timeline can't drift
FLEET_EVENT_MODULE = "dragonfly2_tpu/scheduler/fleet.py"

# dfprof phase-ledger names (profiling.phase_type("<service>.<what>"))
# share the event services' vocabulary: phases belong to a process role
PHASE_SERVICES = EVENT_SERVICES

# fault-point names are <layer>.<what>; mirrors utils/faults.POINT_LAYERS
FAULT_LAYERS = (
    "rpc", "daemon", "scheduler", "trainer", "manager", "kv", "fleet", "preheat",
)

# telemetry aggregate fields are <scope>.<what>; mirrors
# utils/telemetry.TELEMETRY_SCOPES (the manager-derived fields dfstat
# renders — the census keeps the plane's vocabulary from drifting)
TELEMETRY_SCOPES = ("cluster", "swarm", "shard", "trainer", "daemon", "slo")

TESTS_DIR = PACKAGE.parent / "tests"

KINDS = ("counter", "gauge", "histogram")


def _literal_attr_calls(path: Path, attrs) -> list[tuple[str, str, int]]:
    """(literal-first-arg, attr, lineno) for every attribute call in
    ``path`` whose attr is in ``attrs`` and whose first arg is a string
    literal. Only attribute calls are considered (``_r.counter(...)``),
    which is how every registration in the package is written; local
    ``Registry("...")`` instances in tests/bench are out of scope, and a
    forwarder passing a variable (``_plane.point(name)``) never matches."""
    try:
        tree = ast.parse(path.read_text())
    except SyntaxError:
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in attrs):
            continue
        if not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            out.append((first.value, fn.attr, node.lineno))
    return out


def _tests_corpus(tests_dir: Path = TESTS_DIR) -> str:
    """Concatenated test source — the referenced-by-test rule greps
    fault-point names against this."""
    if not tests_dir.is_dir():
        return ""
    return "\n".join(p.read_text() for p in sorted(tests_dir.glob("*.py")))


def check(package_dir: Path = PACKAGE) -> list[str]:
    """Returns a list of human-readable failures (empty = clean)."""
    failures: list[str] = []
    seen: dict[str, tuple[str, str]] = {}  # name -> (kind, site)
    seen_events: dict[str, str] = {}  # event name -> site
    seen_points: dict[str, str] = {}  # fault point -> site
    seen_tfields: dict[str, str] = {}  # telemetry field -> site
    seen_phases: dict[str, str] = {}  # dfprof phase -> site
    for path in sorted(package_dir.rglob("*.py")):
        rel = path.relative_to(package_dir.parent)
        for name, _attr, lineno in _literal_attr_calls(path, ("phase_type",)):
            site = f"{rel}:{lineno}"
            if not all(c.islower() or c.isdigit() or c in "._" for c in name):
                failures.append(
                    f"{site}: dfprof phase {name!r} has characters outside"
                    " [a-z0-9_.]"
                )
            service = name.split(".", 1)[0]
            if "." not in name or service not in PHASE_SERVICES:
                failures.append(
                    f"{site}: dfprof phase {name!r} must be <service>.<what>"
                    f" with service in {PHASE_SERVICES}"
                )
            prev_site = seen_phases.get(name)
            if prev_site is not None:
                failures.append(
                    f"{site}: duplicate dfprof phase registration of {name!r}"
                    f" (first at {prev_site})"
                )
            else:
                seen_phases[name] = site
        for name, _attr, lineno in _literal_attr_calls(path, ("tfield",)):
            site = f"{rel}:{lineno}"
            if not all(c.islower() or c.isdigit() or c in "._" for c in name):
                failures.append(
                    f"{site}: telemetry field {name!r} has characters outside"
                    " [a-z0-9_.]"
                )
            scope = name.split(".", 1)[0]
            if "." not in name or scope not in TELEMETRY_SCOPES:
                failures.append(
                    f"{site}: telemetry field {name!r} must be <scope>.<what>"
                    f" with scope in {TELEMETRY_SCOPES}"
                )
            prev_site = seen_tfields.get(name)
            if prev_site is not None:
                failures.append(
                    f"{site}: duplicate telemetry-field registration of"
                    f" {name!r} (first at {prev_site})"
                )
            else:
                seen_tfields[name] = site
        for name, _attr, lineno in _literal_attr_calls(path, ("point",)):
            site = f"{rel}:{lineno}"
            if not all(c.islower() or c.isdigit() or c in "._" for c in name):
                failures.append(
                    f"{site}: fault point {name!r} has characters outside"
                    " [a-z0-9_.]"
                )
            layer = name.split(".", 1)[0]
            if "." not in name or layer not in FAULT_LAYERS:
                failures.append(
                    f"{site}: fault point {name!r} must be <layer>.<what>"
                    f" with layer in {FAULT_LAYERS}"
                )
            prev_site = seen_points.get(name)
            if prev_site is not None:
                failures.append(
                    f"{site}: duplicate fault-point registration of {name!r}"
                    f" (first at {prev_site})"
                )
            else:
                seen_points[name] = site
        for name, _attr, lineno in _literal_attr_calls(path, ("event_type",)):
            site = f"{rel}:{lineno}"
            if not all(c.islower() or c.isdigit() or c in "._" for c in name):
                failures.append(
                    f"{site}: event {name!r} has characters outside [a-z0-9_.]"
                )
            service = name.split(".", 1)[0]
            if "." not in name or service not in EVENT_SERVICES:
                failures.append(
                    f"{site}: event {name!r} must be <service>.<what> with"
                    f" service in {EVENT_SERVICES}"
                )
            # SLO breach events belong to the manager's burn-rate engine
            # alone: a stray scheduler.slo_* would fork the vocabulary
            # dfdoctor/dfstat key on (manager.slo_burn / manager.slo_clear).
            # Segment test, not substring: "daemon.slow_parent" is fine.
            what = name.split(".", 1)[1] if "." in name else ""
            if (
                (what == "slo" or what.startswith("slo_"))
                and not name.startswith("manager.slo_")
            ):
                failures.append(
                    f"{site}: event {name!r} uses the reserved slo_ segment;"
                    " SLO events must be manager.slo_<what>"
                )
            # the prof.* namespace belongs to the continuous profiler
            if service == "prof" and str(rel) != PROF_EVENT_MODULE:
                failures.append(
                    f"{site}: event {name!r} uses the reserved prof."
                    f" namespace; prof events are declared in"
                    f" {PROF_EVENT_MODULE} only"
                )
            # scheduler.serving_* belongs to the batched scoring plane
            if (
                service == "scheduler"
                and (what == "serving" or what.startswith("serving_"))
                and str(rel) not in SERVING_EVENT_MODULES
            ):
                failures.append(
                    f"{site}: event {name!r} uses the reserved"
                    " scheduler.serving_ segment; serving events are"
                    f" declared in {SERVING_EVENT_MODULES} only"
                )
            # scheduler.wave_* belongs to the wave-scheduling plane
            if (
                service == "scheduler"
                and (what == "wave" or what.startswith("wave_"))
                and str(rel) not in WAVE_EVENT_MODULES
            ):
                failures.append(
                    f"{site}: event {name!r} uses the reserved"
                    " scheduler.wave_ segment; wave events are"
                    f" declared in {WAVE_EVENT_MODULES} only"
                )
            # daemon.proxy_* belongs to the registry proxy plane
            if (
                service == "daemon"
                and (what == "proxy" or what.startswith("proxy_"))
                and str(rel) != PROXY_EVENT_MODULE
            ):
                failures.append(
                    f"{site}: event {name!r} uses the reserved"
                    " daemon.proxy_ segment; proxy events are declared in"
                    f" {PROXY_EVENT_MODULE} only"
                )
            # daemon.object_* belongs to the object-storage gateway plane
            if (
                service == "daemon"
                and (what == "object" or what.startswith("object_"))
                and str(rel) != OBJECT_EVENT_MODULE
            ):
                failures.append(
                    f"{site}: event {name!r} uses the reserved"
                    " daemon.object_ segment; object-storage events are"
                    f" declared in {OBJECT_EVENT_MODULE} only"
                )
            # scheduler.swarm_adopt_* belongs to the replication plane
            # (checked before the broader swarm_ rule it carves out of)
            if (
                service == "scheduler"
                and (what == "swarm_adopt" or what.startswith("swarm_adopt_"))
            ):
                if str(rel) != SWARM_ADOPT_EVENT_MODULE:
                    failures.append(
                        f"{site}: event {name!r} uses the reserved"
                        " scheduler.swarm_adopt_ segment; adoption events"
                        f" are declared in {SWARM_ADOPT_EVENT_MODULE} only"
                    )
            # scheduler.swarm_* belongs to the swarm observatory
            elif (
                service == "scheduler"
                and (what == "swarm" or what.startswith("swarm_"))
                and str(rel) != SWARM_EVENT_MODULE
            ):
                failures.append(
                    f"{site}: event {name!r} uses the reserved"
                    " scheduler.swarm_ segment; swarm-observatory events"
                    f" are declared in {SWARM_EVENT_MODULE} only"
                )
            # scheduler.fleet_* belongs to the membership plane
            if (
                service == "scheduler"
                and (what == "fleet" or what.startswith("fleet_"))
                and str(rel) != FLEET_EVENT_MODULE
            ):
                failures.append(
                    f"{site}: event {name!r} uses the reserved"
                    " scheduler.fleet_ segment; fleet-membership events"
                    f" are declared in {FLEET_EVENT_MODULE} only"
                )
            # the preheat.* ring belongs to the predictive preheat plane
            if service == "preheat" and str(rel) not in PREHEAT_EVENT_MODULES:
                failures.append(
                    f"{site}: event {name!r} uses the reserved preheat."
                    f" namespace; preheat events are declared in"
                    f" {PREHEAT_EVENT_MODULES} only"
                )
            prev_site = seen_events.get(name)
            if prev_site is not None:
                failures.append(
                    f"{site}: duplicate event registration of {name!r}"
                    f" (first at {prev_site})"
                )
            else:
                seen_events[name] = site
        for name, kind, lineno in _literal_attr_calls(path, KINDS):
            site = f"{rel}:{lineno}"
            if not name.replace("_", "").replace("-", "").isascii() or not all(
                c.islower() or c.isdigit() or c == "_" for c in name
            ):
                failures.append(
                    f"{site}: {name!r} has characters outside [a-z0-9_]"
                )
            service = name.split("_", 1)[0]
            if service not in ALLOWED_SERVICES:
                failures.append(
                    f"{site}: {name!r} does not start with a known service"
                    f" segment {ALLOWED_SERVICES} (full name is"
                    f" dragonfly_{name})"
                )
            if kind == "counter" and not name.endswith("_total"):
                failures.append(
                    f"{site}: counter {name!r} must end in _total"
                    " (OpenMetrics counter naming)"
                )
            # swarm_replication_* belongs to the replication plane
            if (
                name == "swarm_replication"
                or name.startswith("swarm_replication_")
            ) and str(rel) != SWARM_REPLICATION_METRIC_MODULE:
                failures.append(
                    f"{site}: metric {name!r} uses the reserved"
                    " swarm_replication_ prefix; replication-plane metrics"
                    f" are declared in {SWARM_REPLICATION_METRIC_MODULE}"
                    " only"
                )
            prev = seen.get(name)
            if prev is not None:
                prev_kind, prev_site = prev
                if prev_kind != kind:
                    failures.append(
                        f"{site}: {name!r} registered as {kind} but"
                        f" {prev_site} registered it as {prev_kind}"
                    )
                else:
                    failures.append(
                        f"{site}: duplicate registration of {name!r}"
                        f" (first at {prev_site})"
                    )
            else:
                seen[name] = (kind, site)
    # OpenMetrics family collisions: a counter 'x_total' exposes under
    # family 'x' — a sibling metric literally named 'x' would produce a
    # duplicate family the strict parser rejects on every scrape
    for name, (kind, site) in seen.items():
        if kind == "counter" and name.endswith("_total"):
            family = name[: -len("_total")]
            if family in seen:
                failures.append(
                    f"{site}: counter {name!r} exposes as OpenMetrics"
                    f" family {family!r}, colliding with the metric of"
                    f" that name at {seen[family][1]}"
                )
    # referenced-by-test: a fault point the test matrix never arms is
    # dead chaos surface — the spec grammar accepts it, nothing proves
    # the layer survives it
    if seen_points:
        corpus = _tests_corpus(package_dir.parent / "tests")
        for name, site in sorted(seen_points.items()):
            if name not in corpus:
                failures.append(
                    f"{site}: fault point {name!r} is not referenced by any"
                    " test under tests/ (add it to the fault matrix in"
                    " tests/test_fault_injection.py)"
                )
    return failures


_SITE_RE = re.compile(r"^(?P<file>[^:]+):(?P<line>\d+): (?P<msg>.*)$", re.S)


def run(package_dir: Path) -> PassResult:
    findings = []
    for failure in check(package_dir):
        m = _SITE_RE.match(failure)
        file, line, msg = (
            (m.group("file"), int(m.group("line")), m.group("msg"))
            if m
            else ("", 0, failure)
        )
        key = re.sub(r"[^A-Za-z0-9_.<>'-]+", "-", msg).strip("-")[:100]
        findings.append(Finding(ID, key, file, line, msg))
    return PassResult(ID, findings)
