"""typecheck: mypy as a dfanalyze pass, with a checked-in baseline.

Strict on ``dragonfly2_tpu/utils/`` and ``dragonfly2_tpu/rpc/`` (the
layers every process links — ``py.typed`` already ships, so their
annotations are API), permissive elsewhere; configuration lives in
``hack/dfanalyze/mypy.ini``. The baseline
(``hack/dfanalyze/baselines/mypy_baseline.txt``) pins the legacy
violation set: a run only FAILS on lines not in the baseline, so new
violations are stopped while the legacy debt is tracked and burned down
deliberately (regenerate with
``python -m hack.dfanalyze --update-mypy-baseline`` after paying some
off — shrinking is the only allowed direction of travel).

The container image doesn't bake mypy in (and the no-new-deps rule says
don't install it): when ``mypy`` isn't importable the pass reports
SKIPPED and passes — the baseline machinery is exercised by unit tests
against a stubbed runner either way, so the wiring can't rot while the
tool is absent.
"""

from __future__ import annotations

import re
import subprocess
import sys
from pathlib import Path

from .. import Finding, PassResult

ID = "typecheck"

HERE = Path(__file__).resolve().parent.parent
CONFIG = HERE / "mypy.ini"
BASELINE = HERE / "baselines" / "mypy_baseline.txt"

# mypy output lines: path:line: error: message  [code]
_LINE_RE = re.compile(
    r"^(?P<file>[^:]+\.py):(?P<line>\d+):(?:\d+:)? (?P<sev>error|note):"
    r" (?P<msg>.*?)(?:  \[(?P<code>[a-z0-9-]+)\])?$"
)


def mypy_available() -> bool:
    try:
        import mypy  # noqa: F401

        return True
    except ImportError:
        return False


def run_mypy(package_dir: Path) -> list[str]:
    """Raw mypy error lines for the package (notes dropped)."""
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "mypy",
            "--config-file",
            str(CONFIG),
            str(package_dir),
        ],
        capture_output=True,
        text=True,
        cwd=str(package_dir.parent),
    )
    out = []
    for line in proc.stdout.splitlines():
        m = _LINE_RE.match(line.strip())
        if m and m.group("sev") == "error":
            out.append(line.strip())
    return out


def normalize(line: str) -> str:
    """Baseline key: file + error code + message, line number dropped —
    legacy violations must not churn the baseline when unrelated edits
    shift them down a few lines."""
    m = _LINE_RE.match(line)
    if not m:
        return line
    code = m.group("code") or "misc"
    return f"{m.group('file')}|{code}|{m.group('msg')}"


def load_baseline(path: Path = BASELINE) -> set[str]:
    if not path.is_file():
        return set()
    return {
        ln
        for ln in path.read_text().splitlines()
        if ln.strip() and not ln.startswith("#")
    }


def write_baseline(lines: list[str], path: Path = BASELINE) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    header = (
        "# mypy baseline — legacy violations tracked, new ones fail.\n"
        "# One normalized line per violation: file|code|message.\n"
        "# Regenerate (after burning some down):\n"
        "#   python -m hack.dfanalyze --update-mypy-baseline\n"
    )
    path.write_text(header + "\n".join(sorted(set(lines))) + ("\n" if lines else ""))


def findings_against_baseline(
    raw_lines: list[str], baseline: set[str]
) -> list[Finding]:
    findings = []
    for line in raw_lines:
        norm = normalize(line)
        if norm in baseline:
            continue
        m = _LINE_RE.match(line)
        file, lineno = (m.group("file"), int(m.group("line"))) if m else ("", 0)
        key = "mypy:" + re.sub(r"[^A-Za-z0-9_.|-]+", "-", norm)[:120]
        findings.append(
            Finding(
                ID,
                key,
                file,
                lineno,
                f"new mypy violation (not in baseline): {line}",
            )
        )
    return findings


def run(package_dir: Path) -> PassResult:
    if not mypy_available():
        return PassResult(
            ID,
            skipped="mypy not installed in this image — baseline unchanged"
            " (pip install mypy locally to run this pass)",
        )
    raw = run_mypy(package_dir)
    return PassResult(ID, findings_against_baseline(raw, load_baseline()))


def update_baseline(package_dir: Path) -> int:
    """--update-mypy-baseline: rewrite the baseline from a fresh run.
    Returns the number of baselined violations."""
    if not mypy_available():
        raise SystemExit("dfanalyze[typecheck]: mypy not installed")
    raw = run_mypy(package_dir)
    write_baseline([normalize(l) for l in raw])
    return len(raw)
