"""Runtime lock-witness: record the acquisition orders that actually
happen, so dynamic orders the AST can't see (callbacks, plugin code,
cross-object nesting) still get caught.

``install()`` replaces ``threading.Lock``/``threading.RLock`` with
factories that wrap ONLY locks created from source files under the
package root (creation site sniffed from the caller's frame, once, at
creation) — stdlib and third-party locks come back raw, so a witnessed
tier-1 run instruments exactly the package's own locking and nothing
else. Each wrapped acquire records, for every lock already held by the
acquiring thread, the ordered pair ``held-site -> acquired-site``; the
creation site (``file:line`` of the ``threading.Lock()`` call) is the
join key the static pass uses to map observed pairs onto its lock nodes
(``dfanalyze --witness-report``).

Same-site pairs are kept with a ``same_site`` marker when the two locks
are *distinct instances* from one creation site (two conductors' locks
nested) — an order a per-class static graph cannot express and a real
deadlock shape; plain re-entry of one RLock instance is dropped.

Opt-in: ``DF_LOCK_WITNESS=1`` makes ``tests/conftest.py`` call
``install()`` before the package imports and dump the report to
``DF_LOCK_WITNESS_OUT`` (default ``dfanalyze-witness.json``) at session
end. The emit path is a few dict operations per acquire; the report is
bounded by the number of distinct (site, site) pairs.
"""

from __future__ import annotations

import _thread
import json
import os
import sys
import threading

_raw_lock = _thread.allocate_lock
_raw_rlock = threading.RLock  # the C implementation behind threading.RLock

_state_lock = _thread.allocate_lock()
_tls = threading.local()

_installed = False
_package_roots: tuple[str, ...] = ()
_edges: dict[tuple[str, str], bool] = {}  # (held, acquired) -> same_site seen
_locks: dict[str, dict] = {}  # site -> {"kind": ..., "instances": n}


def _held_stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _record(site: str, kind: str) -> None:
    with _state_lock:  # creation is rare; the count must not race
        info = _locks.setdefault(site, {"kind": kind, "instances": 0})
        info["instances"] += 1


def _note_acquired(wrapper) -> None:
    stack = _held_stack()
    if any(h._freed for h in stack):
        # a lock this thread acquired was released by ANOTHER thread
        # (legal for threading.Lock — the hand-off pattern): purge it, or
        # every later acquire here records phantom "still held" pairs
        stack[:] = [h for h in stack if not h._freed]
    for held in stack:
        key = (held._site, wrapper._site)
        same = held._site == wrapper._site and held is not wrapper
        cur = _edges.get(key)
        if cur is None or (same and not cur):
            with _state_lock:
                _edges[key] = _edges.get(key, False) or same
    stack.append(wrapper)


def _note_released(wrapper) -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] is wrapper:
            del stack[i]
            return


class _WitnessLock:
    """threading.Lock twin; supports Condition's duck-typing surface."""

    __slots__ = ("_raw", "_site", "_freed")

    def __init__(self, site: str):
        self._raw = _raw_lock()
        self._site = site
        self._freed = False

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            self._freed = False
            _note_acquired(self)
        return ok

    def release(self) -> None:
        # releases may come from a DIFFERENT thread than the acquirer
        # (legal for Lock): flag first so the acquirer's held-stack entry
        # is purged at its next acquire even when the pop below misses
        self._freed = True
        _note_released(self)
        self._raw.release()

    def locked(self) -> bool:
        return self._raw.locked()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<WitnessLock {self._site} {self._raw!r}>"


class _WitnessRLock:
    __slots__ = ("_raw", "_site", "_owner", "_count", "_freed")

    def __init__(self, site: str):
        self._raw = _raw_rlock()
        self._site = site
        self._owner = None
        self._count = 0
        self._freed = False

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            me = _thread.get_ident()
            if self._owner == me:
                self._count += 1  # re-entry: not a new hold for ordering
            else:
                self._owner = me
                self._count = 1
                self._freed = False
                _note_acquired(self)
        return ok

    def release(self) -> None:
        me = _thread.get_ident()
        if self._owner == me:
            self._count -= 1
            if self._count == 0:
                self._owner = None
                self._freed = True
                _note_released(self)
        self._raw.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition support
    def _is_owned(self) -> bool:
        return self._owner == _thread.get_ident()

    def __repr__(self) -> str:
        return f"<WitnessRLock {self._site} {self._raw!r}>"


def _site_of_caller() -> str | None:
    f = sys._getframe(1)
    # frame 1 is the factory below's caller already resolved by callers
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return None
    fn = f.f_code.co_filename
    for root in _package_roots:
        if root in fn:
            return f"{fn}:{f.f_lineno}"
    return None


def _lock_factory():
    site = _site_of_caller()
    if site is None:
        return _raw_lock()
    _record(site, "lock")
    return _WitnessLock(site)


def _rlock_factory():
    site = _site_of_caller()
    if site is None:
        return _raw_rlock()
    _record(site, "rlock")
    return _WitnessRLock(site)


def install(package_roots: tuple[str, ...] = ("dragonfly2_tpu/",)) -> None:
    """Patch the threading factories. Call BEFORE the package imports —
    module-level locks (registries) are created at import time."""
    global _installed, _package_roots
    if _installed:
        return
    _package_roots = tuple(package_roots)
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _raw_lock
    threading.RLock = _raw_rlock
    _installed = False


def active() -> bool:
    return _installed


def reset() -> None:
    with _state_lock:
        _edges.clear()
        _locks.clear()


def snapshot() -> dict:
    with _state_lock:
        return {
            "locks": {s: dict(v) for s, v in _locks.items()},
            "edges": [
                {"from": a, "to": b, "same_site": same}
                for (a, b), same in sorted(_edges.items())
            ],
        }


def dump(path: str | None = None) -> str:
    path = path or os.environ.get("DF_LOCK_WITNESS_OUT", "dfanalyze-witness.json")
    with open(path, "w") as f:
        json.dump(snapshot(), f, indent=2, sort_keys=True)
    return path
