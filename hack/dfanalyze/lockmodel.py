"""AST lock model shared by the lock-order and blocking-under-lock passes.

For every module under the package this builds, statically:

- a **lock registry**: every ``self.X = threading.Lock()`` /
  ``threading.RLock()`` (class attr), module-level ``X = threading.Lock()``
  and function-local lock, identified by a stable node name
  (``<relpath>::<Class>.<attr>``) plus its creation site — the creation
  site is the join key the runtime lock-witness uses to map observed
  acquisition orders back onto this static model;
- a **lock-acquisition graph**: an edge ``A -> B`` whenever lock ``B`` is
  acquired while ``A`` is held, either by direct ``with`` nesting or via
  a call into another method/function *of the same class or module* that
  (transitively) acquires ``B``. Cross-object calls are deliberately out
  of scope — the witness covers orders the AST can't see;
- **blocking-call sites under a held lock**: gRPC-stub calls (CamelCase
  attribute calls on non-module receivers), ``time.sleep``, queue
  get/put, ``.wait``/``.join``/``.acquire``, socket/file I/O, and jax
  dispatch — each classified with a category so the blocking pass can
  report "what kind of wait is happening inside this critical section".

Everything here is heuristic-by-design: the allowlist
(``hack/dfanalyze/allowlist.txt``) is where audited exceptions live, and
the witness run is the dynamic backstop.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
}

# a with-target whose name smells like a lock is treated as one even when
# its definition site wasn't seen (parameter-passed locks, locks defined
# on another object) — better an implicit node than a hole in the graph
_LOCKISH = re.compile(r"lock|mutex", re.I)

_CAMEL = re.compile(r"^[A-Z][A-Za-z0-9]*[a-z][A-Za-z0-9]*$")
_LOWER_IDENT = re.compile(r"^[a-z_][a-z0-9_]*$")
_QUEUEISH = re.compile(
    r"(?:^q$|_q$|queue|bufs|jobs|requests|decisions|deltas|inbox)", re.I
)
_THREADISH = re.compile(r"thread|pool|worker|proc", re.I)

_SOCKET_ATTRS = {"recv", "recv_into", "accept", "connect", "sendall", "makefile"}
_PATH_IO_ATTRS = {"read_text", "read_bytes", "write_text", "write_bytes"}
_OS_BLOCKING = {"os.read", "os.write", "os.sendfile", "os.fsync"}


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass(frozen=True)
class LockDef:
    node: str  # stable name, e.g. "pkg/topology/engine.py::TopologyEngine._lock"
    kind: str  # "lock" | "rlock" | "unknown" (implicit)
    file: str  # repo-relative path
    line: int  # creation/assignment site (witness join key)


@dataclass(frozen=True)
class Edge:
    src: str
    dst: str
    file: str
    line: int
    via: str  # "" for direct `with` nesting, else the callee qualname


@dataclass(frozen=True)
class BlockingSite:
    lock: str  # the held lock's node name
    category: str  # sleep | rpc | queue | wait | thread-join | lock-acquire | socket | file-io | jax
    desc: str  # the call chain as written, e.g. "self.kernels.est_from_landmarks"
    fn: str  # qualname of the function HOLDING the lock
    via: str  # "" when direct, else the callee qualname the call lives in
    file: str
    line: int


@dataclass
class _FnInfo:
    qual: str
    direct_acquires: set[str] = field(default_factory=set)
    # (held-locks-at-call, resolved-callee-qual or None, file, line)
    calls: list = field(default_factory=list)
    # direct nesting edges observed in this function
    edges: list = field(default_factory=list)
    # blocking-classified calls made while locks were held HERE
    blocking: list = field(default_factory=list)  # (held, cat, desc, file, line)
    # every blocking-classified call in this function, held or not — a
    # caller holding a lock around a call into this function blocks on
    # these even though this function itself takes no lock
    blocking_any: list = field(default_factory=list)  # (cat, desc, file, line)
    # calls made regardless of held state, for the transitive fixpoint
    all_callees: set = field(default_factory=set)


@dataclass
class ModuleModel:
    path: str  # repo-relative
    locks: dict[str, LockDef] = field(default_factory=dict)
    edges: list[Edge] = field(default_factory=list)
    blocking: list[BlockingSite] = field(default_factory=list)


class _ModuleWalker:
    def __init__(self, tree: ast.Module, relpath: str):
        self.relpath = relpath
        self.locks: dict[str, LockDef] = {}
        self.fns: dict[str, _FnInfo] = {}
        self.import_roots: set[str] = set()
        self.module_locks: dict[str, str] = {}  # name -> node
        self._collect_imports(tree)
        self._collect_module_locks(tree)
        self._collect_class_locks(tree)
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                class_locks = self.class_locks.get(node.name, {})
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._walk_function(
                            item, f"{node.name}.{item.name}", node.name,
                            class_locks, {},
                        )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_function(node, node.name, None, {}, {})

    # -- collection --------------------------------------------------------
    def _collect_imports(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_roots.add((a.asname or a.name).split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    self.import_roots.add(a.asname or a.name)

    def _lock_kind(self, value: ast.AST) -> str | None:
        if isinstance(value, ast.Call):
            chain = dotted(value.func)
            if chain in LOCK_FACTORIES:
                return LOCK_FACTORIES[chain]
            if chain in ("Lock", "RLock"):  # from threading import Lock
                return "lock" if chain == "Lock" else "rlock"
        return None

    def _collect_module_locks(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                kind = self._lock_kind(node.value)
                if kind and isinstance(t, ast.Name):
                    n = f"{self.relpath}::{t.id}"
                    self.locks[n] = LockDef(n, kind, self.relpath, node.lineno)
                    self.module_locks[t.id] = n

    def _collect_class_locks(self, tree: ast.Module) -> None:
        self.class_locks: dict[str, dict[str, str]] = {}
        for cls in tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            attrs: dict[str, str] = {}
            for node in ast.walk(cls):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                    continue
                t = node.targets[0]
                kind = self._lock_kind(node.value)
                if (
                    kind
                    and isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    n = f"{self.relpath}::{cls.name}.{t.attr}"
                    self.locks[n] = LockDef(n, kind, self.relpath, node.lineno)
                    attrs[t.attr] = n
            self.class_locks[cls.name] = attrs

    # -- per-function walk -------------------------------------------------
    def _walk_function(
        self,
        fn: ast.FunctionDef | ast.AsyncFunctionDef,
        qual: str,
        cls: str | None,
        class_locks: dict[str, str],
        enclosing_locals: dict[str, str],
    ) -> None:
        info = _FnInfo(qual)
        self.fns[qual] = info
        local_locks: dict[str, str] = dict(enclosing_locals)

        def resolve_lock(expr: ast.AST) -> str | None:
            if isinstance(expr, ast.Name):
                if expr.id in local_locks:
                    return local_locks[expr.id]
                if expr.id in self.module_locks:
                    return self.module_locks[expr.id]
                if _LOCKISH.search(expr.id):
                    n = f"{self.relpath}::{qual}.{expr.id}"
                    self.locks.setdefault(
                        n, LockDef(n, "unknown", self.relpath, expr.lineno)
                    )
                    return n
                return None
            chain = dotted(expr)
            if chain is None:
                return None
            if chain.startswith("self.") and chain.count(".") == 1:
                attr = chain.split(".", 1)[1]
                if attr in class_locks:
                    return class_locks[attr]
                if _LOCKISH.search(attr):
                    n = f"{self.relpath}::{cls}.{attr}" if cls else f"{self.relpath}::{chain}"
                    self.locks.setdefault(
                        n, LockDef(n, "unknown", self.relpath, expr.lineno)
                    )
                    return n
                return None
            if _LOCKISH.search(chain.rsplit(".", 1)[-1]):
                n = f"{self.relpath}::{chain}"
                self.locks.setdefault(
                    n, LockDef(n, "unknown", self.relpath, expr.lineno)
                )
                return n
            return None

        def resolve_callee(call: ast.Call) -> str | None:
            f = call.func
            if (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
                and cls is not None
            ):
                return f"{cls}.{f.attr}"
            if isinstance(f, ast.Name):
                # nested function of this one, or module-level function
                if f"{qual}.<locals>.{f.id}" in self.fns:
                    return f"{qual}.<locals>.{f.id}"
                return f.id  # resolved against self.fns at fixpoint time
            return None

        def classify(call: ast.Call) -> tuple[str, str] | None:
            chain = dotted(call.func)
            if chain == "time.sleep":
                return "sleep", chain
            if chain == "open":
                return "file-io", chain
            if chain in _OS_BLOCKING:
                return "file-io", chain
            if chain:
                root = chain.split(".")[0]
                if root in ("jax", "jnp") or ".block_until_ready" in chain:
                    return "jax", chain
                if root in ("socket", "requests", "subprocess") or chain.endswith(
                    ".urlopen"
                ):
                    return "socket", chain
            if not isinstance(call.func, ast.Attribute):
                return None
            attr = call.func.attr
            recv = dotted(call.func.value)
            recv_last = recv.rsplit(".", 1)[-1] if recv else ""
            recv_root = recv.split(".")[0] if recv else ""
            if recv_last in ("kernels", "xp") or (recv or "").endswith(".kernels"):
                return "jax", chain or f"?.{attr}"
            has_timeout = any(k.arg == "timeout" for k in call.keywords)
            if attr in ("get", "put") and (_QUEUEISH.search(recv_last) or has_timeout):
                return "queue", chain or f"?.{attr}"
            if attr == "wait" and not isinstance(call.func.value, ast.Constant):
                return "wait", chain or f"?.{attr}"
            if attr == "join" and recv and _THREADISH.search(recv_last):
                return "thread-join", chain
            if attr == "acquire":
                return "lock-acquire", chain or f"?.{attr}"
            if attr in _SOCKET_ATTRS:
                return "socket", chain or f"?.{attr}"
            if attr in _PATH_IO_ATTRS:
                return "file-io", chain or f"?.{attr}"
            if (
                _CAMEL.match(attr)
                and recv
                and _LOWER_IDENT.match(recv_last)
                and not recv_last.endswith("_pb2")
                and recv_root not in self.import_roots
            ):
                return "rpc", chain
            return None

        def walk(node: ast.AST, held: tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # runs later, not under the current held set
                self._walk_function(
                    node, f"{qual}.<locals>.{node.name}", cls, class_locks, local_locks
                )
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in node.items:
                    for sub in ast.iter_child_nodes(item.context_expr):
                        walk(sub, held)
                    lock = resolve_lock(item.context_expr)
                    if lock is not None:
                        info.direct_acquires.add(lock)
                        for h in new_held:
                            info.edges.append(
                                Edge(h, lock, self.relpath, item.context_expr.lineno, "")
                            )
                        new_held = new_held + (lock,)
                for stmt in node.body:
                    walk(stmt, new_held)
                return
            if isinstance(node, ast.Assign):
                kind = self._lock_kind(node.value)
                if (
                    kind
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                ):
                    name = node.targets[0].id
                    n = f"{self.relpath}::{qual}.{name}"
                    self.locks.setdefault(n, LockDef(n, kind, self.relpath, node.lineno))
                    local_locks[name] = n
            if isinstance(node, ast.Call):
                callee = resolve_callee(node)
                if callee is not None:
                    info.all_callees.add(callee)
                    if held:
                        info.calls.append((held, callee, self.relpath, node.lineno))
                hit = classify(node)
                if hit is not None:
                    info.blocking_any.append(
                        (hit[0], hit[1], self.relpath, node.lineno)
                    )
                    if held:
                        info.blocking.append(
                            (held, hit[0], hit[1], self.relpath, node.lineno)
                        )
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in fn.body:
            walk(stmt, ())


def build_module_model(path: Path, relpath: str) -> ModuleModel | None:
    try:
        tree = ast.parse(path.read_text())
    except SyntaxError:
        return None
    w = _ModuleWalker(tree, relpath)
    model = ModuleModel(relpath, dict(w.locks))

    # fixpoint: transitive lock acquisition + blocking per function. The
    # callee key for a bare Name call is the plain function name, which
    # only resolves when such a module-level function exists.
    acq: dict[str, set[str]] = {q: set(f.direct_acquires) for q, f in w.fns.items()}
    blk: dict[str, list] = {
        q: [(c, d, fl, ln, "") for c, d, fl, ln in f.blocking_any]
        for q, f in w.fns.items()
    }
    changed = True
    iters = 0
    while changed and iters < 50:
        changed = False
        iters += 1
        for q, f in w.fns.items():
            # sorted: the surviving `via` attribution for a deduplicated
            # (category, desc) pair must be deterministic — allowlist
            # keys are derived from it
            for callee in sorted(f.all_callees):
                if callee not in w.fns:
                    continue
                if not acq[callee] <= acq[q]:
                    acq[q] |= acq[callee]
                    changed = True
                have = {(c, d) for c, d, *_ in blk[q]}
                for c, d, fl, ln, via in blk[callee]:
                    if (c, d) not in have:
                        blk[q].append((c, d, fl, ln, via or callee))
                        have.add((c, d))
                        changed = True

    seen_blocking: set[tuple] = set()
    for q, f in w.fns.items():
        model.edges.extend(f.edges)
        for held, callee, fl, ln in f.calls:
            if callee not in w.fns:
                continue
            for lock in sorted(acq[callee]):
                for h in held:
                    model.edges.append(Edge(h, lock, fl, ln, callee))
            for c, d, bfl, bln, via in blk[callee]:
                for h in held:
                    key = (h, c, d, q)
                    if key not in seen_blocking:
                        seen_blocking.add(key)
                        model.blocking.append(
                            BlockingSite(h, c, d, q, via or callee, fl, ln)
                        )
        for held, c, d, fl, ln in f.blocking:
            for h in held:
                key = (h, c, d, q)
                if key not in seen_blocking:
                    seen_blocking.add(key)
                    model.blocking.append(BlockingSite(h, c, d, q, "", fl, ln))
    return model


# one dfanalyze run builds the model for lock-order, blocking AND the
# witness cross-check — parse + fixpoint once per file-set, not three
# times. Keyed by the file snapshot (path, mtime, size) so tests that
# rewrite fixture packages in place get a fresh build.
_model_cache: dict[str, tuple[tuple, list[ModuleModel]]] = {}


def build_package_model(package_dir: Path) -> list[ModuleModel]:
    root = package_dir.parent
    paths = [
        p
        for p in sorted(package_dir.rglob("*.py"))
        if "__pycache__" not in p.parts
    ]
    snapshot = tuple(
        (p.as_posix(), st.st_mtime_ns, st.st_size)
        for p in paths
        for st in [p.stat()]
    )
    key = str(package_dir.resolve())
    cached = _model_cache.get(key)
    if cached is not None and cached[0] == snapshot:
        return cached[1]
    models = []
    for path in paths:
        m = build_module_model(path, path.relative_to(root).as_posix())
        if m is not None:
            models.append(m)
    _model_cache.clear()  # keep one entry: runs alternate repo/fixture dirs
    _model_cache[key] = (snapshot, models)
    return models
