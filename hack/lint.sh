#!/usr/bin/env bash
# hack/lint.sh — the single entry point builders and reviewers run before
# pushing: dfanalyze (lock-order, blocking-under-lock, hygiene,
# jaxhygiene XLA-dispatch lints, metrics census, mypy baseline), the
# legacy check_metrics shim, and a pytest collection smoke. Exits
# nonzero on any regression. Opt-in deep checks ride any pytest run:
# DF_LOCK_WITNESS=1 (lock orders) and DF_JIT_WITNESS=1 (jit
# compiles/transfers, cross-checked via --jit-witness-report).
#
# The collection smoke tolerates ONLY the known environment-caused
# collection errors (modules this image can't import: cryptography,
# jax.shard_map/pallas — see ROADMAP "pre-existing env failures"); any
# NEW file failing collection fails the lint.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== dfanalyze (static passes)"
python -m hack.dfanalyze

echo "== check_metrics (legacy shim entry point)"
python hack/check_metrics.py

echo "== pytest collection smoke"
KNOWN_ENV_ERRORS="tests/test_cert_issuance.py tests/test_ops.py tests/test_security.py tests/test_trainer.py"
out=$(JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --collect-only \
    --continue-on-collection-errors -p no:cacheprovider 2>&1) || true
new_errors=0
while read -r f; do
    case " $KNOWN_ENV_ERRORS " in
        *" $f "*) ;;
        *) echo "lint.sh: NEW collection error in $f"; new_errors=1 ;;
    esac
done < <(printf '%s\n' "$out" | grep -aE '^ERROR tests/' | awk '{print $2}' | sort -u)
# -q collect output is one "tests/test_x.py: N" line per module
collected=$(printf '%s\n' "$out" | grep -aE '^tests/[a-z0-9_]+\.py: [0-9]+$' \
    | awk -F': ' '{s+=$2} END {print s+0}')
echo "lint.sh: $collected test nodes collected"
if [ "$collected" -lt 400 ]; then
    # tier-1 collects 600+; a hard drop means collection itself broke
    echo "lint.sh: collection regressed (expected >= 400 nodes)"
    printf '%s\n' "$out" | tail -20
    exit 1
fi
if [ "$new_errors" -ne 0 ]; then
    exit 1
fi

echo "lint.sh: all clean"
