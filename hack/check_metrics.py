#!/usr/bin/env python3
"""Thin shim: the metric/event/fault-point census now lives in
``hack/dfanalyze/passes/metrics.py`` (one pass of the dfanalyze
framework — run ``python -m hack.dfanalyze`` for the full suite). This
entry point keeps the old CLI (``python hack/check_metrics.py``) and the
``check()`` API that ``tests/test_check_metrics.py`` and muscle memory
depend on.
"""

from __future__ import annotations

import sys
from pathlib import Path

# prefer the canonical hack.dfanalyze tree (what tests/conftest import)
# so one process never holds two copies of the framework; the top-level
# fallback covers the standalone `python hack/check_metrics.py` run,
# where only this script's directory is on sys.path
try:
    from hack.dfanalyze.passes import metrics as _impl
except ImportError:
    try:
        from dfanalyze.passes import metrics as _impl
    except ImportError:
        sys.path.insert(0, str(Path(__file__).resolve().parent))
        from dfanalyze.passes import metrics as _impl

PACKAGE = _impl.PACKAGE
ALLOWED_SERVICES = _impl.ALLOWED_SERVICES
EVENT_SERVICES = _impl.EVENT_SERVICES
FAULT_LAYERS = _impl.FAULT_LAYERS
TESTS_DIR = _impl.TESTS_DIR
KINDS = _impl.KINDS
check = _impl.check


def main() -> int:
    failures = check()
    for f in failures:
        print(f"check_metrics: {f}", file=sys.stderr)
    if failures:
        print(f"check_metrics: {len(failures)} failure(s)", file=sys.stderr)
        return 1
    print(f"check_metrics: OK ({PACKAGE})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
