#!/usr/bin/env python3
"""Real-chip compute-plane smoke: compile and run every jitted path on
whatever accelerator `jax.devices()` resolves to (the single tunneled
TPU in this environment; CPU works too) and check numerics against the
oracles. The CPU test suite runs the same code under the Pallas
interpreter / virtual-device meshes — which cannot catch TPU-only
lowering failures (e.g. the Mosaic block-tiling rule that rejected the
flash kernel's original rank-2 LSE spec). Run this after touching any
kernel or jitted path:

    python hack/tpu_smoke.py

Exit 0 + "COMPUTE-PLANE SMOKE OK" = every path compiled and validated.
"""

from __future__ import annotations

import os
import sys

# runnable from anywhere: sys.path[0] is hack/ when invoked as a script
# (do NOT use PYTHONPATH for this — it breaks the container's
# sitecustomize registration of the axon TPU platform)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    print("devices:", jax.devices())

    # ---- flash attention (Pallas kernel, Mosaic-compiled on TPU) ----
    from dragonfly2_tpu.ops.flash import flash_attention
    from dragonfly2_tpu.ops.ring import local_attention

    failures = []

    def check(name: str, err: float, tol: float) -> None:
        ok = err < tol
        print(f"{name}: max|err|={err:.4f} tol={tol} {'ok' if ok else 'FAIL'}")
        if not ok:
            failures.append(name)

    # MXU default precision truncates f32 matmul inputs to bf16, so the
    # oracle deltas sit ~1e-2 absolute on O(1) outputs — the tolerance
    # tests TPU-semantics parity, not f32 bit equality (the CPU suite
    # covers that at 2e-4)
    TOL = 5e-2
    for (b, t, h, d, causal, dt) in [
        (2, 512, 4, 64, True, jnp.float32),
        (2, 200, 4, 64, True, jnp.float32),  # padded tail
        (1, 333, 2, 32, False, jnp.float32),  # odd length, non-causal
        (2, 512, 4, 64, False, jnp.bfloat16),
        (1, 96, 8, 128, True, jnp.float32),  # short seq, wide head
    ]:
        key = jax.random.PRNGKey(t)
        q, k, v = (
            jax.random.normal(kk, (b, t, h, d), dt) for kk in jax.random.split(key, 3)
        )
        out = flash_attention(q, k, v, causal=causal)
        want = local_attention(q, k, v, causal=causal)
        err = float(
            jnp.max(jnp.abs(out.astype(jnp.float32) - want.astype(jnp.float32)))
        )
        check(f"flash t={t} d={d} causal={causal} {dt.__name__}", err, TOL)

    # non-default block hints must stay Mosaic-legal (the LSE lane rule
    # bites when block_q isn't a multiple of 128)
    for bq_hint, bk_hint, t in [
        (64, 64, 512),
        (24, 16, 100),
        (32, 96, 96),
        (127, 127, 512),  # unaligned pair: must not lcm-explode t_pad
        (128, 12, 512),  # bk not a multiple of 8: sublane rule
    ]:
        key = jax.random.PRNGKey(bq_hint * t)
        q, k, v = (
            jax.random.normal(kk, (1, t, 2, 32), jnp.float32)
            for kk in jax.random.split(key, 3)
        )
        out = flash_attention(q, k, v, causal=True, block_q=bq_hint, block_k=bk_hint)
        want = local_attention(q, k, v, causal=True)
        err = float(jnp.max(jnp.abs(out - want)))
        check(f"flash block_q={bq_hint} block_k={bk_hint} t={t}", err, TOL)

    # backward through the kernel (custom VJP rebuilding P from LSE)
    b, t, h, d = 2, 256, 4, 64
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(kk, (b, t, h, d), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    g_fl = jax.grad(lambda *a: jnp.sum(flash_attention(*a, causal=True) ** 2), (0, 1, 2))(q, k, v)
    g_or = jax.grad(lambda *a: jnp.sum(local_attention(*a, causal=True) ** 2), (0, 1, 2))(q, k, v)
    for name, a, bb in zip("qkv", g_fl, g_or):
        check(f"flash grad d{name}", float(jnp.max(jnp.abs(a - bb))), 2e-1)

    # ---- sequence-parallel paths on a device mesh ----
    from dragonfly2_tpu.ops.ring import make_ring_attention
    from dragonfly2_tpu.ops.ulysses import make_ulysses_attention
    from dragonfly2_tpu.parallel.mesh import make_mesh

    n = len(jax.devices())
    mesh = make_mesh(jax.devices()[:n], sp=n)
    b, t, h, d = 2, 64 * n, max(2, n), 32
    q, k, v = (
        jax.random.normal(kk, (b, t, h, d), jnp.float32)
        for kk in jax.random.split(jax.random.PRNGKey(1), 3)
    )
    want = local_attention(q, k, v, causal=True)
    out_r = make_ring_attention(mesh, "sp", causal=True)(q, k, v)
    check("ring attention", float(jnp.max(jnp.abs(out_r - want))), TOL)
    out_u = make_ulysses_attention(mesh, "sp", causal=True, use_pallas=True)(q, k, v)
    check("ulysses+pallas", float(jnp.max(jnp.abs(out_u - want))), TOL)

    # ---- GNN (sharded + plain), GRU ----
    from dragonfly2_tpu.schema.columnar import records_to_columns
    from dragonfly2_tpu.schema.features import build_probe_graph
    from dragonfly2_tpu.schema.synth import make_topology_records
    from dragonfly2_tpu.trainer.train import GNNFitConfig, train_gnn, train_gnn_sharded

    graph = build_probe_graph(
        records_to_columns(make_topology_records(60, num_hosts=24, seed=0)),
        max_degree=4,
    )
    gp_mesh = make_mesh(jax.devices()[:n], gp=n)
    res = train_gnn_sharded(graph, gp_mesh, config=GNNFitConfig(hidden_dims=(16,), epochs=2))
    check("gnn_sharded loss finite", 0.0 if np.isfinite(res.history[-1]) else 1.0, 0.5)
    r2 = train_gnn(graph, config=GNNFitConfig(hidden_dims=(16,), epochs=2))
    check("train_gnn loss finite", 0.0 if np.isfinite(r2.history[-1]) else 1.0, 0.5)

    from dragonfly2_tpu.models import gru as gru_mod

    gp = gru_mod.init_gru(jax.random.PRNGKey(2), 2, 16)
    seqs = np.random.default_rng(0).random((16, 6, 2)).astype(np.float32)
    pred = jax.jit(gru_mod.predict_next_cost)(
        gp, jnp.asarray(seqs), jnp.full((16,), 6, np.int32)
    )
    check("gru pred finite", 0.0 if np.isfinite(np.asarray(pred)).all() else 1.0, 0.5)

    # ---- orbax checkpoint round-trip of on-device arrays ----
    import tempfile

    from dragonfly2_tpu.models import mlp as mlp_mod
    from dragonfly2_tpu.schema.features import MLP_FEATURE_DIM
    from dragonfly2_tpu.trainer.checkpoint import FitCheckpointer, params_equal

    params = jax.device_put(
        mlp_mod.init_mlp(jax.random.PRNGKey(0), [MLP_FEATURE_DIM, 32, 1])
    )
    with tempfile.TemporaryDirectory(prefix="smoke-ckpt-") as d:
        ck = FitCheckpointer(d)
        state = {"params": params, "epoch": 3}
        ck.save(3, state)
        got = ck.restore_latest(like=state)
        ck.close()
        ok = got is not None and got[0] == 3 and params_equal(params, got[1]["params"])
        check("orbax device-array round-trip", 0.0 if ok else 1.0, 0.5)

    if failures:
        raise SystemExit(f"SMOKE FAILURES: {failures}")
    print("COMPUTE-PLANE SMOKE OK")


if __name__ == "__main__":
    main()
