#!/usr/bin/env python3
"""Boot a full local cluster as real OS processes and drive a download
through it (reference deploy/docker-compose bring-up + test/e2e dfget):

    manager (gRPC + REST) → trainer → scheduler → 2 dfdaemons
    → dfget back-to-source through daemon A
    → dfget P2P through daemon B (pieces served by A)
    → verify bytes, a Download record on the scheduler, REST visibility

Exit code 0 = PASS. Used by hack/run_cluster.sh and the subprocess e2e
test (tests/test_cluster_subprocess.py).
"""

from __future__ import annotations

import json
import os
import queue
import shutil
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Proc:
    def __init__(self, name: str, args: list[str], env: dict):
        self.name = name
        self.proc = subprocess.Popen(
            [sys.executable, *args],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL if os.environ.get("DF_QUIET") else None,
            text=True,
            env=env,
            cwd=REPO,
        )
        self.addr: str | None = None
        self.metrics_addr: str | None = None
        self.rest_addr: str | None = None
        self.gateway_addr: str | None = None
        self.kv_addr: str | None = None
        # a dedicated reader thread avoids mixing select() on the raw fd
        # with buffered readline() (lines stranded in the TextIOWrapper
        # buffer would make select starve)
        self._lines: "queue.Queue[str | None]" = queue.Queue()
        threading.Thread(target=self._pump, daemon=True).start()

    def _pump(self) -> None:
        for line in self.proc.stdout:
            self._lines.put(line)
        self._lines.put(None)

    def wait_ready(self, timeout: float = 120.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.proc.poll() is not None and self._lines.empty():
                raise RuntimeError(f"{self.name} exited rc={self.proc.returncode}")
            try:
                line = self._lines.get(timeout=1.0)
            except queue.Empty:
                continue
            if line is None:
                continue
            if line.startswith("METRICS "):
                self.metrics_addr = line.split()[2]
            if line.startswith("REST "):
                self.rest_addr = line.split()[2]
            if line.startswith("GATEWAY "):
                self.gateway_addr = line.split()[2]
            if line.startswith("KV "):
                self.kv_addr = line.split()[2]
            if line.startswith("READY "):
                self.addr = line.split()[2]
                return self.addr
        raise TimeoutError(f"{self.name} not READY within {timeout}s")

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def main() -> int:
    work = tempfile.mkdtemp(prefix="dfcluster-")
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        PYTHONUNBUFFERED="1",
        DF_JAX_PLATFORM=os.environ.get("DF_JAX_PLATFORM", "cpu"),
        # service-plane spans in OTLP/JSON — the round-5 wire-parity leg:
        # every line a complete ExportTraceServiceRequest the otel
        # collector's otlpjsonfile receiver (→ Jaeger) ingests
        DF_TRACE_DIR=os.path.join(work, "traces"),
        DF_TRACE_FORMAT="otlp",
    )
    procs: list[Proc] = []
    try:
        manager = Proc(
            "manager",
            [
                "-m",
                "dragonfly2_tpu.manager",
                "--set",
                f"data_dir={work}/manager",
                "--set",
                "rest_port=0",
            ],
            env,
        )
        procs.append(manager)
        manager_addr = manager.wait_ready()

        trainer = Proc(
            "trainer",
            [
                "-m",
                "dragonfly2_tpu.trainer",
                "--set",
                f"data_dir={work}/trainer",
                "--set",
                f"manager_address={manager_addr}",
                # GRU trains by default (TrainingConfig.gru); the smoke
                # swarm yields only a handful of sequences, so lower the
                # floor the leg needs to fit
                "--set",
                "gru_min_sequences=1",
            ],
            env,
        )
        procs.append(trainer)
        trainer_addr = trainer.wait_ready()

        scheduler = Proc(
            "scheduler",
            [
                "-m",
                "dragonfly2_tpu.scheduler",
                "--set",
                f"data_dir={work}/scheduler",
                "--set",
                f"manager_address={manager_addr}",
                "--set",
                f"trainer_address={trainer_addr}",
                "--set",
                "algorithm=ml",
                "--set",
                "storage_buffer_size=1",
                "--set",
                "hostname=sched-e2e",
                "--set",
                "metrics_port=0",
                # export the probe graph as NetworkTopology records fast
                # enough for the GNN train leg (reference default: 2h)
                "--set",
                "topology_snapshot_interval=2.0",
            ],
            env,
        )
        procs.append(scheduler)
        scheduler_addr = scheduler.wait_ready()

        sock_a = f"{work}/dfdaemon-a.sock"
        daemons = []
        for name in ("a", "b"):
            args = [
                "-m",
                "dragonfly2_tpu.client.daemon",
                "--set",
                f"data_dir={work}/daemon-{name}",
                "--set",
                f"hostname=host-{name}",
                "--set",
                "piece_length=65536",
                "--set",
                "schedule_timeout=10.0",
                # fast prober so SyncProbes populates the scheduler's
                # probe graph within the script's lifetime (the GNN
                # train leg below consumes its snapshot)
                "--set",
                "probe_interval=0.5",
            ]
            if name == "a":
                # daemon A: static scheduler list + unix socket (the
                # local-CLI path dfget drives below)
                args += [
                    "--set", f"scheduler_address={scheduler_addr}",
                    "--set", f"unix_socket={sock_a}",
                ]
            else:
                # daemon B: no static list — scheduler set discovered
                # from the manager (dynconfig), and it registers itself
                # as a seed peer; also fronts the object-storage gateway
                args += [
                    "--set", 'scheduler_address=""',
                    "--set", f"manager_address={manager_addr}",
                    "--set", "host_type=super",
                    "--set", "object_storage_port=0",
                    "--set", f"object_storage_dir={work}/objects",
                ]
            d = Proc(f"daemon-{name}", args, env)
            procs.append(d)
            daemons.append(d)
        daemon_addrs = [d.wait_ready() for d in daemons]
        daemon_addrs[0] = f"unix:{sock_a}"

        # origin file (file:// keeps the script hermetic; http origins are
        # covered by the in-process e2e tests)
        payload = os.urandom(300 * 1024)
        origin = os.path.join(work, "origin.bin")
        with open(origin, "wb") as f:
            f.write(payload)
        url = f"file://{origin}"

        # dfget through daemon A: back-to-source
        out_a = os.path.join(work, "out-a.bin")
        rc = subprocess.run(
            [
                sys.executable,
                "-m",
                "dragonfly2_tpu.client.dfget",
                url,
                "-O",
                out_a,
                "--daemon",
                daemon_addrs[0],
            ],
            env=env,
            cwd=REPO,
            capture_output=True,
            text=True,
        )
        assert rc.returncode == 0, f"dfget A failed: {rc.stderr[-2000:]}"
        assert open(out_a, "rb").read() == payload, "daemon A bytes mismatch"
        print("PASS dfget back-to-source via daemon A (unix socket)")

        # dfget through daemon B: must pull pieces from A over P2P
        out_b = os.path.join(work, "out-b.bin")
        rc = subprocess.run(
            [
                sys.executable,
                "-m",
                "dragonfly2_tpu.client.dfget",
                url,
                "-O",
                out_b,
                "--daemon",
                daemon_addrs[1],
            ],
            env=env,
            cwd=REPO,
            capture_output=True,
            text=True,
        )
        assert rc.returncode == 0, f"dfget B failed: {rc.stderr[-2000:]}"
        assert open(out_b, "rb").read() == payload, "daemon B bytes mismatch"
        print("PASS dfget P2P via daemon B")

        # ranged dfget: the slice is its own task, correct bytes only
        out_r = os.path.join(work, "out-range.bin")
        rc = subprocess.run(
            [
                sys.executable, "-m", "dragonfly2_tpu.client.dfget",
                url, "-O", out_r,
                "--daemon", daemon_addrs[1],
                "--range", "1000-65999",
            ],
            env=env, cwd=REPO, capture_output=True, text=True,
        )
        assert rc.returncode == 0, f"ranged dfget failed: {rc.stderr[-2000:]}"
        assert open(out_r, "rb").read() == payload[1000:66000], "ranged bytes mismatch"
        print("PASS ranged dfget (--range) via daemon B")

        # zero-byte origin: completes as an empty file through both
        # daemons (reference feature gate dfget-empty-file); the
        # scheduler must record the true length 0, not stay "unknown"
        empty_origin = os.path.join(work, "empty.bin")
        open(empty_origin, "wb").close()
        for i, addr in enumerate(daemon_addrs):
            out_e = os.path.join(work, f"out-empty-{i}.bin")
            rc = subprocess.run(
                [
                    sys.executable, "-m", "dragonfly2_tpu.client.dfget",
                    f"file://{empty_origin}", "-O", out_e, "--daemon", addr,
                ],
                env=env, cwd=REPO, capture_output=True, text=True,
            )
            assert rc.returncode == 0, f"empty dfget {i} failed: {rc.stderr[-2000:]}"
            assert os.path.getsize(out_e) == 0, "empty download must be empty"
        print("PASS empty-file dfget via both daemons")

        # dfcache: import a local file into the cache through the real
        # daemon binary, stat it, export it back (reference dfcache e2e)
        cache_src = os.path.join(work, "cache-src.bin")
        with open(cache_src, "wb") as f:
            f.write(os.urandom(70 * 1024))
        cache_url = "d7y:///cache-e2e"
        for cmd_args in (
            ["import", cache_url, "--path", cache_src],
            ["stat", cache_url],
            # --local-only on export: the step must assert a LOCAL cache
            # hit — without it a miss falls back to "downloading" the
            # unresolvable d7y:// url instead of failing crisply
            [
                "export", cache_url, "--local-only",
                "--output", os.path.join(work, "cache-out.bin"),
            ],
        ):
            rc = subprocess.run(
                [
                    sys.executable, "-m", "dragonfly2_tpu.client.dfcache",
                    *cmd_args, "--daemon", daemon_addrs[0],
                ],
                env=env, cwd=REPO, capture_output=True, text=True, timeout=60,
            )
            assert rc.returncode == 0, (
                f"dfcache {cmd_args[0]} failed: {rc.stderr[-2000:]}"
            )
        assert (
            open(os.path.join(work, "cache-out.bin"), "rb").read()
            == open(cache_src, "rb").read()
        ), "dfcache export bytes mismatch"
        print("PASS dfcache import/stat/export via daemon A")

        # dfstore: object put/stat/get through daemon B's real gateway
        # process (S3-verb surface; upload seeds the swarm)
        gateway = daemons[1].gateway_addr
        assert gateway, "daemon B did not report a GATEWAY address"
        store_src = os.path.join(work, "store-src.bin")
        with open(store_src, "wb") as f:
            f.write(os.urandom(90 * 1024))
        store_out = os.path.join(work, "store-out.bin")
        for cmd_args in (
            ["mb", "df://e2e"],
            ["cp", store_src, "df://e2e/dir/obj.bin"],
            ["stat", "df://e2e/dir/obj.bin"],
            ["cp", "df://e2e/dir/obj.bin", store_out],
        ):
            rc = subprocess.run(
                [
                    sys.executable, "-m", "dragonfly2_tpu.client.dfstore",
                    "--endpoint", gateway, *cmd_args,
                ],
                env=env, cwd=REPO, capture_output=True, text=True, timeout=60,
            )
            assert rc.returncode == 0, (
                f"dfstore {cmd_args[0]} failed: {rc.stderr[-2000:]}"
            )
        assert (
            open(store_out, "rb").read() == open(store_src, "rb").read()
        ), "dfstore round-trip bytes mismatch"
        print("PASS dfstore mb/cp/stat round-trip via daemon B gateway")

        # stress tool: concurrent load through the daemon RPC, one JSON
        # line of percentiles (reference test/tools/stress)
        rc = subprocess.run(
            [
                sys.executable, "-m", "dragonfly2_tpu.tools.stress",
                "--url", url, "--daemon", daemon_addrs[1], "-c", "3", "-n", "9",
            ],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert rc.returncode == 0, f"stress failed: {rc.stderr[-2000:]}"
        stress_stats = json.loads(rc.stdout.strip().splitlines()[-1])
        assert stress_stats["failures"] == 0 and stress_stats["requests"] >= 9, (
            f"stress run unhealthy: {stress_stats}"
        )
        print(
            "PASS stress load generator"
            f" (p50 {stress_stats['latency_s']['p50']}s,"
            f" {stress_stats['throughput_mb_s']} MB/s)"
        )

        # training records landed on the scheduler
        records_dir = os.path.join(work, "scheduler", "records")
        deadline = time.time() + 10
        have_records = False
        while time.time() < deadline and not have_records:
            for root, _, files in os.walk(records_dir):
                if any(f.startswith("download") and f.endswith(".csv") for f in files):
                    have_records = True
            time.sleep(0.2)
        assert have_records, f"no download records under {records_dir}"
        print("PASS download records written")

        # scheduler /metrics scrape shows the download actually moved
        # the instrumented series
        assert scheduler.metrics_addr, "scheduler did not report a metrics address"
        with urllib.request.urlopen(
            f"http://{scheduler.metrics_addr}/metrics", timeout=5
        ) as resp:
            series = resp.read().decode()
        assert "dragonfly_scheduler_announce_peer_total" in series
        assert 'dragonfly_scheduler_register_peer_total' in series
        print("PASS scheduler metrics scrape")

        # manager sees the registered scheduler (gRPC registry; the REST
        # surface gets its own stanza below)
        sys.path.insert(0, REPO)
        from dragonfly2_tpu.rpc import glue, gen  # noqa: F401
        import manager_pb2
        from dragonfly2_tpu.manager.service import SERVICE_NAME

        ch = glue.dial(manager_addr)
        client = glue.ServiceClient(ch, SERVICE_NAME)
        resp = client.ListSchedulers(manager_pb2.ListSchedulersRequest())
        names = [s.hostname for s in resp.schedulers]
        assert "sched-e2e" in names, f"scheduler not registered: {names}"
        ch.close()
        print("PASS scheduler registered with manager")

        # v1 wire generation bound in the production scheduler binary:
        # StatTask over the v1 service sees the downloaded task
        from dragonfly2_tpu.rpc.glue import SCHEDULER_V1_SERVICE
        from dragonfly2_tpu.utils.idgen import task_id_v1
        import scheduler_v1_pb2 as v1

        ch = glue.dial(scheduler_addr)
        v1c = glue.ServiceClient(ch, SCHEDULER_V1_SERVICE)
        stat = v1c.StatTask(v1.StatTaskRequest(task_id=task_id_v1(url, None)))
        assert stat.state == "Succeeded" and stat.has_available_peer, stat
        ch.close()
        print("PASS v1 wire generation serves the same swarm")

        # REST surface: console page, user bootstrap → signin → PAT →
        # authenticated API call
        rest = manager.rest_addr
        assert rest, "manager did not report a REST address"

        def call(method, path, body=None, token=None):
            req = urllib.request.Request(
                f"http://{rest}{path}",
                method=method,
                data=json.dumps(body).encode() if body is not None else None,
                headers={
                    "Content-Type": "application/json",
                    **({"Authorization": f"Bearer {token}"} if token else {}),
                },
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                return json.loads(r.read())

        page = urllib.request.urlopen(f"http://{rest}/", timeout=5).read().decode()
        assert "Dragonfly2-TPU" in page and "/api/v1/models" in page
        user = call("POST", "/api/v1/users", {"name": "op", "password": "pw", "role": "admin"})
        session = call("POST", "/api/v1/users/signin", {"name": "op", "password": "pw"})
        pat = call(
            "POST",
            f"/api/v1/users/{user['id']}/personal-access-tokens",
            {"name": "e2e"},
            token=session["token"],
        )
        rows = call("GET", "/api/v1/schedulers", token=pat["token"])
        assert any(r["hostname"] == "sched-e2e" for r in rows), rows
        print("PASS console + users/PAT auth over REST")

        # daemon B discovered its scheduler from the manager AND
        # registered itself as a seed peer (visible over REST)
        rows = call("GET", "/api/v1/seed-peers", token=pat["token"])
        assert any(r["hostname"] == "host-b" for r in rows), rows
        print("PASS manager-fed discovery + seed-peer registration")

        # train→serve round-trip at subprocess level: the scheduler's
        # Download records stream over the trainer's Train RPC, EOF
        # fires the fit, the model lands in the manager registry, and
        # activation flips it live (SURVEY §3.3)
        import glob as _glob

        import trainer_pb2

        csvs = [
            p
            for p in _glob.glob(
                os.path.join(records_dir, "**", "download*.csv"), recursive=True
            )
            if os.path.isfile(p)
        ]
        assert csvs, "no download CSVs to upload"

        # the probe loop (probe_interval=0.5 above) + snapshot timer
        # (topology_snapshot_interval=2.0) must have exported probe-graph
        # records by now — the GNN leg trains on them
        def _topo_csvs():
            return [
                p
                for p in _glob.glob(
                    os.path.join(records_dir, "**", "networktopology*.csv"),
                    recursive=True,
                )
                if os.path.isfile(p) and os.path.getsize(p) > 0
            ]

        deadline = time.time() + 60
        topo = _topo_csvs()
        while time.time() < deadline and not topo:
            time.sleep(0.5)
            topo = _topo_csvs()
        assert topo, f"no networktopology CSVs under {records_dir}"
        print("PASS probe loop exported NetworkTopology records")

        tchan = glue.dial(trainer_addr)
        tclient = glue.ServiceClient(tchan, glue.TRAINER_SERVICE)

        def _train_reqs():
            for p in csvs:
                with open(p, "rb") as f:
                    data = f.read()
                yield trainer_pb2.TrainRequest(
                    ip="10.99.0.1",
                    hostname="sched-e2e",
                    train_mlp=trainer_pb2.TrainMlpRequest(dataset=data),
                )
            for p in topo:
                with open(p, "rb") as f:
                    data = f.read()
                yield trainer_pb2.TrainRequest(
                    ip="10.99.0.1",
                    hostname="sched-e2e",
                    train_gnn=trainer_pb2.TrainGnnRequest(dataset=data),
                )

        tclient.Train(_train_reqs(), timeout=600)
        tchan.close()
        models = {}
        deadline = time.time() + 240
        while time.time() < deadline and len(models) < 3:
            rows = call("GET", "/api/v1/models", token=pat["token"])
            models = {r["type"]: r for r in rows}
            time.sleep(1)
        # NOTE: no early exit once some models land — on a 1-core CI box
        # the three fits' first XLA compiles run concurrently and the
        # slowest can trail the others by minutes; "two landed, third
        # missing" does NOT imply the third failed
        missing_hint = "(check the trainer proc's log for the fit error)"
        assert "mlp" in models, f"no MLP model uploaded: {sorted(models)} {missing_hint}"
        assert "gnn" in models, f"no GNN model uploaded: {sorted(models)} {missing_hint}"
        assert "gru" in models, f"no GRU model uploaded: {sorted(models)} {missing_hint}"
        model = models["mlp"]
        act = call(
            "PUT",
            f"/api/v1/models/{model['model_id']}/versions/{model['version']}/state",
            {"state": "active"},
            token=pat["token"],
        )
        assert act["state"] == "active"
        print(
            "PASS train-serve roundtrip (records -> Train RPC -> MLP+GNN+GRU"
            f" fits -> CreateModel → activation; models={sorted(models)})"
        )

        # dynamic certificate issuance: CSR → booted manager's CA →
        # chain that verifies against the persisted root
        from dragonfly2_tpu.utils.issuer import obtain_certificate

        key_pem, leaf_pem, ca_pem = obtain_certificate(
            manager_addr, "e2e-service", hosts=["localhost", "127.0.0.1"]
        )
        assert b"BEGIN CERTIFICATE" in leaf_pem and b"BEGIN CERTIFICATE" in ca_pem
        on_disk_ca = open(os.path.join(work, "manager", "ca", "ca.crt"), "rb").read()
        assert ca_pem == on_disk_ca, "returned chain root must be the persisted CA"
        print("PASS dynamic certificate issuance (CSR → manager CA)")

        # OTLP trace export: the booted binaries wrote span files whose
        # every line parses as an ExportTraceServiceRequest
        trace_files = _glob.glob(os.path.join(work, "traces", "*.otlp.jsonl"))
        assert trace_files, "no OTLP trace files written"
        span_count = 0
        services = set()
        for tf in trace_files:
            for line in open(tf):
                try:
                    req = json.loads(line)
                except json.JSONDecodeError:
                    # the services are still running — a final line may
                    # be mid-write; completed lines are the contract
                    continue
                rs = req["resourceSpans"][0]
                svc = {
                    a["key"]: a["value"]["stringValue"]
                    for a in rs["resource"]["attributes"]
                }
                services.add(svc["service.name"])
                for sp in rs["scopeSpans"][0]["spans"]:
                    assert len(sp["traceId"]) == 32 and len(sp["spanId"]) == 16
                    span_count += 1
        assert span_count > 0
        print(
            f"PASS OTLP trace export ({span_count} spans from {sorted(services)})"
        )

        print("CLUSTER E2E: ALL PASS")
        return 0
    finally:
        for p in reversed(procs):
            p.stop()
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
