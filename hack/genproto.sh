#!/usr/bin/env bash
# Regenerate protobuf message modules (messages only; gRPC method stubs are
# hand-written in dragonfly2_tpu/rpc/glue.py).
set -euo pipefail
cd "$(dirname "$0")/../dragonfly2_tpu/rpc"
protoc -I protos --python_out=gen \
  protos/common.proto protos/scheduler.proto protos/scheduler_v1.proto protos/trainer.proto \
  protos/manager.proto protos/dfdaemon.proto
echo "generated: $(ls gen/*_pb2.py)"
