# Makes hack/ importable so `python -m hack.dfanalyze` works from the
# repo root. The scripts in here still run standalone too.
