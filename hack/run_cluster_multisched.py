#!/usr/bin/env python3
"""Two-scheduler cluster e2e: the shared-KV (Redis role) deployment shape.

    manager (gRPC + embedded RESP KV server)
    → scheduler-1 + scheduler-2, both pointed at the manager's KV
    → daemon A + daemon B with BOTH schedulers in their static list
    → dfgets whose task ids deterministically hash to each scheduler
      (consistent-hash affinity actually splits the workload)
    → SyncProbes from both daemons land in the ONE shared store
    → each scheduler's topology snapshot exports edges the OTHER
      scheduler's clients synced (cross-process sharing, the round-4
      verdict's last architectural hole)

Reference shape: N schedulers × one Redis
(scheduler/networktopology/network_topology.go:88-89 takes a
redis.UniversalClient; key schema pkg/redis/redis.go). Exit 0 = PASS.
"""

from __future__ import annotations

import glob as globmod
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from hack.run_cluster import Proc  # noqa: E402 — shared process harness


def wait_for(pred, timeout: float, what: str, interval: float = 0.5):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {what}")


def main() -> int:
    work = tempfile.mkdtemp(prefix="dfcluster2-")
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        PYTHONUNBUFFERED="1",
        DF_JAX_PLATFORM=os.environ.get("DF_JAX_PLATFORM", "cpu"),
    )
    procs: list[Proc] = []
    try:
        manager = Proc(
            "manager",
            [
                "-m", "dragonfly2_tpu.manager",
                "--set", f"data_dir={work}/manager",
                "--set", "kv_port=0",
                "--set", "kv_host=127.0.0.1",
            ],
            env,
        )
        procs.append(manager)
        manager_addr = manager.wait_ready()
        kv_addr = manager.kv_addr
        assert kv_addr, "manager did not report a KV address"
        print(f"manager kv at {kv_addr}")

        scheds = []
        for i in (1, 2):
            s = Proc(
                f"scheduler-{i}",
                [
                    "-m", "dragonfly2_tpu.scheduler",
                    "--set", f"data_dir={work}/scheduler-{i}",
                    "--set", f"manager_address={manager_addr}",
                    "--set", f"kv_address={kv_addr}",
                    "--set", f"hostname=sched-{i}",
                    "--set", "storage_buffer_size=1",
                    # fast probe-graph CSV export so the cross-visibility
                    # assertion lands within the script's lifetime
                    "--set", "topology_snapshot_interval=2.0",
                ],
                env,
            )
            procs.append(s)
            scheds.append(s)
        sched_addrs = [s.wait_ready() for s in scheds]
        sched_list = ",".join(sched_addrs)

        daemons = []
        for name in ("a", "b"):
            d = Proc(
                f"daemon-{name}",
                [
                    "-m", "dragonfly2_tpu.client.daemon",
                    "--set", f"data_dir={work}/daemon-{name}",
                    "--set", f"hostname=host-{name}",
                    "--set", f"scheduler_address={sched_list}",
                    "--set", "piece_length=65536",
                    "--set", "schedule_timeout=10.0",
                    "--set", "probe_interval=0.5",
                ],
                env,
            )
            procs.append(d)
            daemons.append(d)
        daemon_addrs = [d.wait_ready() for d in daemons]

        # -- task affinity: pick origin files whose task ids hash to EACH
        # scheduler, so the split is deterministic, not luck
        from dragonfly2_tpu.rpc.glue import ConsistentHashRing
        from dragonfly2_tpu.utils.idgen import task_id_v1

        ring = ConsistentHashRing(sched_addrs)
        by_sched: dict[str, list[str]] = {a: [] for a in sched_addrs}
        i = 0
        while any(len(v) < 2 for v in by_sched.values()):
            path = os.path.join(work, f"origin-{i}.bin")
            url = f"file://{path}"
            node = ring.pick(task_id_v1(url, None))
            if len(by_sched[node]) < 2:
                with open(path, "wb") as f:
                    f.write(os.urandom(96 * 1024 + i))
                by_sched[node].append(url)
            i += 1
        urls = [u for v in by_sched.values() for u in v]

        for j, url in enumerate(urls):
            out = os.path.join(work, f"out-{j}.bin")
            rc = subprocess.run(
                [
                    sys.executable, "-m", "dragonfly2_tpu.client.dfget",
                    url, "-O", out, "--daemon", daemon_addrs[j % 2],
                ],
                env=env, cwd=REPO, capture_output=True, text=True, timeout=120,
            )
            assert rc.returncode == 0, f"dfget {url} failed: {rc.stderr[-2000:]}"
            assert (
                open(out, "rb").read() == open(url[len("file://"):], "rb").read()
            ), f"bytes mismatch for {url}"
        print(f"PASS {len(urls)} dfgets across both daemons")

        # -- consistent-hash affinity split the workload: each scheduler
        # wrote Download records for ITS tasks
        def records_of(i):
            rows = []
            for p in globmod.glob(
                os.path.join(work, f"scheduler-{i}", "records", "**", "download*.csv"),
                recursive=True,
            ):
                if os.path.getsize(p) > 0:
                    rows.append(p)
            return rows

        wait_for(lambda: records_of(1) and records_of(2), 30,
                 "download records on both schedulers")
        print("PASS task affinity split records across both schedulers")

        # -- SyncProbes from both daemons landed in the ONE shared store
        from dragonfly2_tpu.utils.kvstore import RemoteKVStore

        kv = RemoteKVStore(kv_addr)

        def probe_srcs():
            srcs = set()
            for key in kv.scan_iter("networktopology:*"):
                srcs.add(key.split(":", 2)[1])
            return srcs if len(srcs) >= 2 else None

        srcs = wait_for(probe_srcs, 60, "probe edges from two hosts in the shared KV")
        assert len(srcs) >= 2, srcs
        counts = kv.scan_iter("probedcount:*")
        assert counts, "no probed-count counters in the shared store"
        print(f"PASS SyncProbes from {len(srcs)} hosts share one KV store ({len(counts)} counters)")

        # -- cross-process visibility: EACH scheduler's topology snapshot
        # exports edges for BOTH daemons, including the edge synced via
        # the other scheduler (both read the same store; hosts are known
        # everywhere because the daemon announces to every scheduler)
        def snapshot_srcs(i):
            srcs = set()
            for p in globmod.glob(
                os.path.join(
                    work, f"scheduler-{i}", "records", "**", "networktopology*.csv"
                ),
                recursive=True,
            ):
                if os.path.getsize(p) == 0:
                    continue
                with open(p) as f:
                    header = f.readline().strip().split(",")
                    try:
                        idx = header.index("host.id")
                    except ValueError:
                        continue
                    for line in f:
                        cells = line.split(",")
                        if len(cells) > idx and cells[idx]:
                            srcs.add(cells[idx])
            return srcs

        wait_for(
            lambda: len(snapshot_srcs(1)) >= 2 and len(snapshot_srcs(2)) >= 2,
            60,
            "both schedulers exporting both hosts' probe edges",
        )
        print("PASS each scheduler snapshots the SHARED graph (both hosts' edges)")

        print("CLUSTER2 E2E: ALL PASS")
        return 0
    finally:
        for p in reversed(procs):
            p.stop()
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
