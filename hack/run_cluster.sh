#!/usr/bin/env bash
# Boot manager + trainer + scheduler + 2 dfdaemons as real processes and
# drive dfget through the swarm (reference deploy/docker-compose +
# test/e2e). Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python3 hack/run_cluster.py "$@"
