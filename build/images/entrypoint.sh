#!/bin/sh
# Service selector: manager | scheduler | trainer | daemon | dfget | dfcache | dfstore
set -e
svc="$1"; shift || true
case "$svc" in
  manager|scheduler|trainer) exec python -m "dragonfly2_tpu.$svc" "$@" ;;
  daemon)  exec python -m dragonfly2_tpu.client.daemon "$@" ;;
  dfget)   exec python -m dragonfly2_tpu.client.dfget "$@" ;;
  dfcache) exec python -m dragonfly2_tpu.client.dfcache "$@" ;;
  dfstore) exec python -m dragonfly2_tpu.client.dfstore "$@" ;;
  *) echo "usage: <manager|scheduler|trainer|daemon|dfget|dfcache|dfstore> [flags]" >&2; exit 2 ;;
esac
