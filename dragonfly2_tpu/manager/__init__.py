"""Manager — the control plane (reference manager/, SURVEY.md §2.4):
cluster registry, dynamic config serving, model registry with
inactive→active versioning, searcher, object storage."""
