"""`python -m dragonfly2_tpu.manager` — the manager binary (reference
cmd/manager/main.go)."""

import sys

from dragonfly2_tpu.cli.runner import main_with_config
from dragonfly2_tpu.manager.server import build

if __name__ == "__main__":
    sys.exit(main_with_config("manager", build))
