"""Manager Prometheus series (reference manager/metrics: request
volumes on the control-plane surfaces)."""

from dragonfly2_tpu.utils.metrics import default_registry as _r

GRPC_REQUEST_TOTAL = _r.counter(
    "manager_grpc_request_total", "gRPC requests", ("method",)
)
REST_REQUEST_TOTAL = _r.counter(
    "manager_rest_request_total", "REST requests", ("method", "status")
)
KEEPALIVE_TOTAL = _r.counter(
    "manager_keepalive_total", "Keepalive messages", ("source_type",)
)
MODEL_CREATED_TOTAL = _r.counter(
    "manager_model_created_total", "Models uploaded by trainers", ("type",)
)
