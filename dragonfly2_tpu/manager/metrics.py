"""Manager Prometheus series (reference manager/metrics: request
volumes on the control-plane surfaces)."""

from dragonfly2_tpu.utils.metrics import default_registry as _r

GRPC_REQUEST_TOTAL = _r.counter(
    "manager_grpc_request_total", "gRPC requests", ("method",)
)
REST_REQUEST_TOTAL = _r.counter(
    "manager_rest_request_total", "REST requests", ("method", "status")
)
KEEPALIVE_TOTAL = _r.counter(
    "manager_keepalive_total", "Keepalive messages", ("source_type",)
)
MODEL_CREATED_TOTAL = _r.counter(
    "manager_model_created_total", "Models uploaded by trainers", ("type",)
)

# -- cluster telemetry plane (manager/telemetry.py, docs/telemetry.md) --
TELEMETRY_REPORTS_TOTAL = _r.counter(
    "manager_telemetry_reports_total",
    "Telemetry reports received, by outcome",
    ("service", "outcome"),  # outcome: applied | registered | duplicate
)
TELEMETRY_REPORTERS = _r.gauge(
    "manager_telemetry_reporters",
    "Reporters known to the telemetry plane",
    ("service",),
)
SLO_BURN_RATE = _r.gauge(
    "manager_slo_burn_rate",
    "Error-budget burn rate per SLO and evaluation window",
    ("slo", "window"),
)
SLO_BREACHED = _r.gauge(
    "manager_slo_breached",
    "1 while the SLO's multi-window burn rate is in breach",
    ("slo",),
)
