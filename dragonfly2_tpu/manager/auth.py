"""Users + personal access tokens for the manager REST plane.

Role parity: reference manager/handlers user/PAT surface with casbin
role checks (manager/service/ users.go, personal_access_tokens.go) —
reduced to the two roles the API distinguishes (admin = full access,
guest = read-only, reference roles `root`/`guest`). Passwords are
PBKDF2-hashed with a per-user salt; tokens are random secrets returned
exactly once and stored as SHA-256 hashes, so a database leak exposes
neither.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
import time

TOKEN_PREFIX = "dfp_"  # personal access token (reference PAT-style)
ROLES = ("admin", "guest")
_PBKDF2_ITERS = 100_000


def _hash_password(password: str, salt: str) -> str:
    return hashlib.pbkdf2_hmac(
        "sha256", password.encode(), bytes.fromhex(salt), _PBKDF2_ITERS
    ).hex()


def _hash_token(token: str) -> str:
    return hashlib.sha256(token.encode()).hexdigest()


def create_user(
    db, name: str, password: str, role: str = "guest", email: str = ""
) -> dict:
    if role not in ROLES:
        raise ValueError(f"role must be one of {ROLES}")
    if not name or not password:
        raise ValueError("name and password are required")
    salt = secrets.token_hex(16)
    now = time.time()
    cur = db.execute(
        "INSERT INTO users (name, email, password_salt, password_hash, role,"
        " created_at, updated_at) VALUES (?, ?, ?, ?, ?, ?, ?)",
        (name, email, salt, _hash_password(password, salt), role, now, now),
    )
    return db.query_one("SELECT * FROM users WHERE id = ?", (cur.lastrowid,))


def set_password(db, user_id: int, new_password: str) -> None:
    """Re-salt and store a new password (reset_password handler)."""
    if not new_password:
        raise ValueError("new password must not be empty")
    salt = secrets.token_hex(16)
    db.execute(
        "UPDATE users SET password_salt = ?, password_hash = ?, updated_at = ?"
        " WHERE id = ?",
        (salt, _hash_password(new_password, salt), time.time(), user_id),
    )


def revoke_pats_for_token(db, token: str) -> int:
    """Revoke the PAT row matching this plaintext token (signout).
    Returns rows revoked (0 when the token is config-file based or
    already gone — callers surface that as a client error)."""
    cur = db.execute(
        "UPDATE personal_access_tokens SET state = 'revoked' WHERE token_hash = ?",
        (_hash_token(token),),
    )
    return cur.rowcount


def verify_password(db, name: str, password: str) -> dict | None:
    """→ user row on a correct password for an enabled user, else None."""
    row = db.query_one(
        "SELECT * FROM users WHERE name = ? AND state = 'enabled'", (name,)
    )
    if row is None:
        return None
    expected = _hash_password(password, row["password_salt"])
    if not hmac.compare_digest(expected, row["password_hash"]):
        return None
    return row


def create_pat(db, user_id: int, name: str, ttl: float = 0.0) -> tuple[str, dict]:
    """Mint a token for a user; returns (plaintext_token, row). The
    plaintext is shown exactly once — only its hash is stored."""
    token = TOKEN_PREFIX + secrets.token_urlsafe(32)
    now = time.time()
    cur = db.execute(
        "INSERT INTO personal_access_tokens (user_id, name, token_hash,"
        " expires_at, created_at, updated_at) VALUES (?, ?, ?, ?, ?, ?)",
        (user_id, name, _hash_token(token), now + ttl if ttl > 0 else 0.0, now, now),
    )
    row = db.query_one(
        "SELECT * FROM personal_access_tokens WHERE id = ?", (cur.lastrowid,)
    )
    return token, row


def revoke_pat(db, pat_id: int) -> None:
    db.execute(
        "UPDATE personal_access_tokens SET state = 'revoked', updated_at = ?"
        " WHERE id = ?",
        (time.time(), pat_id),
    )


def _resolve_token_row(db, token: str) -> dict | None:
    """ONE definition of token validity (active token, not expired,
    enabled owner) shared by authentication (role) and authorization
    (owner id) — two copies of this rule set in a security path would
    inevitably drift."""
    if not token.startswith(TOKEN_PREFIX):
        return None
    row = db.query_one(
        "SELECT t.user_id, t.expires_at, u.role, u.state AS user_state FROM"
        " personal_access_tokens t JOIN users u ON u.id = t.user_id"
        " WHERE t.token_hash = ? AND t.state = 'active'",
        (_hash_token(token),),
    )
    if row is None or row["user_state"] != "enabled":
        return None
    if row["expires_at"] and row["expires_at"] < time.time():
        return None
    return row


def resolve_token(db, token: str) -> str | None:
    """Bearer token → role, or None. Valid = active token, not expired,
    owned by an enabled user."""
    row = _resolve_token_row(db, token)
    return None if row is None else row["role"]


# ---------------------------------------------------------------------------
# OAuth2 sign-in (reference manager/auth/oauth/{oauth,google,github}.go +
# handlers/oauth.go). Providers are DB rows with generic endpoint URLs
# (auth/token/userinfo) instead of baked per-vendor SDK configs — google
# and github are both expressible as rows, and tests can point a row at
# a fake provider.
# ---------------------------------------------------------------------------


def sign_state(secret: bytes, provider: str, ttl: float = 600.0) -> str:
    """CSRF state: provider|expiry|nonce, HMAC-signed (the reference
    signs a random state into the AuthCodeURL the same way)."""
    import base64

    payload = f"{provider}|{time.time() + ttl:.0f}|{secrets.token_hex(8)}"
    sig = hmac.new(secret, payload.encode(), hashlib.sha256).hexdigest()[:32]
    return base64.urlsafe_b64encode(f"{payload}|{sig}".encode()).decode()


def verify_state(secret: bytes, state: str, provider: str) -> bool:
    import base64

    try:
        payload, _, sig = (
            base64.urlsafe_b64decode(state.encode()).decode().rpartition("|")
        )
        want = hmac.new(secret, payload.encode(), hashlib.sha256).hexdigest()[:32]
        prov, expiry, _nonce = payload.split("|", 2)
    except (ValueError, UnicodeDecodeError):
        return False
    return (
        hmac.compare_digest(sig, want)
        and prov == provider
        and float(expiry) >= time.time()
    )


def oauth_authorize_url(provider: dict, state: str) -> str:
    """The URL the browser is redirected to (reference AuthCodeURL)."""
    import urllib.parse

    params = {
        "response_type": "code",
        "client_id": provider["client_id"],
        "state": state,
    }
    if provider.get("redirect_url"):
        params["redirect_uri"] = provider["redirect_url"]
    if provider.get("scopes"):
        params["scope"] = provider["scopes"]
    sep = "&" if "?" in provider["auth_url"] else "?"
    return provider["auth_url"] + sep + urllib.parse.urlencode(params)


def oauth_exchange(provider: dict, code: str, timeout: float = 10.0) -> str:
    """Authorization code → access token (reference Exchange)."""
    import json as _json
    import urllib.parse
    import urllib.request

    body = urllib.parse.urlencode(
        {
            "grant_type": "authorization_code",
            "code": code,
            "client_id": provider["client_id"],
            "client_secret": provider["client_secret"],
            **(
                {"redirect_uri": provider["redirect_url"]}
                if provider.get("redirect_url")
                else {}
            ),
        }
    ).encode()
    req = urllib.request.Request(
        provider["token_url"],
        data=body,
        headers={
            "Content-Type": "application/x-www-form-urlencoded",
            "Accept": "application/json",
        },
    )
    import urllib.error

    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            data = _json.loads(resp.read())
    except urllib.error.HTTPError as e:
        # RFC 6749 token endpoints signal invalid_grant etc. as HTTP 400
        # — a routine client retry, not a server fault
        raise ValueError(f"token endpoint refused the code: {e.code}") from e
    except urllib.error.URLError as e:
        raise ValueError(f"token endpoint unreachable: {e.reason}") from e
    token = data.get("access_token", "")
    if not token:
        raise ValueError(f"token endpoint returned no access_token: {data}")
    return token


def oauth_userinfo(provider: dict, access_token: str, timeout: float = 10.0) -> dict:
    import json as _json
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        provider["userinfo_url"],
        headers={"Authorization": f"Bearer {access_token}", "Accept": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return _json.loads(resp.read())
    except urllib.error.HTTPError as e:
        # insufficient scope / revoked token — an IdP refusal, not a
        # manager fault (mirrors oauth_exchange's mapping)
        raise ValueError(f"userinfo endpoint refused the token: {e.code}") from e
    except urllib.error.URLError as e:
        raise ValueError(f"userinfo endpoint unreachable: {e.reason}") from e


def oauth_signin(db, provider: dict, code: str) -> tuple[str, dict]:
    """Full callback leg: exchange the code, fetch the identity,
    find-or-provision the user (role guest, no password — OAuth is the
    credential), and mint a 24h session token. → (token, user_row).

    Users are matched by (provider, subject) — the IdP's STABLE id —
    never by display name: an attacker-controlled login/name at the IdP
    must not be able to take over an existing local account (e.g. one
    named like an admin). A taken display name gets uniquified."""
    access = oauth_exchange(provider, code)
    info = oauth_userinfo(provider, access)
    email = str(info.get("email") or "")
    # id/sub only: login handles are reassignable at most IdPs, so a
    # recycled handle must never resolve to the previous owner's account
    subject = str(info.get("id") or info.get("sub") or "")
    display = str(
        info.get("login") or info.get("name") or email.partition("@")[0] or ""
    )
    if not subject:
        raise ValueError(
            "oauth userinfo lacks a stable subject (id/sub) — refusing to"
            " link accounts by a reassignable handle"
        )
    user = db.query_one(
        "SELECT * FROM users WHERE oauth_provider = ? AND oauth_subject = ?",
        (provider["name"], subject),
    )
    if user is None:
        name = display or f"{provider['name']}-{subject}"
        for suffix in ("", *(f"-{i}" for i in range(2, 100))):
            if db.query_one(
                "SELECT id FROM users WHERE name = ?", (name + suffix,)
            ) is None:
                name = name + suffix
                break
        else:
            raise ValueError(f"cannot allocate a unique name for {display!r}")
        user = create_user(db, name, secrets.token_hex(16), role="guest", email=email)
        db.execute(
            "UPDATE users SET oauth_provider = ?, oauth_subject = ? WHERE id = ?",
            (provider["name"], subject, user["id"]),
        )
        user = db.query_one("SELECT * FROM users WHERE id = ?", (user["id"],))
    if user["state"] != "enabled":
        raise ValueError(f"user {user['name']!r} is disabled")
    token, _ = create_pat(
        db, user["id"], f"oauth-session-{provider['name']}", ttl=24 * 3600.0
    )
    return token, user


def state_secret(db) -> bytes:
    """The OAuth CSRF-state HMAC key, stored in the DB so the
    redirect→callback round-trip survives manager restarts and works
    across replicas sharing the database."""
    row = db.query_one("SELECT value FROM settings WHERE key = 'oauth_state_secret'")
    if row is not None:
        return bytes.fromhex(row["value"])
    key = secrets.token_bytes(32)
    # racing replicas: INSERT OR IGNORE, then re-read the winner
    db.execute(
        "INSERT OR IGNORE INTO settings (key, value) VALUES"
        " ('oauth_state_secret', ?)",
        (key.hex(),),
    )
    row = db.query_one("SELECT value FROM settings WHERE key = 'oauth_state_secret'")
    return bytes.fromhex(row["value"])
