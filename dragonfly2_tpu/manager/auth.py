"""Users + personal access tokens for the manager REST plane.

Role parity: reference manager/handlers user/PAT surface with casbin
role checks (manager/service/ users.go, personal_access_tokens.go) —
reduced to the two roles the API distinguishes (admin = full access,
guest = read-only, reference roles `root`/`guest`). Passwords are
PBKDF2-hashed with a per-user salt; tokens are random secrets returned
exactly once and stored as SHA-256 hashes, so a database leak exposes
neither.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
import time

TOKEN_PREFIX = "dfp_"  # personal access token (reference PAT-style)
ROLES = ("admin", "guest")
_PBKDF2_ITERS = 100_000


def _hash_password(password: str, salt: str) -> str:
    return hashlib.pbkdf2_hmac(
        "sha256", password.encode(), bytes.fromhex(salt), _PBKDF2_ITERS
    ).hex()


def _hash_token(token: str) -> str:
    return hashlib.sha256(token.encode()).hexdigest()


def create_user(
    db, name: str, password: str, role: str = "guest", email: str = ""
) -> dict:
    if role not in ROLES:
        raise ValueError(f"role must be one of {ROLES}")
    if not name or not password:
        raise ValueError("name and password are required")
    salt = secrets.token_hex(16)
    now = time.time()
    cur = db.execute(
        "INSERT INTO users (name, email, password_salt, password_hash, role,"
        " created_at, updated_at) VALUES (?, ?, ?, ?, ?, ?, ?)",
        (name, email, salt, _hash_password(password, salt), role, now, now),
    )
    return db.query_one("SELECT * FROM users WHERE id = ?", (cur.lastrowid,))


def verify_password(db, name: str, password: str) -> dict | None:
    """→ user row on a correct password for an enabled user, else None."""
    row = db.query_one(
        "SELECT * FROM users WHERE name = ? AND state = 'enabled'", (name,)
    )
    if row is None:
        return None
    expected = _hash_password(password, row["password_salt"])
    if not hmac.compare_digest(expected, row["password_hash"]):
        return None
    return row


def create_pat(db, user_id: int, name: str, ttl: float = 0.0) -> tuple[str, dict]:
    """Mint a token for a user; returns (plaintext_token, row). The
    plaintext is shown exactly once — only its hash is stored."""
    token = TOKEN_PREFIX + secrets.token_urlsafe(32)
    now = time.time()
    cur = db.execute(
        "INSERT INTO personal_access_tokens (user_id, name, token_hash,"
        " expires_at, created_at, updated_at) VALUES (?, ?, ?, ?, ?, ?)",
        (user_id, name, _hash_token(token), now + ttl if ttl > 0 else 0.0, now, now),
    )
    row = db.query_one(
        "SELECT * FROM personal_access_tokens WHERE id = ?", (cur.lastrowid,)
    )
    return token, row


def revoke_pat(db, pat_id: int) -> None:
    db.execute(
        "UPDATE personal_access_tokens SET state = 'revoked', updated_at = ?"
        " WHERE id = ?",
        (time.time(), pat_id),
    )


def resolve_token(db, token: str) -> str | None:
    """Bearer token → role, or None. Valid = active token, not expired,
    owned by an enabled user."""
    if not token.startswith(TOKEN_PREFIX):
        return None
    row = db.query_one(
        "SELECT t.expires_at, u.role, u.state AS user_state FROM"
        " personal_access_tokens t JOIN users u ON u.id = t.user_id"
        " WHERE t.token_hash = ? AND t.state = 'active'",
        (_hash_token(token),),
    )
    if row is None or row["user_state"] != "enabled":
        return None
    if row["expires_at"] and row["expires_at"] < time.time():
        return None
    return row["role"]
