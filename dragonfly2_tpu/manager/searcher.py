"""Searcher: pick the best scheduler cluster for a joining peer
(reference manager/searcher/searcher.go:38-290).

Scoring weights: security/CIDR affinity 0.4, IDC 0.35, location 0.24,
cluster type (default bonus) 0.01 — reference searcher.go:47-57.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field

CIDR_AFFINITY_WEIGHT = 0.4
IDC_AFFINITY_WEIGHT = 0.35
LOCATION_AFFINITY_WEIGHT = 0.24
CLUSTER_TYPE_WEIGHT = 0.01

MAX_LOCATION_ELEMENTS = 5


@dataclass
class ClusterScope:
    idc: str = ""  # "|"-separated alternatives
    location: str = ""
    cidrs: list[str] = field(default_factory=list)


@dataclass
class Cluster:
    id: int
    name: str
    scopes: ClusterScope = field(default_factory=ClusterScope)
    is_default: bool = False


@dataclass
class PeerInfo:
    ip: str = ""
    idc: str = ""
    location: str = ""


def cidr_affinity(ip: str, cidrs: list[str]) -> float:
    if not ip or not cidrs:
        return 0.0
    try:
        addr = ipaddress.ip_address(ip)
    except ValueError:
        return 0.0
    for cidr in cidrs:
        try:
            if addr in ipaddress.ip_network(cidr, strict=False):
                return 1.0
        except ValueError:
            continue
    return 0.0


def idc_affinity(peer_idc: str, cluster_idc: str) -> float:
    if not peer_idc or not cluster_idc:
        return 0.0
    alternatives = [x.lower() for x in cluster_idc.split("|")]
    return 1.0 if peer_idc.lower() in alternatives else 0.0


def location_affinity(peer_location: str, cluster_location: str) -> float:
    if not peer_location or not cluster_location:
        return 0.0
    pe = peer_location.split("|")
    ce = cluster_location.split("|")
    n = min(len(pe), len(ce), MAX_LOCATION_ELEMENTS)
    score = 0
    for i in range(n):
        if pe[i].lower() != ce[i].lower():
            break
        score += 1
    return score / MAX_LOCATION_ELEMENTS


class Searcher:
    def find_matching_cluster(
        self, clusters: list[Cluster], peer: PeerInfo
    ) -> Cluster | None:
        if not clusters:
            return None
        return max(clusters, key=lambda c: self.score(c, peer))

    def score(self, cluster: Cluster, peer: PeerInfo) -> float:
        return (
            CIDR_AFFINITY_WEIGHT * cidr_affinity(peer.ip, cluster.scopes.cidrs)
            + IDC_AFFINITY_WEIGHT * idc_affinity(peer.idc, cluster.scopes.idc)
            + LOCATION_AFFINITY_WEIGHT
            * location_affinity(peer.location, cluster.scopes.location)
            + CLUSTER_TYPE_WEIGHT * (1.0 if cluster.is_default else 0.0)
        )


def new_searcher() -> "Searcher":
    """Factory with the plugin seam (reference manager/searcher uses
    dfplugin to swap the cluster-scoring algorithm)."""
    from dragonfly2_tpu.utils.dfplugin import registry

    plugin = registry.searcher()
    return plugin if plugin is not None else Searcher()
