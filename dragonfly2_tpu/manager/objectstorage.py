"""Object storage behind one interface (role parity: reference
pkg/objectstorage — S3/OSS drivers). The filesystem driver is the
in-cluster default here (no cloud credentials in this environment); the
interface is the S3 verb set so a real driver drops in."""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import Iterator, Protocol


class ObjectStorage(Protocol):
    def put_object(self, bucket: str, key: str, data: bytes) -> None: ...

    def get_object(self, bucket: str, key: str) -> bytes: ...

    def head_object(self, bucket: str, key: str) -> bool: ...

    def stat_object(self, bucket: str, key: str) -> int: ...

    def delete_object(self, bucket: str, key: str) -> None: ...

    def list_objects(self, bucket: str, prefix: str = "") -> list[str]: ...

    def create_bucket(self, bucket: str) -> None: ...


class FSObjectStorage:
    def __init__(self, root: str | Path):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, bucket: str, key: str = "") -> Path:
        p = (self.root / bucket / key).resolve()
        # component-wise check — a string-prefix test would accept sibling
        # dirs sharing the root's name as a prefix (/data/backend-x)
        if not p.is_relative_to(self.root.resolve()):
            raise ValueError(f"object key escapes storage root: {key}")
        return p

    def create_bucket(self, bucket: str) -> None:
        self._path(bucket).mkdir(parents=True, exist_ok=True)

    def put_object(self, bucket: str, key: str, data: bytes) -> None:
        p = self._path(bucket, key)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_suffix(p.suffix + ".tmp")
        tmp.write_bytes(data)
        tmp.replace(p)  # atomic publish

    def get_object(self, bucket: str, key: str) -> bytes:
        return self._path(bucket, key).read_bytes()

    def head_object(self, bucket: str, key: str) -> bool:
        return self._path(bucket, key).is_file()

    def stat_object(self, bucket: str, key: str) -> int:
        """Object size without reading the bytes."""
        return self._path(bucket, key).stat().st_size

    def delete_object(self, bucket: str, key: str) -> None:
        self._path(bucket, key).unlink(missing_ok=True)

    def list_objects(self, bucket: str, prefix: str = "") -> list[str]:
        base = self._path(bucket)
        if not base.exists():
            return []
        out = []
        for p in base.rglob("*"):
            if p.is_file() and not p.name.endswith(".tmp"):
                key = str(p.relative_to(base))
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def delete_bucket(self, bucket: str) -> None:
        shutil.rmtree(self._path(bucket), ignore_errors=True)
