"""Object storage behind one interface (role parity: reference
pkg/objectstorage — S3/OSS drivers). The filesystem driver is the
in-cluster default here (no cloud credentials in this environment); the
interface is the S3 verb set so a real driver drops in."""

from __future__ import annotations

import shutil
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from pathlib import Path
from typing import Iterator, Protocol

from dragonfly2_tpu.utils.awssig import sigv4_headers


class ObjectStorage(Protocol):
    def put_object(self, bucket: str, key: str, data: bytes) -> None: ...

    def get_object(self, bucket: str, key: str) -> bytes: ...

    def head_object(self, bucket: str, key: str) -> bool: ...

    def stat_object(self, bucket: str, key: str) -> int: ...

    def delete_object(self, bucket: str, key: str) -> None: ...

    def list_objects(self, bucket: str, prefix: str = "") -> list[str]: ...

    def create_bucket(self, bucket: str) -> None: ...


class FSObjectStorage:
    def __init__(self, root: str | Path):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, bucket: str, key: str = "") -> Path:
        p = (self.root / bucket / key).resolve()
        # component-wise check — a string-prefix test would accept sibling
        # dirs sharing the root's name as a prefix (/data/backend-x)
        if not p.is_relative_to(self.root.resolve()):
            raise ValueError(f"object key escapes storage root: {key}")
        return p

    def create_bucket(self, bucket: str) -> None:
        self._path(bucket).mkdir(parents=True, exist_ok=True)

    def put_object(self, bucket: str, key: str, data: bytes) -> None:
        p = self._path(bucket, key)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_suffix(p.suffix + ".tmp")
        tmp.write_bytes(data)
        tmp.replace(p)  # atomic publish

    def get_object(self, bucket: str, key: str) -> bytes:
        return self._path(bucket, key).read_bytes()

    def head_object(self, bucket: str, key: str) -> bool:
        return self._path(bucket, key).is_file()

    def stat_object(self, bucket: str, key: str) -> int:
        """Object size without reading the bytes."""
        return self._path(bucket, key).stat().st_size

    def delete_object(self, bucket: str, key: str) -> None:
        self._path(bucket, key).unlink(missing_ok=True)

    def list_objects(self, bucket: str, prefix: str = "") -> list[str]:
        base = self._path(bucket)
        if not base.exists():
            return []
        out = []
        for p in base.rglob("*"):
            if p.is_file() and not p.name.endswith(".tmp"):
                key = str(p.relative_to(base))
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def delete_bucket(self, bucket: str) -> None:
        shutil.rmtree(self._path(bucket), ignore_errors=True)

    def list_buckets(self) -> list[str]:
        return sorted(p.name for p in self.root.iterdir() if p.is_dir())


def _s3_error_code(e: "urllib.error.HTTPError") -> str:
    """<Code> from an S3/OSS XML error body ('' when unparsable)."""
    try:
        root = ET.fromstring(e.read())
        ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") else ""
        code = root.find(f"{ns}Code")
        return code.text or "" if code is not None else ""
    except Exception:
        return ""


class _HTTPObjectStorage:
    """Shared verb layer for REST object stores; subclasses provide the
    signed ``_request`` and the listing dialect. Missing objects surface
    as ``FileNotFoundError`` so both drivers are true drop-ins for
    ``FSObjectStorage`` behind the Protocol (the gateway maps that to
    HTTP 404)."""

    _scheme = "object"

    def __init__(self, endpoint: str, timeout: float = 30.0):
        if not endpoint:
            raise ValueError(f"{self._scheme} object storage needs an endpoint URL")
        self._e = urllib.parse.urlsplit(endpoint)
        self.timeout = timeout

    # subclasses implement: _request(method, bucket, key, query, data)
    # and the listing dialect hooks below.
    def _create_bucket_body(self) -> bytes:
        return b""

    def _list_query(self, prefix: str, token: str) -> dict:
        raise NotImplementedError

    def _list_next(self, root, ns: str) -> str:
        raise NotImplementedError

    # -- verbs ----------------------------------------------------------
    def create_bucket(self, bucket: str) -> None:
        try:
            with self._request("PUT", bucket, data=self._create_bucket_body() or None):
                pass
        except urllib.error.HTTPError as e:
            # only OUR existing bucket is success; a 409 for a bucket
            # owned by someone else must fail loudly now, not as
            # confusing 403s on the first put. Stores that return a
            # codeless 409 (our fakes, some MinIO setups) count as ours.
            code = _s3_error_code(e) if e.code == 409 else ""
            if e.code == 409 and code in ("", "BucketAlreadyOwnedByYou"):
                return
            raise

    def put_object(self, bucket: str, key: str, data: bytes) -> None:
        with self._request("PUT", bucket, key, data=data):
            pass

    def get_object(self, bucket: str, key: str) -> bytes:
        try:
            with self._request("GET", bucket, key) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise FileNotFoundError(f"{self._scheme}://{bucket}/{key}") from e
            raise

    def head_object(self, bucket: str, key: str) -> bool:
        try:
            with self._request("HEAD", bucket, key):
                return True
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return False
            raise

    def stat_object(self, bucket: str, key: str) -> int:
        try:
            with self._request("HEAD", bucket, key) as resp:
                return int(resp.headers.get("Content-Length", 0) or 0)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise FileNotFoundError(f"{self._scheme}://{bucket}/{key}") from e
            raise

    def delete_object(self, bucket: str, key: str) -> None:
        try:
            with self._request("DELETE", bucket, key):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:  # delete is idempotent, like the FS driver
                raise

    def list_objects(self, bucket: str, prefix: str = "") -> list[str]:
        """Paged listing; subclasses define the query/continuation dialect."""
        out: list[str] = []
        token = ""
        while True:
            # canonical query must be sorted AND percent-encoded the way
            # signatures canonicalize (quote, not quote_plus — a '+' for
            # space breaks verification server-side)
            query = urllib.parse.urlencode(
                sorted(self._list_query(prefix, token).items()),
                quote_via=urllib.parse.quote,
            )
            with self._request("GET", bucket, query=query) as resp:
                root = ET.fromstring(resp.read())
            ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") else ""
            for c in root.findall(f"{ns}Contents"):
                k = c.find(f"{ns}Key")
                if k is not None and k.text:
                    out.append(k.text)
            trunc = root.find(f"{ns}IsTruncated")
            if trunc is None or trunc.text != "true":
                break
            token = self._list_next(root, ns)
            if not token:
                break
        return sorted(out)

    def delete_bucket(self, bucket: str) -> None:
        try:
            with self._request("DELETE", bucket):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


class S3ObjectStorage(_HTTPObjectStorage):
    """S3-compatible driver over SigV4-signed REST (role parity:
    reference pkg/objectstorage s3 driver via aws-sdk) — endpoint-style
    addressing (``endpoint/bucket/key``), so MinIO/Ceph/R2-style
    S3-compatible stores work the same as AWS."""

    _scheme = "s3"

    def __init__(
        self,
        endpoint: str,
        access_key: str,
        secret_key: str,
        region: str = "us-east-1",
        timeout: float = 30.0,
    ):
        super().__init__(endpoint, timeout)
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    def _request(self, method: str, bucket: str, key: str = "", query: str = "",
                 data: bytes | None = None):
        path = f"/{bucket}" + (f"/{urllib.parse.quote(key)}" if key else "")
        headers = sigv4_headers(
            method, self._e.netloc, path, query,
            self.region, self.access_key, self.secret_key,
        )
        url = f"{self._e.scheme}://{self._e.netloc}{path}"
        if query:
            url = f"{url}?{query}"
        req = urllib.request.Request(url, method=method, headers=headers, data=data)
        return urllib.request.urlopen(req, timeout=self.timeout)

    def _create_bucket_body(self) -> bytes:
        # non-default regions need an explicit LocationConstraint body —
        # AWS rejects a bare PUT outside us-east-1
        if self.region == "us-east-1":
            return b""
        return (
            '<CreateBucketConfiguration xmlns='
            '"http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<LocationConstraint>{self.region}</LocationConstraint>"
            "</CreateBucketConfiguration>"
        ).encode()

    def _list_query(self, prefix: str, token: str) -> dict:
        q = {"list-type": "2"}
        if prefix:
            q["prefix"] = prefix
        if token:
            q["continuation-token"] = token
        return q

    def _list_next(self, root, ns: str) -> str:
        nxt = root.find(f"{ns}NextContinuationToken")
        return nxt.text if nxt is not None and nxt.text else ""


class OSSObjectStorage(_HTTPObjectStorage):
    """Alibaba OSS driver: classic header signature
    (``OSS <key>:<base64 hmac-sha1>``; role parity: reference
    pkg/objectstorage oss driver)."""

    _scheme = "oss"

    def __init__(
        self,
        endpoint: str,
        access_key: str,
        secret_key: str,
        timeout: float = 30.0,
    ):
        super().__init__(endpoint, timeout)
        self.access_key = access_key
        self.secret_key = secret_key

    def _request(self, method: str, bucket: str, key: str = "", query: str = "",
                 data: bytes | None = None):
        from dragonfly2_tpu.utils.awssig import oss_sign_headers

        # urllib force-adds a Content-Type to data-carrying requests, and
        # OSS signs Content-Type — so writers declare one explicitly and
        # it participates in the signature
        content_type = "application/octet-stream" if data is not None else ""
        headers = oss_sign_headers(
            method, bucket, key, self.access_key, self.secret_key,
            content_type=content_type,
        )
        path = f"/{bucket}" + (f"/{urllib.parse.quote(key)}" if key else "")
        url = f"{self._e.scheme}://{self._e.netloc}{path}"
        if query:
            url = f"{url}?{query}"
        req = urllib.request.Request(url, method=method, headers=headers, data=data)
        return urllib.request.urlopen(req, timeout=self.timeout)

    def _list_query(self, prefix: str, token: str) -> dict:
        q = {}
        if prefix:
            q["prefix"] = prefix
        if token:
            q["marker"] = token
        return q

    def _list_next(self, root, ns: str) -> str:
        nxt = root.find(f"{ns}NextMarker")
        return nxt.text if nxt is not None and nxt.text else ""


def new_object_storage(
    driver: str = "fs",
    root: str = "",
    endpoint: str = "",
    access_key: str = "",
    secret_key: str = "",
    region: str = "us-east-1",
) -> "ObjectStorage":
    """Driver factory (reference pkg/objectstorage New): ``fs`` (default),
    ``s3`` (any S3-compatible endpoint), or ``oss``."""
    if driver == "s3":
        return S3ObjectStorage(
            endpoint, access_key, secret_key, region=region
        )
    if driver == "oss":
        return OSSObjectStorage(endpoint, access_key, secret_key)
    if driver in ("", "fs"):
        return FSObjectStorage(root)
    raise ValueError(f"unknown object-storage driver {driver!r} (fs | s3 | oss)")
