"""Manager REST API (reference manager/router/router.go:269 +
manager/handlers/ + manager/service/): cluster / scheduler / seed-peer /
job / model / application CRUD over HTTP JSON, with bearer-token role
auth standing in for the reference's casbin RBAC (admin = full access,
guest = read-only; reference roles `root`/`guest`).

Stdlib http.server — the service plane needs no framework; the threaded
server handles the console/API concurrency a control plane sees. Model
activation flips versions through ModelRegistry.activate, the REST
equivalent of reference manager/service/model.go:109
updateModelStateToActive.
"""

from __future__ import annotations

import json
import re
import sqlite3
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dragonfly2_tpu.manager.service import ManagerService
from dragonfly2_tpu.utils import dflog

logger = dflog.get("manager.rest")

#                 (method, rx, fn, write, auth, raw pattern)
_ROUTES: list[tuple[str, re.Pattern, str, bool, bool, str]] = []


def route(method: str, pattern: str, write: bool = False, auth: bool = True):
    """``auth=False`` marks the route itself unauthenticated (health
    probes, credential-exchange legs) — a per-route flag, not a path
    prefix, so unrelated routes can never inherit the exemption."""
    # literal segments are escaped: a '.' in a pattern (openapi.json)
    # must match only itself, never any byte
    rx = re.compile(
        "^" + re.sub(r":(\w+)", r"(?P<\1>[^/]+)", re.escape(pattern)) + "$"
    )

    def wrap(fn):
        _ROUTES.append((method, rx, fn.__name__, write, auth, pattern))
        return fn

    return wrap


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class Redirect(Exception):
    """Handler outcome: 302 with a Location header (OAuth authorize leg,
    reference handlers/oauth.go OauthSignin → ctx.Redirect)."""

    def __init__(self, location: str):
        super().__init__(location)
        self.location = location


def _ttl_of(body: dict, default: float) -> float:
    """Validated token TTL from a request body: numeric, non-negative
    (0 = never expires for PATs; the session default applies a cap).
    A string or negative ttl is a client error, not a 500 and not an
    accidental forever-token."""
    raw = body.get("ttl", default)
    try:
        ttl = float(raw)
    except (TypeError, ValueError):
        raise ApiError(400, f"ttl must be a number of seconds, got {raw!r}")
    if ttl < 0:
        raise ApiError(400, "ttl must be >= 0")
    return ttl


class RestApi:
    """Route handlers; one instance per server, stateless per request."""

    def __init__(self, service: ManagerService):
        from dragonfly2_tpu.manager import auth as _auth

        self.service = service
        self.db = service.db
        self.models = service.models
        # OAuth CSRF-state HMAC key, persisted in the DB: the
        # redirect→callback round-trip survives restarts and works
        # across replicas sharing the database
        self.oauth_state_secret = _auth.state_secret(self.db)

    # -- OpenAPI (reference api/manager/docs.go generated swagger; here
    # the spec is derived live from the route table, so it can never
    # drift from the actual surface) -------------------------------------
    _openapi_cache: dict | None = None  # immutable after import; built once

    @route("GET", "/api/v1/openapi.json", auth=False)
    def openapi(self, req):
        if RestApi._openapi_cache is not None:
            return RestApi._openapi_cache
        paths: dict = {}
        for method, rx, fname, write, needs_auth, pattern in _ROUTES:
            oa_path = re.sub(r":(\w+)", r"{\1}", pattern)
            params = [
                {
                    "name": m.group(1),
                    "in": "path",
                    "required": True,
                    "schema": {"type": "string"},
                }
                for m in re.finditer(r":(\w+)", pattern)
            ]
            doc = (getattr(type(self), fname).__doc__ or "").strip().split("\n")[0]
            op = {
                "operationId": fname,
                "summary": doc or fname.replace("_", " "),
                "responses": {"200": {"description": "OK"}},
            }
            if params:
                op["parameters"] = params
            if needs_auth:
                op["security"] = [{"bearerAuth": []}]
                op["responses"]["401"] = {"description": "unauthenticated"}
            if write:
                op["responses"]["403"] = {"description": "requires the admin role"}
            if method in ("POST", "PATCH", "PUT"):
                op["requestBody"] = {
                    "content": {"application/json": {"schema": {"type": "object"}}}
                }
            paths.setdefault(oa_path, {})[method.lower()] = op
        RestApi._openapi_cache = {
            "openapi": "3.0.3",
            "info": {
                "title": "dragonfly2_tpu manager API",
                "version": "1",
                "description": "Derived from the live route table"
                " (reference api/manager swagger docs).",
            },
            "components": {
                "securitySchemes": {
                    "bearerAuth": {"type": "http", "scheme": "bearer"}
                }
            },
            "paths": paths,
        }
        return RestApi._openapi_cache

    # -- health ----------------------------------------------------------
    @route("GET", "/healthy", auth=False)
    def healthy(self, req):
        return {"status": "ok"}

    # -- cluster telemetry (manager/telemetry.py, docs/telemetry.md) -----
    @route("GET", "/api/v1/telemetry", auth=False)
    def get_telemetry(self, req):
        """Cluster-wide telemetry snapshot: per-service inventory, swarm
        table, per-shard/per-trainer windowed aggregates, SLO burn
        state. Unauthenticated like the health probes — it is the
        observability surface dfstat/dfdoctor poll."""
        plane = getattr(self.service, "telemetry", None)
        if plane is None:
            raise ApiError(503, "telemetry plane not enabled on this manager")
        return plane.snapshot()

    # -- scheduler clusters ----------------------------------------------
    @route("GET", "/api/v1/scheduler-clusters")
    def list_scheduler_clusters(self, req):
        return self.db.query("SELECT * FROM scheduler_clusters ORDER BY id")

    @route("POST", "/api/v1/scheduler-clusters", write=True)
    def create_scheduler_cluster(self, req):
        body = req["body"]
        name = body.get("name")
        if not name:
            raise ApiError(400, "name is required")
        now = time.time()
        cur = self.db.execute(
            "INSERT INTO scheduler_clusters (name, config, client_config, scopes,"
            " is_default, created_at, updated_at) VALUES (?, ?, ?, ?, ?, ?, ?)",
            (
                name,
                json.dumps(body.get("config", {})),
                json.dumps(body.get("client_config", {})),
                json.dumps(body.get("scopes", {})),
                1 if body.get("is_default") else 0,
                now,
                now,
            ),
        )
        return self.db.query_one(
            "SELECT * FROM scheduler_clusters WHERE id = ?", (cur.lastrowid,)
        )

    @route("GET", "/api/v1/scheduler-clusters/:id")
    def get_scheduler_cluster(self, req):
        row = self.db.query_one(
            "SELECT * FROM scheduler_clusters WHERE id = ?", (int(req["id"]),)
        )
        if row is None:
            raise ApiError(404, "scheduler cluster not found")
        return row

    @route("PATCH", "/api/v1/scheduler-clusters/:id", write=True)
    def update_scheduler_cluster(self, req):
        body = req["body"]
        sets, params = [], []
        for col in ("name", "config", "client_config", "scopes"):
            if col in body:
                v = body[col]
                sets.append(f"{col} = ?")
                params.append(v if isinstance(v, str) else json.dumps(v))
        if "is_default" in body:
            sets.append("is_default = ?")
            params.append(1 if body["is_default"] else 0)
        if not sets:
            raise ApiError(400, "no updatable fields in body")
        sets.append("updated_at = ?")
        params.append(time.time())
        params.append(int(req["id"]))
        self.db.execute(
            f"UPDATE scheduler_clusters SET {', '.join(sets)} WHERE id = ?",
            tuple(params),
        )
        return self.get_scheduler_cluster(req)

    @route("DELETE", "/api/v1/scheduler-clusters/:id", write=True)
    def delete_scheduler_cluster(self, req):
        self.db.execute(
            "DELETE FROM scheduler_clusters WHERE id = ?", (int(req["id"]),)
        )
        return {"deleted": int(req["id"])}

    # -- schedulers ------------------------------------------------------
    @route("GET", "/api/v1/schedulers")
    def list_schedulers(self, req):
        return self.db.query("SELECT * FROM schedulers ORDER BY id")

    @route("GET", "/api/v1/schedulers/:id")
    def get_scheduler(self, req):
        row = self.db.query_one(
            "SELECT * FROM schedulers WHERE id = ?", (int(req["id"]),)
        )
        if row is None:
            raise ApiError(404, "scheduler not found")
        return row

    @route("DELETE", "/api/v1/schedulers/:id", write=True)
    def delete_scheduler(self, req):
        self.db.execute("DELETE FROM schedulers WHERE id = ?", (int(req["id"]),))
        return {"deleted": int(req["id"])}

    # -- seed peers ------------------------------------------------------
    @route("GET", "/api/v1/seed-peers")
    def list_seed_peers(self, req):
        return self.db.query("SELECT * FROM seed_peers ORDER BY id")

    @route("GET", "/api/v1/seed-peers/:id")
    def get_seed_peer(self, req):
        row = self.db.query_one(
            "SELECT * FROM seed_peers WHERE id = ?", (int(req["id"]),)
        )
        if row is None:
            raise ApiError(404, "seed peer not found")
        return row

    # -- jobs (preheat etc.) --------------------------------------------
    @route("GET", "/api/v1/jobs")
    def list_jobs(self, req):
        return self.db.query("SELECT * FROM jobs ORDER BY id DESC LIMIT 100")

    @route("POST", "/api/v1/jobs", write=True)
    def create_job(self, req):
        """One cluster → one job row. ``scheduler_cluster_ids`` (a list)
        fans the job to every named cluster under a shared group id
        (reference manager/job createGroupJob over machinery groups);
        the group's aggregate state lives at /jobs/groups/:group_id."""
        body = req["body"]
        jtype = body.get("type")
        if not jtype:
            raise ApiError(400, "type is required")
        raw_clusters = body.get("scheduler_cluster_ids")
        grouped = raw_clusters is not None
        if grouped:
            if not isinstance(raw_clusters, list) or not raw_clusters:
                raise ApiError(400, "scheduler_cluster_ids must be a non-empty list")
        else:
            raw_clusters = [body.get("scheduler_cluster_id", 0)]
        # validate EVERY id before the first insert — execute() commits
        # per statement, so a mid-loop error would leave orphaned queued
        # jobs the caller can neither track nor cancel
        try:
            # id 0 = unspecified → the default cluster (matching gRPC
            # CreateJob); a literal 0 would dead-letter the job — no
            # worker ever leases cluster 0
            clusters = [
                int(c) or self.service.default_cluster_id for c in raw_clusters
            ]
        except (TypeError, ValueError):
            raise ApiError(400, f"non-numeric scheduler cluster id in {raw_clusters!r}")
        import uuid

        # the list form ALWAYS gets a group (a 1-element list is still
        # the group contract — callers poll /jobs/groups/:group_id)
        group_id = uuid.uuid4().hex if grouped else ""
        now = time.time()
        rows = []
        args = json.dumps(body.get("args", {}))
        for cid in clusters:
            cur = self.db.execute(
                "INSERT INTO jobs (type, state, args, scheduler_cluster_id,"
                " group_id, created_at, updated_at) VALUES (?, 'queued', ?, ?, ?, ?, ?)",
                (jtype, args, cid, group_id, now, now),
            )
            rows.append(
                self.db.query_one("SELECT * FROM jobs WHERE id = ?", (cur.lastrowid,))
            )
        if grouped:
            return {"group_id": group_id, "state": "queued", "jobs": rows}
        return rows[0]

    @route("GET", "/api/v1/jobs/groups/:group_id")
    def get_job_group(self, req):
        """Aggregate group state (reference machinery group semantics):
        failed if ANY member failed, succeeded when ALL succeeded,
        running if any is running, else queued."""
        rows = self.db.query(
            "SELECT * FROM jobs WHERE group_id = ? ORDER BY id", (req["group_id"],)
        )
        if not rows:
            raise ApiError(404, "job group not found")
        states = {r["state"] for r in rows}
        if "failed" in states:
            agg = "failed"
        elif states == {"succeeded"}:
            agg = "succeeded"
        elif "running" in states:
            agg = "running"
        else:
            agg = "queued"
        return {"group_id": req["group_id"], "state": agg, "jobs": rows}

    @route("GET", "/api/v1/jobs/:id")
    def get_job(self, req):
        row = self.db.query_one("SELECT * FROM jobs WHERE id = ?", (int(req["id"]),))
        if row is None:
            raise ApiError(404, "job not found")
        return row

    # -- models (registry + activation) ----------------------------------
    @route("GET", "/api/v1/models")
    def list_models(self, req):
        cluster = req["query"].get("scheduler_cluster_id")
        rows = self.models.list(int(cluster) if cluster else None)
        return [vars(r) for r in rows]

    @route("GET", "/api/v1/models/:model_id/versions/:version")
    def get_model(self, req):
        row = self.models.get(req["model_id"], int(req["version"]))
        if row is None:
            raise ApiError(404, "model not found")
        return vars(row)

    @route("PUT", "/api/v1/models/:model_id/versions/:version/state", write=True)
    def update_model_state(self, req):
        state = req["body"].get("state")
        if state not in ("active", "inactive"):
            raise ApiError(400, "state must be 'active' or 'inactive'")
        model_id, version = req["model_id"], int(req["version"])
        try:
            if state == "active":
                row = self.models.activate(model_id, version)
            else:
                row = self.models.deactivate(model_id, version)
        except KeyError:
            raise ApiError(404, "model not found")
        return vars(row)

    @route("DELETE", "/api/v1/models/:model_id/versions/:version", write=True)
    def delete_model(self, req):
        self.models.delete(req["model_id"], int(req["version"]))
        return {"deleted": req["model_id"], "version": int(req["version"])}

    # -- users + personal access tokens (reference manager/handlers
    # users.go / personal_access_tokens.go; roles stand in for casbin) ---
    @route("GET", "/api/v1/users")
    def list_users(self, req):
        return self.db.query(
            "SELECT id, name, email, role, state, created_at, updated_at"
            " FROM users ORDER BY id"
        )

    @route("POST", "/api/v1/users", write=True)
    def create_user(self, req):
        from dragonfly2_tpu.manager import auth

        body = req["body"]
        try:
            row = auth.create_user(
                self.db,
                body.get("name", ""),
                body.get("password", ""),
                role=body.get("role", "guest"),
                email=body.get("email", ""),
            )
        except ValueError as e:
            raise ApiError(400, str(e))
        return {k: v for k, v in row.items() if not k.startswith("password")}

    @route("PATCH", "/api/v1/users/:id", write=True)
    def update_user(self, req):
        body = req["body"]
        sets, params = [], []
        if "role" in body:
            from dragonfly2_tpu.manager.auth import ROLES

            if body["role"] not in ROLES:
                raise ApiError(400, f"role must be one of {ROLES}")
            sets.append("role = ?")
            params.append(body["role"])
        if "state" in body:
            if body["state"] not in ("enabled", "disabled"):
                raise ApiError(400, "state must be 'enabled' or 'disabled'")
            sets.append("state = ?")
            params.append(body["state"])
        if not sets:
            raise ApiError(400, "no updatable fields in body")
        sets.append("updated_at = ?")
        params += [time.time(), int(req["id"])]
        self.db.execute(f"UPDATE users SET {', '.join(sets)} WHERE id = ?", tuple(params))
        row = self.db.query_one(
            "SELECT id, name, email, role, state FROM users WHERE id = ?",
            (int(req["id"]),),
        )
        if row is None:
            raise ApiError(404, "user not found")
        return row

    @route("POST", "/api/v1/users/signin", auth=False)
    def signin(self, req):
        """Password → short-lived session token (the console's login;
        reference issues a session JWT — here a TTL'd PAT)."""
        from dragonfly2_tpu.manager import auth

        body = req["body"]
        user = auth.verify_password(
            self.db, body.get("name", ""), body.get("password", "")
        )
        if user is None:
            raise ApiError(401, "bad credentials")
        # session TTLs are CAPPED: ttl=0 on the unauthenticated signin
        # route must not mint an immortal credential (never-expiring
        # tokens stay exclusive to the admin-gated PAT route)
        ttl = _ttl_of(body, default=24 * 3600.0)
        ttl = min(ttl or 24 * 3600.0, 30 * 24 * 3600.0)
        token, _ = auth.create_pat(self.db, user["id"], "session", ttl=ttl)
        return {"token": token, "role": user["role"]}

    def _require_admin_or_self(self, req, user_id: int) -> None:
        """Token metadata is a credential inventory: only an admin or
        the user who owns it may read it (reference casbin policy scopes
        the nested PAT group to the token's subject). The caller's id
        was resolved once with the role (dispatcher _auth_info)."""
        if req["auth_role"] == "admin":
            return
        if req.get("auth_user_id") is not None and req["auth_user_id"] == user_id:
            return
        raise ApiError(403, "forbidden (admin or resource owner only)")

    @route("GET", "/api/v1/users/:id/personal-access-tokens")
    def list_pats(self, req):
        self._require_admin_or_self(req, int(req["id"]))
        return self.db.query(
            "SELECT id, user_id, name, state, expires_at, created_at"
            " FROM personal_access_tokens WHERE user_id = ? ORDER BY id",
            (int(req["id"]),),
        )

    @route("POST", "/api/v1/users/:id/personal-access-tokens", write=True)
    def create_pat(self, req):
        from dragonfly2_tpu.manager import auth

        user = self.db.query_one(
            "SELECT id FROM users WHERE id = ?", (int(req["id"]),)
        )
        if user is None:
            raise ApiError(404, "user not found")
        token, row = auth.create_pat(
            self.db,
            user["id"],
            req["body"].get("name", "token"),
            ttl=_ttl_of(req["body"], default=0.0),
        )
        # plaintext returned exactly once; only the hash is stored
        return {"token": token, "id": row["id"], "name": row["name"]}

    @route("DELETE", "/api/v1/users/:id/personal-access-tokens/:pat_id", write=True)
    def revoke_pat(self, req):
        from dragonfly2_tpu.manager import auth

        auth.revoke_pat(self.db, int(req["pat_id"]))
        return {"revoked": int(req["pat_id"])}

    # -- user lifecycle: signup / signout / refresh / reset ---------------
    # (reference router.go:97-111; self-service legs are auth=False like
    # signin — they exchange credentials, they don't consume a session)
    @route("POST", "/api/v1/users/signup", auth=False)
    def signup(self, req):
        """Self-service registration — always the guest role (an open
        route must never mint admins; promotion is an admin PATCH,
        reference SignUp creates a regular user the same way)."""
        from dragonfly2_tpu.manager import auth

        body = req["body"]
        try:
            row = auth.create_user(
                self.db,
                body.get("name", ""),
                body.get("password", ""),
                role="guest",
                email=body.get("email", ""),
            )
        except ValueError as e:
            raise ApiError(400, str(e))
        return {k: v for k, v in row.items() if not k.startswith("password")}

    @route("POST", "/api/v1/users/signout")
    def signout(self, req):
        """Revoke the presenting session token (reference LogoutHandler)."""
        from dragonfly2_tpu.manager import auth

        if not req["token"]:
            raise ApiError(400, "no bearer token to sign out")
        if not auth.revoke_pats_for_token(self.db, req["token"]):
            # config-file tokens aren't DB rows — nothing to revoke
            raise ApiError(400, "token is not a revocable session token")
        return {"signed_out": True}

    @route("POST", "/api/v1/users/refresh_token")
    def refresh_token(self, req):
        """Rotate the presenting session token: mint a fresh one with the
        same ownership, revoke the old (reference RefreshHandler extends
        the JWT; rotation is the PAT-shaped equivalent)."""
        from dragonfly2_tpu.manager import auth

        if not req["token"]:
            raise ApiError(400, "no bearer token to refresh")
        row = self.db.query_one(
            "SELECT * FROM personal_access_tokens WHERE token_hash = ?"
            " AND state = 'active'",
            (auth._hash_token(req["token"]),),
        )
        if row is None:
            raise ApiError(400, "token is not a refreshable session token")
        ttl = min(_ttl_of(req["body"], default=24 * 3600.0) or 24 * 3600.0,
                  30 * 24 * 3600.0)
        token, _ = auth.create_pat(self.db, row["user_id"], row["name"], ttl=ttl)
        auth.revoke_pat(self.db, row["id"])
        return {"token": token}

    @route("POST", "/api/v1/users/:id/reset_password", auth=False)
    def reset_password(self, req):
        """Credential exchange: proves the OLD password, stores a new one
        (reference ResetPassword — unauthenticated route, router.go:107,
        gated by the credential itself)."""
        from dragonfly2_tpu.manager import auth

        body = req["body"]
        user = self.db.query_one(
            "SELECT * FROM users WHERE id = ?", (int(req["id"]),)
        )
        if user is None:
            raise ApiError(404, "user not found")
        verified = auth.verify_password(
            self.db, user["name"], body.get("old_password", "")
        )
        if verified is None:
            raise ApiError(401, "old password incorrect")
        try:
            auth.set_password(self.db, user["id"], body.get("new_password", ""))
        except ValueError as e:
            raise ApiError(400, str(e))
        return {"reset": user["id"]}

    # -- roles / permissions (read surface of the two-role model — the
    # casbin delta is documented in PARITY.md; reference router.go:108-124)
    @route("GET", "/api/v1/roles")
    def list_roles(self, req):
        from dragonfly2_tpu.manager.auth import ROLES

        return list(ROLES)

    @route("GET", "/api/v1/roles/:role")
    def get_role(self, req):
        from dragonfly2_tpu.manager.auth import ROLES

        if req["role"] not in ROLES:
            raise ApiError(404, f"no role {req['role']!r}")
        writable = req["role"] == "admin"
        return {
            "name": req["role"],
            "permissions": [
                {"object": pattern, "action": method}
                for method, _rx, _f, write, _a, pattern in _ROUTES
                if writable or not write
            ],
        }

    @route("GET", "/api/v1/permissions")
    def list_permissions(self, req):
        """Route-derived permission objects (reference GetPermissions
        walks the gin route table the same way)."""
        pairs = sorted(
            {(pattern, method) for method, _rx, _f, _w, _a, pattern in _ROUTES}
        )
        return [{"object": p, "action": m} for p, m in pairs]

    @route("GET", "/api/v1/users/:id/roles")
    def get_user_roles(self, req):
        row = self.db.query_one(
            "SELECT role FROM users WHERE id = ?", (int(req["id"]),)
        )
        if row is None:
            raise ApiError(404, "user not found")
        return [row["role"]]

    @route("PUT", "/api/v1/users/:id/roles/:role", write=True)
    def add_user_role(self, req):
        """Two-role model: PUT admin promotes, PUT guest demotes —
        role assignment IS the role field."""
        from dragonfly2_tpu.manager.auth import ROLES

        if req["role"] not in ROLES:
            raise ApiError(400, f"role must be one of {ROLES}")
        cur = self.db.execute(
            "UPDATE users SET role = ?, updated_at = ? WHERE id = ?",
            (req["role"], time.time(), int(req["id"])),
        )
        if cur.rowcount == 0:
            raise ApiError(404, "user not found")
        return {"id": int(req["id"]), "role": req["role"]}

    @route("DELETE", "/api/v1/users/:id/roles/:role", write=True)
    def delete_user_role(self, req):
        """Removing a role falls back to guest (the floor role)."""
        cur = self.db.execute(
            "UPDATE users SET role = 'guest', updated_at = ? WHERE id = ? AND role = ?",
            (time.time(), int(req["id"]), req["role"]),
        )
        if cur.rowcount == 0:
            raise ApiError(404, "user not found or does not hold that role")
        return {"id": int(req["id"]), "role": "guest"}

    # -- top-level personal-access-tokens group (reference router.go:254-260;
    # the per-user nested group above is the console's path)
    @route("GET", "/api/v1/personal-access-tokens")
    def list_all_pats(self, req):
        """Admin-only: the cross-user token inventory would otherwise
        let any guest enumerate every user's credential metadata."""
        if req["auth_role"] != "admin":
            raise ApiError(403, "forbidden (requires the admin role)")
        return self.db.query(
            "SELECT id, user_id, name, state, expires_at, created_at"
            " FROM personal_access_tokens ORDER BY id"
        )

    @route("GET", "/api/v1/personal-access-tokens/:id")
    def get_pat(self, req):
        row = self.db.query_one(
            "SELECT id, user_id, name, state, expires_at, created_at"
            " FROM personal_access_tokens WHERE id = ?",
            (int(req["id"]),),
        )
        # existence is leaked only to admins too: 403 before 404 for
        # guests, so token ids can't be probed
        if req["auth_role"] != "admin":
            uid = req.get("auth_user_id")
            if row is None or uid is None or int(row["user_id"]) != uid:
                raise ApiError(403, "forbidden (admin or resource owner only)")
        if row is None:
            raise ApiError(404, "personal access token not found")
        return row

    @route("POST", "/api/v1/personal-access-tokens", write=True)
    def create_pat_toplevel(self, req):
        from dragonfly2_tpu.manager import auth

        body = req["body"]
        user_id = body.get("user_id")
        if not user_id:
            raise ApiError(400, "user_id is required")
        if self.db.query_one("SELECT id FROM users WHERE id = ?", (int(user_id),)) is None:
            raise ApiError(404, "user not found")
        token, row = auth.create_pat(
            self.db, int(user_id), body.get("name", "token"),
            ttl=_ttl_of(body, default=0.0),
        )
        return {"token": token, "id": row["id"], "name": row["name"]}

    @route("PATCH", "/api/v1/personal-access-tokens/:id", write=True)
    def update_pat(self, req):
        state = req["body"].get("state")
        if state not in ("active", "inactive"):
            raise ApiError(400, "state must be 'active' or 'inactive'")
        cur = self.db.execute(
            "UPDATE personal_access_tokens SET state = ? WHERE id = ?"
            " AND state != 'revoked'",
            (state, int(req["id"])),
        )
        if cur.rowcount == 0:
            raise ApiError(404, "token not found or revoked")
        return {"id": int(req["id"]), "state": state}

    @route("DELETE", "/api/v1/personal-access-tokens/:id", write=True)
    def delete_pat_toplevel(self, req):
        from dragonfly2_tpu.manager import auth

        auth.revoke_pat(self.db, int(req["id"]))
        return {"revoked": int(req["id"])}

    # -- applications ----------------------------------------------------
    # -- oauth providers + sign-in flow ---------------------------------
    # (reference manager/handlers/oauth.go CRUD + OauthSignin/Callback)
    _OAUTH_PUBLIC = ("id", "name", "bio", "client_id", "redirect_url",
                     "auth_url", "scopes", "created_at", "updated_at")

    def _oauth_row(self, ident: str) -> dict:
        # numeric → by id only; else by name — a provider NAMED like
        # another provider's id must never be resolved (or deleted) in
        # its place (same rule as get_config)
        if ident.isdigit():
            row = self.db.query_one("SELECT * FROM oauth WHERE id = ?", (int(ident),))
        else:
            row = self.db.query_one("SELECT * FROM oauth WHERE name = ?", (ident,))
        if row is None:
            raise ApiError(404, f"no oauth provider {ident!r}")
        return row

    def _oauth_public(self, row: dict) -> dict:
        # client_secret and token/userinfo endpoints stay server-side
        return {k: row[k] for k in self._OAUTH_PUBLIC if k in row}

    @route("GET", "/api/v1/oauth")
    def list_oauth(self, req):
        return [self._oauth_public(r) for r in self.db.query("SELECT * FROM oauth ORDER BY id")]

    @route("POST", "/api/v1/oauth", write=True)
    def create_oauth(self, req):
        body = req["body"]
        for field in ("name", "client_id", "client_secret", "auth_url",
                      "token_url", "userinfo_url"):
            if not body.get(field):
                raise ApiError(400, f"{field} is required")
        now = time.time()
        cur = self.db.execute(
            "INSERT INTO oauth (name, bio, client_id, client_secret,"
            " redirect_url, auth_url, token_url, userinfo_url, scopes,"
            " created_at, updated_at) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
            (
                body["name"], body.get("bio", ""), body["client_id"],
                body["client_secret"], body.get("redirect_url", ""),
                body["auth_url"], body["token_url"], body["userinfo_url"],
                body.get("scopes", ""), now, now,
            ),
        )
        return self._oauth_public(
            self.db.query_one("SELECT * FROM oauth WHERE id = ?", (cur.lastrowid,))
        )

    @route("GET", "/api/v1/oauth/:id")
    def get_oauth(self, req):
        return self._oauth_public(self._oauth_row(req["id"]))

    @route("PATCH", "/api/v1/oauth/:id", write=True)
    def update_oauth(self, req):
        row = self._oauth_row(req["id"])
        body = req["body"]
        fields = ("name", "bio", "client_id", "client_secret", "redirect_url",
                  "auth_url", "token_url", "userinfo_url", "scopes")
        updates = {k: body[k] for k in fields if k in body}
        if updates:
            sets = ", ".join(f"{k} = ?" for k in updates)
            self.db.execute(
                f"UPDATE oauth SET {sets}, updated_at = ? WHERE id = ?",
                (*updates.values(), time.time(), row["id"]),
            )
        return self._oauth_public(self._oauth_row(str(row["id"])))

    @route("DELETE", "/api/v1/oauth/:id", write=True)
    def delete_oauth(self, req):
        row = self._oauth_row(req["id"])
        self.db.execute("DELETE FROM oauth WHERE id = ?", (row["id"],))
        return {"deleted": row["id"]}

    @route("GET", "/api/v1/users/signin/:name", auth=False)
    def oauth_signin_redirect(self, req):
        from dragonfly2_tpu.manager import auth

        provider = self._oauth_row(req["name"])
        state = auth.sign_state(self.oauth_state_secret, provider["name"])
        raise Redirect(auth.oauth_authorize_url(provider, state))

    @route("GET", "/api/v1/users/signin/:name/callback", auth=False)
    def oauth_signin_callback(self, req):
        from dragonfly2_tpu.manager import auth

        provider = self._oauth_row(req["name"])
        code = req["query"].get("code", "")
        state = req["query"].get("state", "")
        if not code:
            raise ApiError(400, "missing code")
        if not auth.verify_state(self.oauth_state_secret, state, provider["name"]):
            raise ApiError(403, "state verification failed")
        try:
            token, user = auth.oauth_signin(self.db, provider, code)
        except ValueError as e:
            raise ApiError(401, str(e))
        return {
            "token": token,
            "user": {k: user[k] for k in ("id", "name", "email", "role")},
        }

    # -- peers (reference handlers/peer.go; rows materialized from
    # sync_peers job results) -------------------------------------------
    @route("POST", "/api/v1/peers", write=True)
    def create_peer(self, req):
        """Manual peer row (reference CreatePeer — rows normally arrive
        via the sync_peers job; the write exists for operator tooling)."""
        body = req["body"]
        if not body.get("host_id"):
            raise ApiError(400, "host_id is required")
        now = time.time()
        cur = self.db.execute(
            "INSERT INTO peers (host_id, hostname, ip, type, state,"
            " scheduler_cluster_id, created_at, updated_at)"
            " VALUES (?, ?, ?, ?, 'active', ?, ?, ?)",
            (
                body["host_id"], body.get("hostname", ""), body.get("ip", ""),
                body.get("type", "normal"),
                int(body.get("scheduler_cluster_id", 1)), now, now,
            ),
        )
        return self.db.query_one("SELECT * FROM peers WHERE id = ?", (cur.lastrowid,))

    @route("GET", "/api/v1/peers")
    def list_peers(self, req):
        q = "SELECT * FROM peers"
        params: tuple = ()
        if req["query"].get("scheduler_cluster_id"):
            q += " WHERE scheduler_cluster_id = ?"
            params = (int(req["query"]["scheduler_cluster_id"]),)
        return self.db.query(q + " ORDER BY id", params)

    @route("GET", "/api/v1/peers/:id")
    def get_peer(self, req):
        row = self.db.query_one("SELECT * FROM peers WHERE id = ?", (int(req["id"]),))
        if row is None:
            raise ApiError(404, "peer not found")
        return row

    @route("DELETE", "/api/v1/peers/:id", write=True)
    def delete_peer(self, req):
        self.db.execute("DELETE FROM peers WHERE id = ?", (int(req["id"]),))
        return {"deleted": int(req["id"])}

    # -- configs (reference handlers/config.go: named config rows) ------
    @route("GET", "/api/v1/configs")
    def list_configs(self, req):
        return self.db.query("SELECT * FROM configs ORDER BY id")

    @staticmethod
    def _config_text(v) -> str:
        # structured values stored as JSON (like cluster config fields),
        # scalars as plain text — never Python repr
        return json.dumps(v) if isinstance(v, (dict, list)) else str(v)

    @route("POST", "/api/v1/configs", write=True)
    def create_config(self, req):
        body = req["body"]
        if not body.get("name") or not isinstance(body["name"], str):
            raise ApiError(400, "name is required")
        now = time.time()
        cur = self.db.execute(
            "INSERT INTO configs (name, value, bio, created_at, updated_at)"
            " VALUES (?, ?, ?, ?, ?)",
            (
                body["name"],
                self._config_text(body.get("value", "")),
                self._config_text(body.get("bio", "")),
                now,
                now,
            ),
        )
        return self.db.query_one("SELECT * FROM configs WHERE id = ?", (cur.lastrowid,))

    @route("GET", "/api/v1/configs/:id")
    def get_config(self, req):
        # numeric path param addresses by id, anything else by name —
        # never both at once (an id-lookup must not resolve some OTHER
        # row whose name happens to be that number)
        ident = req["id"]
        if ident.isdigit():
            row = self.db.query_one("SELECT * FROM configs WHERE id = ?", (int(ident),))
        else:
            row = self.db.query_one("SELECT * FROM configs WHERE name = ?", (ident,))
        if row is None:
            raise ApiError(404, "config not found")
        return row

    @route("PATCH", "/api/v1/configs/:id", write=True)
    def update_config(self, req):
        row = self.get_config(req)
        body = req["body"]
        if "name" in body and (not body["name"] or not isinstance(body["name"], str)):
            raise ApiError(400, "name cannot be empty")
        updates = {
            k: self._config_text(body[k]) for k in ("name", "value", "bio") if k in body
        }
        if updates:
            sets = ", ".join(f"{k} = ?" for k in updates)
            self.db.execute(
                f"UPDATE configs SET {sets}, updated_at = ? WHERE id = ?",
                (*updates.values(), time.time(), row["id"]),
            )
        return self.db.query_one("SELECT * FROM configs WHERE id = ?", (row["id"],))

    @route("DELETE", "/api/v1/configs/:id", write=True)
    def delete_config(self, req):
        row = self.get_config(req)
        self.db.execute("DELETE FROM configs WHERE id = ?", (row["id"],))
        return {"deleted": row["id"]}

    # -- buckets (reference handlers/bucket.go over pkg/objectstorage) --
    @route("GET", "/api/v1/buckets")
    def list_buckets(self, req):
        storage = self.models.storage
        if not hasattr(storage, "list_buckets"):
            raise ApiError(501, "bucket listing unsupported by this storage driver")
        return [{"name": b} for b in storage.list_buckets()]

    @route("POST", "/api/v1/buckets", write=True)
    def create_bucket(self, req):
        name = req["body"].get("name", "")
        if not isinstance(name, str) or not name or "/" in name or name.startswith("."):
            raise ApiError(400, "a bucket needs a plain name")
        self.models.storage.create_bucket(name)
        return {"name": name}

    @route("GET", "/api/v1/buckets/:name")
    def get_bucket(self, req):
        storage = self.models.storage
        if hasattr(storage, "list_buckets") and req["name"] not in storage.list_buckets():
            raise ApiError(404, "bucket not found")
        try:
            objects = len(storage.list_objects(req["name"]))
        except Exception:
            # drivers without list_buckets (S3/OSS) surface a missing
            # bucket here — that's a 404, not a server fault
            raise ApiError(404, "bucket not found")
        return {"name": req["name"], "objects": objects}

    @route("DELETE", "/api/v1/buckets/:name", write=True)
    def delete_bucket(self, req):
        storage = self.models.storage
        if not hasattr(storage, "delete_bucket"):
            raise ApiError(501, "bucket deletion unsupported by this storage driver")
        storage.delete_bucket(req["name"])
        return {"deleted": req["name"]}

    @route("GET", "/api/v1/applications")
    def list_applications(self, req):
        return self.db.query("SELECT * FROM applications ORDER BY id")

    @route("POST", "/api/v1/applications", write=True)
    def create_application(self, req):
        body = req["body"]
        if not body.get("name"):
            raise ApiError(400, "name is required")
        now = time.time()
        cur = self.db.execute(
            "INSERT INTO applications (name, url, priority, created_at, updated_at)"
            " VALUES (?, ?, ?, ?, ?)",
            (
                body["name"],
                body.get("url", ""),
                json.dumps(body.get("priority", {})),
                now,
                now,
            ),
        )
        return self.db.query_one(
            "SELECT * FROM applications WHERE id = ?", (cur.lastrowid,)
        )

    @route("GET", "/api/v1/applications/:id")
    def get_application(self, req):
        row = self.db.query_one(
            "SELECT * FROM applications WHERE id = ?", (int(req["id"]),)
        )
        if row is None:
            raise ApiError(404, "application not found")
        return row

    @route("PATCH", "/api/v1/applications/:id", write=True)
    def update_application(self, req):
        body = req["body"]
        sets, params = [], []
        for col in ("name", "url"):
            if col in body:
                sets.append(f"{col} = ?")
                params.append(body[col])
        if "priority" in body:
            sets.append("priority = ?")
            v = body["priority"]
            params.append(v if isinstance(v, str) else json.dumps(v))
        if not sets:
            raise ApiError(400, "no updatable fields in body")
        sets.append("updated_at = ?")
        params += [time.time(), int(req["id"])]
        cur = self.db.execute(
            f"UPDATE applications SET {', '.join(sets)} WHERE id = ?", tuple(params)
        )
        if cur.rowcount == 0:
            raise ApiError(404, "application not found")
        return self.get_application(req)

    @route("DELETE", "/api/v1/applications/:id", write=True)
    def delete_application(self, req):
        self.db.execute("DELETE FROM applications WHERE id = ?", (int(req["id"]),))
        return {"deleted": int(req["id"])}

    # -- seed-peer clusters (reference router.go:159-168) -----------------
    @route("GET", "/api/v1/seed-peer-clusters")
    def list_seed_peer_clusters(self, req):
        return self.db.query("SELECT * FROM seed_peer_clusters ORDER BY id")

    @route("POST", "/api/v1/seed-peer-clusters", write=True)
    def create_seed_peer_cluster(self, req):
        body = req["body"]
        if not body.get("name"):
            raise ApiError(400, "name is required")
        now = time.time()
        cur = self.db.execute(
            "INSERT INTO seed_peer_clusters (name, config, created_at, updated_at)"
            " VALUES (?, ?, ?, ?)",
            (
                body["name"],
                json.dumps(body.get("config", {})),
                now,
                now,
            ),
        )
        return self.db.query_one(
            "SELECT * FROM seed_peer_clusters WHERE id = ?", (cur.lastrowid,)
        )

    @route("GET", "/api/v1/seed-peer-clusters/:id")
    def get_seed_peer_cluster(self, req):
        row = self.db.query_one(
            "SELECT * FROM seed_peer_clusters WHERE id = ?", (int(req["id"]),)
        )
        if row is None:
            raise ApiError(404, "seed peer cluster not found")
        return row

    @route("PATCH", "/api/v1/seed-peer-clusters/:id", write=True)
    def update_seed_peer_cluster(self, req):
        body = req["body"]
        sets, params = [], []
        if "name" in body:
            sets.append("name = ?")
            params.append(body["name"])
        if "config" in body:
            v = body["config"]
            sets.append("config = ?")
            params.append(v if isinstance(v, str) else json.dumps(v))
        if not sets:
            raise ApiError(400, "no updatable fields in body")
        sets.append("updated_at = ?")
        params += [time.time(), int(req["id"])]
        cur = self.db.execute(
            f"UPDATE seed_peer_clusters SET {', '.join(sets)} WHERE id = ?",
            tuple(params),
        )
        if cur.rowcount == 0:
            raise ApiError(404, "seed peer cluster not found")
        return self.get_seed_peer_cluster(req)

    @route("DELETE", "/api/v1/seed-peer-clusters/:id", write=True)
    def delete_seed_peer_cluster(self, req):
        self.db.execute(
            "DELETE FROM seed_peer_clusters WHERE id = ?", (int(req["id"]),)
        )
        return {"deleted": int(req["id"])}

    @route("PUT", "/api/v1/seed-peer-clusters/:id/seed-peers/:seed_peer_id", write=True)
    def add_seed_peer_to_cluster(self, req):
        """Re-home a seed peer into a cluster (reference
        AddSeedPeerToSeedPeerCluster)."""
        if self.db.query_one(
            "SELECT id FROM seed_peer_clusters WHERE id = ?", (int(req["id"]),)
        ) is None:
            raise ApiError(404, "seed peer cluster not found")
        cur = self.db.execute(
            "UPDATE seed_peers SET seed_peer_cluster_id = ?, updated_at = ?"
            " WHERE id = ?",
            (int(req["id"]), time.time(), int(req["seed_peer_id"])),
        )
        if cur.rowcount == 0:
            raise ApiError(404, "seed peer not found")
        return {"seed_peer_cluster_id": int(req["id"]),
                "seed_peer_id": int(req["seed_peer_id"])}

    # -- users read (reference GetUser, router.go:99)
    @route("GET", "/api/v1/users/:id")
    def get_user(self, req):
        row = self.db.query_one(
            "SELECT id, name, email, role, state, created_at, updated_at"
            " FROM users WHERE id = ?",
            (int(req["id"]),),
        )
        if row is None:
            raise ApiError(404, "user not found")
        return row

    # -- jobs PATCH/DELETE (reference router.go:202-203)
    @route("PATCH", "/api/v1/jobs/:id", write=True)
    def update_job(self, req):
        body = req["body"]
        sets, params = [], []
        if "state" in body:
            if body["state"] not in ("queued", "running", "succeeded", "failed"):
                raise ApiError(400, "invalid state")
            sets.append("state = ?")
            params.append(body["state"])
        if "result" in body:
            v = body["result"]
            sets.append("result = ?")
            params.append(v if isinstance(v, str) else json.dumps(v))
        if not sets:
            raise ApiError(400, "no updatable fields in body")
        sets.append("updated_at = ?")
        params += [time.time(), int(req["id"])]
        cur = self.db.execute(
            f"UPDATE jobs SET {', '.join(sets)} WHERE id = ?", tuple(params)
        )
        if cur.rowcount == 0:
            raise ApiError(404, "job not found")
        return self.get_job(req)

    @route("DELETE", "/api/v1/jobs/:id", write=True)
    def delete_job(self, req):
        self.db.execute("DELETE FROM jobs WHERE id = ?", (int(req["id"]),))
        return {"deleted": int(req["id"])}

    # -- scheduler / seed-peer write surface (reference router.go:151-174:
    # instances normally register over gRPC keepalive; the REST writes
    # exist for operators pre-provisioning or correcting rows)
    @route("POST", "/api/v1/schedulers", write=True)
    def create_scheduler(self, req):
        body = req["body"]
        if not body.get("hostname") or not body.get("ip"):
            raise ApiError(400, "hostname and ip are required")
        now = time.time()
        cur = self.db.execute(
            "INSERT INTO schedulers (hostname, ip, port, idc, location, state,"
            " scheduler_cluster_id, created_at, updated_at)"
            " VALUES (?, ?, ?, ?, ?, 'inactive', ?, ?, ?)",
            (
                body["hostname"], body["ip"], int(body.get("port", 8002)),
                body.get("idc", ""), body.get("location", ""),
                int(body.get("scheduler_cluster_id", 1)), now, now,
            ),
        )
        return self.db.query_one(
            "SELECT * FROM schedulers WHERE id = ?", (cur.lastrowid,)
        )

    @route("PATCH", "/api/v1/schedulers/:id", write=True)
    def update_scheduler(self, req):
        body = req["body"]
        sets, params = [], []
        for col in ("idc", "location", "state"):
            if col in body:
                sets.append(f"{col} = ?")
                params.append(body[col])
        if "scheduler_cluster_id" in body:
            sets.append("scheduler_cluster_id = ?")
            params.append(int(body["scheduler_cluster_id"]))
        if not sets:
            raise ApiError(400, "no updatable fields in body")
        sets.append("updated_at = ?")
        params += [time.time(), int(req["id"])]
        cur = self.db.execute(
            f"UPDATE schedulers SET {', '.join(sets)} WHERE id = ?", tuple(params)
        )
        if cur.rowcount == 0:
            raise ApiError(404, "scheduler not found")
        return self.get_scheduler(req)

    @route("POST", "/api/v1/seed-peers", write=True)
    def create_seed_peer(self, req):
        body = req["body"]
        if not body.get("hostname") or not body.get("ip"):
            raise ApiError(400, "hostname and ip are required")
        now = time.time()
        cur = self.db.execute(
            "INSERT INTO seed_peers (hostname, ip, port, download_port, type,"
            " idc, location, state, seed_peer_cluster_id, created_at, updated_at)"
            " VALUES (?, ?, ?, ?, ?, ?, ?, 'inactive', ?, ?, ?)",
            (
                body["hostname"], body["ip"], int(body.get("port", 8002)),
                int(body.get("download_port", 0)), body.get("type", "super"),
                body.get("idc", ""), body.get("location", ""),
                int(body.get("seed_peer_cluster_id", 1)), now, now,
            ),
        )
        return self.db.query_one(
            "SELECT * FROM seed_peers WHERE id = ?", (cur.lastrowid,)
        )

    @route("PATCH", "/api/v1/seed-peers/:id", write=True)
    def update_seed_peer(self, req):
        body = req["body"]
        sets, params = [], []
        for col in ("idc", "location", "state", "type"):
            if col in body:
                sets.append(f"{col} = ?")
                params.append(body[col])
        if "seed_peer_cluster_id" in body:
            sets.append("seed_peer_cluster_id = ?")
            params.append(int(body["seed_peer_cluster_id"]))
        if not sets:
            raise ApiError(400, "no updatable fields in body")
        sets.append("updated_at = ?")
        params += [time.time(), int(req["id"])]
        cur = self.db.execute(
            f"UPDATE seed_peers SET {', '.join(sets)} WHERE id = ?", tuple(params)
        )
        if cur.rowcount == 0:
            raise ApiError(404, "seed peer not found")
        return self.get_seed_peer(req)

    @route("DELETE", "/api/v1/seed-peers/:id", write=True)
    def delete_seed_peer(self, req):
        self.db.execute("DELETE FROM seed_peers WHERE id = ?", (int(req["id"]),))
        return {"deleted": int(req["id"])}

    @route("PUT", "/api/v1/scheduler-clusters/:id/schedulers/:scheduler_id", write=True)
    def add_scheduler_to_cluster(self, req):
        """Re-home a scheduler into a cluster (reference
        AddSchedulerToSchedulerCluster)."""
        if self.db.query_one(
            "SELECT id FROM scheduler_clusters WHERE id = ?", (int(req["id"]),)
        ) is None:
            raise ApiError(404, "scheduler cluster not found")
        cur = self.db.execute(
            "UPDATE schedulers SET scheduler_cluster_id = ?, updated_at = ?"
            " WHERE id = ?",
            (int(req["id"]), time.time(), int(req["scheduler_id"])),
        )
        if cur.rowcount == 0:
            raise ApiError(404, "scheduler not found")
        return {"scheduler_cluster_id": int(req["id"]),
                "scheduler_id": int(req["scheduler_id"])}

    # -- v1-compat preheat + ping (reference router.go:283-289, kept for
    # old clients: a thin alias over the jobs queue)
    @route("GET", "/_ping", auth=False)
    def ping(self, req):
        return {"status": "ok"}

    @route("POST", "/preheats", write=True)
    def create_v1_preheat(self, req):
        body = req["body"]
        url = (body.get("url") or "").strip()
        if not url:
            raise ApiError(400, "url is required")
        job = self.create_job(
            {**req, "body": {"type": "preheat", "args": {"url": url}}}
        )
        return {"id": str(job["id"]), "status": job["state"]}

    @route("GET", "/preheats/:id")
    def get_v1_preheat(self, req):
        job = self.get_job(req)
        return {"id": str(job["id"]), "status": job["state"]}

    # -- open API (reference router.go:262-281: /oapi/v1 groups gated by
    # personal access tokens — here PATs already authenticate every
    # bearer route, so these are first-class aliases of the same
    # handlers for automation clients)
    @route("GET", "/oapi/v1/jobs")
    def oapi_list_jobs(self, req):
        return self.list_jobs(req)

    @route("POST", "/oapi/v1/jobs", write=True)
    def oapi_create_job(self, req):
        return self.create_job(req)

    @route("GET", "/oapi/v1/jobs/:id")
    def oapi_get_job(self, req):
        return self.get_job(req)

    @route("PATCH", "/oapi/v1/jobs/:id", write=True)
    def oapi_update_job(self, req):
        return self.update_job(req)

    @route("DELETE", "/oapi/v1/jobs/:id", write=True)
    def oapi_delete_job(self, req):
        return self.delete_job(req)

    # -- composite clusters group (reference router.go:133-139: the main
    # UI resource — one "cluster" = a scheduler cluster and its paired
    # seed-peer cluster, created/listed together)
    @route("GET", "/api/v1/clusters")
    def list_clusters(self, req):
        out = []
        spc_by_name = {
            r["name"]: r
            for r in self.db.query("SELECT * FROM seed_peer_clusters")
        }
        for sc in self.db.query("SELECT * FROM scheduler_clusters ORDER BY id"):
            spc = spc_by_name.get(sc["name"])
            out.append(
                {
                    "id": sc["id"],
                    "name": sc["name"],
                    "scheduler_cluster": sc,
                    "seed_peer_cluster": spc,
                }
            )
        return out

    @route("POST", "/api/v1/clusters", write=True)
    def create_cluster(self, req):
        """One call provisions the scheduler cluster AND its paired
        seed-peer cluster under a shared name (reference CreateCluster)."""
        body = req["body"]
        if not body.get("name"):
            raise ApiError(400, "name is required")
        # pre-check BOTH names: the composite must not half-create (a
        # scheduler cluster with no pair) when either side collides —
        # sqlite has no cross-statement transaction here, so collision
        # is answered before any write
        for table in ("scheduler_clusters", "seed_peer_clusters"):
            if self.db.query_one(
                f"SELECT id FROM {table} WHERE name = ?", (body["name"],)
            ) is not None:
                raise ApiError(409, f"{table[:-1]} named {body['name']!r} exists")
        sc = self.create_scheduler_cluster(
            {**req, "body": {
                "name": body["name"],
                "config": body.get("scheduler_cluster_config", {}),
                "client_config": body.get("client_config", {}),
                "scopes": body.get("scopes", {}),
                "is_default": body.get("is_default", False),
            }}
        )
        spc = self.create_seed_peer_cluster(
            {**req, "body": {
                "name": body["name"],
                "config": body.get("seed_peer_cluster_config", {}),
            }}
        )
        return {"id": sc["id"], "name": sc["name"],
                "scheduler_cluster": sc, "seed_peer_cluster": spc}

    @route("GET", "/api/v1/clusters/:id")
    def get_cluster(self, req):
        sc = self.get_scheduler_cluster(req)
        spc = self.db.query_one(
            "SELECT * FROM seed_peer_clusters WHERE name = ?", (sc["name"],)
        )
        return {"id": sc["id"], "name": sc["name"],
                "scheduler_cluster": sc, "seed_peer_cluster": spc}

    @route("PATCH", "/api/v1/clusters/:id", write=True)
    def update_cluster(self, req):
        """Composite update: scheduler-cluster fields apply directly;
        seed_peer_cluster_config applies to the paired cluster; a rename
        renames BOTH sides (the pairing is by name, so renaming only one
        would orphan the other)."""
        body = dict(req["body"])
        spc_cfg = body.pop("seed_peer_cluster_config", None)
        # resolve the pair by the CURRENT name before any rename
        sc_before = self.get_scheduler_cluster(req)
        spc = self.db.query_one(
            "SELECT id FROM seed_peer_clusters WHERE name = ?", (sc_before["name"],)
        )
        if body:
            self.update_scheduler_cluster({**req, "body": body})
        if spc is not None:
            spc_body = {}
            if "name" in body:
                spc_body["name"] = body["name"]
            if spc_cfg is not None:
                spc_body["config"] = spc_cfg
            if spc_body:
                self.update_seed_peer_cluster(
                    {**req, "id": str(spc["id"]), "body": spc_body}
                )
        return self.get_cluster(req)

    @route("DELETE", "/api/v1/clusters/:id", write=True)
    def delete_cluster(self, req):
        sc = self.get_scheduler_cluster(req)
        self.db.execute(
            "DELETE FROM seed_peer_clusters WHERE name = ?", (sc["name"],)
        )
        return self.delete_scheduler_cluster(req)

    @route("GET", "/oapi/v1/clusters")
    def oapi_list_clusters(self, req):
        return self.list_scheduler_clusters(req)

    @route("POST", "/oapi/v1/clusters", write=True)
    def oapi_create_cluster(self, req):
        return self.create_scheduler_cluster(req)

    @route("GET", "/oapi/v1/clusters/:id")
    def oapi_get_cluster(self, req):
        return self.get_scheduler_cluster(req)

    @route("PATCH", "/oapi/v1/clusters/:id", write=True)
    def oapi_update_cluster(self, req):
        return self.update_scheduler_cluster(req)

    @route("DELETE", "/oapi/v1/clusters/:id", write=True)
    def oapi_delete_cluster(self, req):
        return self.delete_scheduler_cluster(req)


class RestServer:
    def __init__(
        self,
        service: ManagerService,
        host: str = "127.0.0.1",
        port: int = 0,
        tokens: dict[str, str] | None = None,
    ):
        self.api = RestApi(service)
        self.tokens = dict(tokens or {})  # token -> role ("admin"|"guest")
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------
    def _auth_info(self, auth_header: str | None) -> tuple[str | None, int | None]:
        """→ (role, owning user id), or (None, None) when
        unauthenticated. Config tokens are checked first (they have no
        DB user, so no owner id), then DB-backed personal access tokens
        — resolved ONCE here; handlers needing the owner (per-user PAT
        routes) read it from the request instead of re-querying. No
        config tokens AND no users = open admin access (dev mode, like
        the reference without auth)."""
        from dragonfly2_tpu.manager import auth

        token = ""
        if auth_header and auth_header.startswith("Bearer "):
            token = auth_header[7:]
        if token:
            role = self.tokens.get(token)
            if role is not None:
                return role, None
            row = auth._resolve_token_row(self.api.db, token)
            if row is not None:
                return row["role"], int(row["user_id"])
        if not self.tokens and not self._has_admin():
            return "admin", None
        return None, None

    def _has_admin(self) -> bool:
        """Anonymous dev-mode admin ends when an ADMIN credential exists
        — not when any user does: an OAuth passerby auto-provisioned as
        guest must not close the bootstrap window and lock every write
        route with no admin account in existence."""
        return (
            self.api.db.query_one(
                "SELECT id FROM users WHERE role = 'admin' LIMIT 1"
            )
            is not None
        )

    def start(self) -> str:
        api = self.api
        auth_info = self._auth_info

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to dflog, not stderr
                logger.debug("%s " + fmt, self.client_address[0], *args)

            def _dispatch(self):
                from urllib.parse import parse_qsl, urlsplit

                parts = urlsplit(self.path)
                # embedded console: a static page (unauthenticated, like
                # any static asset — its data calls carry the token); the
                # reference embeds its React console the same way
                # (manager/manager.go:61-85)
                if self.command == "GET" and parts.path in ("/", "/console"):
                    from dragonfly2_tpu.manager.console import index_html

                    data = index_html()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                query = dict(parse_qsl(parts.query))
                auth_header = self.headers.get("Authorization") or ""
                bearer = (
                    auth_header[7:] if auth_header.startswith("Bearer ") else ""
                )
                role, auth_user_id = auth_info(self.headers.get("Authorization"))
                for method, rx, fname, write, needs_auth, _pattern in _ROUTES:
                    if method != self.command:
                        continue
                    m = rx.match(parts.path)
                    if not m:
                        continue
                    # auth=False routes (health probe, password signin,
                    # OAuth redirect/callback legs) stay open — a
                    # per-route flag, so nothing else inherits it
                    if role is None and needs_auth:
                        return self._send(401, {"error": "unauthorized"})
                    if write and role != "admin":
                        return self._send(403, {"error": "forbidden (read-only role)"})
                    body = {}
                    length = int(self.headers.get("Content-Length") or 0)
                    if length:
                        try:
                            body = json.loads(self.rfile.read(length))
                        except ValueError:
                            return self._send(400, {"error": "invalid JSON body"})
                    # the bearer token rides along for the session
                    # routes (signout revokes it, refresh_token rotates
                    # it); the caller's role under a NON-COLLIDING key —
                    # path params (e.g. :role) must always win
                    req = {
                        "body": body,
                        "query": query,
                        "token": bearer,
                        "auth_role": role,
                        "auth_user_id": auth_user_id,
                        **m.groupdict(),
                    }
                    try:
                        return self._send(200, getattr(api, fname)(req))
                    except Redirect as r:
                        return self._send(
                            302, {"location": r.location}, location=r.location
                        )
                    except ApiError as e:
                        return self._send(e.status, {"error": str(e)})
                    except sqlite3.IntegrityError as e:
                        # UNIQUE/foreign-key violations are client
                        # mistakes (duplicate name), not server faults
                        return self._send(409, {"error": str(e)})
                    except ValueError as e:
                        # non-numeric path/query params etc. are client
                        # errors, not server faults
                        return self._send(400, {"error": str(e)})
                    except Exception as e:  # pragma: no cover - defensive
                        logger.exception("REST handler failed")
                        return self._send(500, {"error": str(e)})
                self._send(404, {"error": f"no route for {self.command} {parts.path}"})

            def _send(self, status: int, payload, location: str | None = None):
                from dragonfly2_tpu.manager import metrics as M

                M.REST_REQUEST_TOTAL.labels(self.command, str(status)).inc()
                data = json.dumps(payload, default=str).encode()
                self.send_response(status)
                if location is not None:
                    self.send_header("Location", location)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _dispatch

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="manager.rest", daemon=True
        )
        self._thread.start()
        return f"{self.host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
