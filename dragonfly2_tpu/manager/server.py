"""Manager server assembly (reference manager/manager.go:87-330): DB +
object-storage-backed model registry + gRPC service, with Serve/Stop
lifecycle. The REST API router rides the same assembly when enabled."""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

from dragonfly2_tpu.manager.database import Database
from dragonfly2_tpu.manager.models_registry import ModelRegistry
from dragonfly2_tpu.manager.objectstorage import new_object_storage
from dragonfly2_tpu.manager.service import ManagerService
from dragonfly2_tpu.rpc import glue
from dragonfly2_tpu.utils import dflog, flight, profiling

logger = dflog.get("manager.server")



@dataclass
class ManagerServerConfig:
    data_dir: str = "/tmp/dragonfly2-manager"
    listen: str = "127.0.0.1:0"
    # REST API (manager/router): -1 = disabled, 0 = ephemeral port
    rest_port: int = -1
    rest_host: str = "127.0.0.1"
    # bearer tokens accepted by the REST API, role per token
    # ({token: "admin"|"guest"}); empty = unauthenticated (dev mode)
    rest_tokens: dict = field(default_factory=dict)
    # Prometheus /metrics endpoint (reference :8000): -1 = disabled
    metrics_port: int = -1
    metrics_host: str = "127.0.0.1"
    # gRPC TLS: PEM file paths; tls_client_ca_file enforces mTLS
    tls_cert_file: str = ""
    tls_key_file: str = ""
    tls_client_ca_file: str = ""
    # read-through DB cache TTL in seconds (reference manager/cache Redis
    # TTLs); 0 disables caching
    db_cache_ttl: float = 30.0
    # dynamic certificate issuance (IssueCertificate RPC): CA persisted
    # under data_dir/ca; False = static cert files only. The token gates
    # who may obtain signed identities ('' = open — dev only)
    issue_certs: bool = True
    issue_certs_token: str = ""
    # embedded RESP KV server (the Redis role): schedulers point their
    # kv_address here to share one probe-graph/counter store across
    # processes (reference deploys Redis alongside the manager for the
    # same purpose). -1 = disabled, 0 = ephemeral port. The bind host
    # and the ADVERTISED host are distinct (same pattern as the gRPC
    # listen/advertise split): 0.0.0.0 binds everywhere but is not a
    # dialable address, so kv_advertise_ip is what lands in kv_addr /
    # the runner's KV line. Loopback bind by default — exposing the KV
    # on the network is an explicit opt-in, and should come with
    # kv_secret so every connection must AUTH (requirepass semantics;
    # schedulers pass the same value as their kv_secret).
    kv_port: int = -1
    kv_host: str = "127.0.0.1"
    kv_advertise_ip: str = "127.0.0.1"
    kv_secret: str = ""
    # object storage for model weights: fs (default, under data_dir) or
    # s3 (any S3-compatible endpoint; reference pkg/objectstorage)
    object_storage_driver: str = "fs"
    object_storage_endpoint: str = ""
    object_storage_access_key: str = ""
    object_storage_secret_key: str = ""
    object_storage_region: str = "us-east-1"


class ManagerServer:
    def __init__(self, config: ManagerServerConfig):
        self.cfg = config
        Path(config.data_dir).mkdir(parents=True, exist_ok=True)
        self.db = Database(str(Path(config.data_dir) / "manager.db"))
        if config.db_cache_ttl > 0:
            from dragonfly2_tpu.manager.cache import CachedDatabase

            self.db = CachedDatabase(self.db, ttl=config.db_cache_ttl)
        self.object_storage = new_object_storage(
            driver=config.object_storage_driver,
            root=str(Path(config.data_dir) / "objects"),
            endpoint=config.object_storage_endpoint,
            access_key=config.object_storage_access_key,
            secret_key=config.object_storage_secret_key,
            region=config.object_storage_region,
        )
        self.models = ModelRegistry(self.db, self.object_storage)
        self.service = ManagerService(
            self.db,
            self.models,
            ca=self._load_ca(config),
            ca_token=config.issue_certs_token,
        )
        # cluster telemetry plane (manager/telemetry.py): in-memory by
        # design — reporters re-register and re-baseline after a manager
        # restart, so the aggregates and the dedup state die together
        from dragonfly2_tpu.manager.telemetry import TelemetryPlane

        self.telemetry = TelemetryPlane()
        self.service.telemetry = self.telemetry
        self._grpc = None
        self._rest = None
        self.rest_addr: str | None = None

    @staticmethod
    def _load_ca(config):
        """The cluster CA behind IssueCertificate, persisted under
        data_dir/ca so restarts keep issuing from the same root
        (reference pkg/issuer + securityv1). ``issue_certs=False``
        disables dynamic issuance entirely."""
        if not config.issue_certs:
            return None
        from dragonfly2_tpu.utils.issuer import CertificateAuthority

        ca_dir = Path(config.data_dir) / "ca"
        cert_p, key_p = ca_dir / "ca.crt", ca_dir / "ca.key"
        if cert_p.exists() and key_p.exists():
            return CertificateAuthority.load(cert_p.read_bytes(), key_p.read_bytes())
        ca = CertificateAuthority(common_name="dragonfly2-tpu manager CA")
        ca_dir.mkdir(parents=True, exist_ok=True)
        cert_p.write_bytes(ca.cert_pem)
        # the key file is born 0600 — a chmod-after-write leaves a window
        # where any local user can open (and keep) a readable fd to the
        # cluster root key
        import os as _os

        fd = _os.open(str(key_p), _os.O_WRONLY | _os.O_CREAT | _os.O_EXCL, 0o600)
        with _os.fdopen(fd, "wb") as f:
            f.write(ca.key_pem)
        return ca

    def serve(self) -> str:
        from dragonfly2_tpu.manager.service import SERVICE_NAME

        # flight recorder: crash dumps + the Diagnose snapshot RPC
        flight.install("manager")
        # continuous profiler: always-on sampler + phase ledger
        profiling.install("manager")
        from dragonfly2_tpu.manager.telemetry import TelemetryService
        from dragonfly2_tpu.rpc.diagnose import DiagnoseService
        from dragonfly2_tpu.utils.metrics import set_build_info

        set_build_info("manager")
        self._grpc, port = glue.serve(
            {
                SERVICE_NAME: self.service,
                glue.DIAGNOSE_SERVICE: DiagnoseService(),
                # telemetry rides the same channel every service already
                # dials for KeepAlive/dynconfig
                glue.TELEMETRY_SERVICE: TelemetryService(self.telemetry),
            },
            self.cfg.listen,
            **glue.serve_tls_args(
                self.cfg.tls_cert_file, self.cfg.tls_key_file, self.cfg.tls_client_ca_file
            ),
        )
        host = self.cfg.listen.rsplit(":", 1)[0]
        addr = f"{host}:{port}"
        if self.cfg.rest_port >= 0:
            from dragonfly2_tpu.manager.rest import RestServer

            self._rest = RestServer(
                self.service,
                host=self.cfg.rest_host,
                port=self.cfg.rest_port,
                tokens=self.cfg.rest_tokens,
            )
            self.rest_addr = self._rest.start()
            logger.info("manager REST on %s", self.rest_addr)
        if self.cfg.metrics_port >= 0:
            from dragonfly2_tpu.manager import metrics  # noqa: F401 — register series
            from dragonfly2_tpu.utils.metrics import MetricsServer, default_registry

            self._metrics = MetricsServer(default_registry, host=self.cfg.metrics_host, port=self.cfg.metrics_port)
            # liveness on the scrape port (/healthz): the gRPC plane up
            self._metrics.register_health("manager", lambda: self._grpc is not None)
            # SLO state rides the liveness body next to the resilience
            # map — a burning SLO is degraded, never a 503
            self._metrics.register_status_section(
                "slo", self.telemetry.health_section
            )
            self.metrics_addr = self._metrics.start()
            logger.info("manager metrics on %s", self.metrics_addr)
        if self.cfg.kv_port >= 0:
            from dragonfly2_tpu.utils.kvserver import KVServer

            self._kv = KVServer(
                host=self.cfg.kv_host, port=self.cfg.kv_port, secret=self.cfg.kv_secret
            )
            kv_port = self._kv.serve()
            advertise = (
                self.cfg.kv_advertise_ip
                if self.cfg.kv_host in ("0.0.0.0", "::")
                else self.cfg.kv_host
            )
            self.kv_addr = f"{advertise}:{kv_port}"
            # scheduler-fleet view: the embedded KV is where fleet
            # leases live, so the dynconfig scheduler list can scope to
            # live members (ManagerService._fleet_members)
            self.service.fleet_kv = self._kv.store
            logger.info(
                "manager kv (RESP) bound %s:%d, advertising %s",
                self.cfg.kv_host, kv_port, self.kv_addr,
            )
        logger.info("manager gRPC on %s", addr)
        return addr

    def stop(self) -> None:
        if getattr(self, "_kv", None) is not None:
            self._kv.stop()
        if getattr(self, "_metrics", None) is not None:
            self._metrics.stop()
        if self._rest is not None:
            self._rest.stop()
        if self._grpc is not None:
            self._grpc.stop(grace=2).wait(5)
        self.db.close()


def build(config_path, overrides):
    from dragonfly2_tpu.cli.config import load_config

    cfg = load_config(
        ManagerServerConfig, config_path, env_prefix="DF_MANAGER", overrides=overrides
    )
    return ManagerServer(cfg)
