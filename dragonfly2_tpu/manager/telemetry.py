"""Manager-side cluster telemetry plane: windowed rolling aggregates of
the reports every service pushes (utils/telemetry.py), plus the SLO
burn-rate engine on top (docs/telemetry.md).

The reference Manager is the cluster's aggregation point (control plane
with cluster DB and console); this module is our equivalent for the
*operational* state nobody can see from per-process ``/metrics``
endpoints alone: swarm health per task, per-scheduler-shard rates,
trainer freshness — and objectives attached to them.

Aggregation model: cumulative series values land in per-reporter
baselines; the derived deltas fold into 10-second buckets kept for one
hour, so every windowed rate (1m/5m/1h) is one pass over ≤ 360 buckets
at query time. Baselining pushes (a reporter's registration, and every
FULL snapshot) store unknown series without counting them — a payload
after a manager restart can therefore never replay a reporter's whole
history as one spike — while an unknown series on an ordinary
changed-only push counts from zero, because the full baseline already
enumerated everything older (a previously clean counter's first error
must burn the SLO, not vanish). The dedup state and the aggregates
live and die together, so a retried delivery after a lost ack folds to
zero: no double counting.

SLO engine: declarative specs (ratio / latency / freshness) evaluated
with classic multi-window burn rates — breach when BOTH the fast and
slow windows burn error budget faster than ``burn_threshold``×. A
breach transition emits a ``manager.slo_burn`` flight event (so a
dfdoctor postmortem shows the breach next to its cause), flips the
``dragonfly_manager_slo_*`` series, and rides the ``/healthz`` body
through the status-section hook — degraded, not down: a burning SLO
keeps the 200.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field

from dragonfly2_tpu.manager import metrics as M
from dragonfly2_tpu.utils import dflog, flight

# the plane's vocabulary: snapshot keys come from the TFIELDS census
# (utils/telemetry.py, linted by dfanalyze) so producer and consumers
# (dfstat, the soak's manager-view check) can never drift apart
from dragonfly2_tpu.utils.telemetry import (
    F_CLUSTER_FLOW_BYTES,
    F_CLUSTER_P2P_EFFICIENCY,
    F_CLUSTER_PEERS,
    F_CLUSTER_SCHEDULE_OPS,
    F_CLUSTER_TASKS,
    F_DAEMON_BACK_TO_SOURCE,
    F_DAEMON_FLOW_BYTES,
    F_DAEMON_FLOW_ORIGIN_BYTES,
    F_DAEMON_FLOW_P2P_BYTES,
    F_DAEMON_PIECE_BYTES,
    F_SHARD_ANNOUNCE_OPS,
    F_SHARD_DECISION_P99,
    F_SHARD_PEERS,
    F_SHARD_SCHEDULE_OPS,
    F_SHARD_SWARM_DEPTHS,
    F_SHARD_SWARM_PEERS,
    F_SHARD_SWARM_STRAGGLERS,
    F_SHARD_SWARM_TASKS,
    F_SHARD_TASKS,
    F_SLO_BREACHED,
    F_SWARM_DONE_PIECES,
    F_SWARM_PEERS,
    F_SWARM_SEEDERS,
    F_SWARM_STRAGGLERS,
    F_SWARM_TOTAL_PIECES,
    F_TRAINER_DATASET_BYTES,
    F_TRAINER_FIT_FRESHNESS,
    F_TRAINER_INGEST_RECORDS,
)

logger = dflog.get("manager.telemetry")

EV_SLO_BURN = flight.event_type("manager.slo_burn")
EV_SLO_CLEAR = flight.event_type("manager.slo_clear")

BUCKET_S = 10.0
MAX_BUCKETS = 360  # one hour of 10s buckets
WINDOWS_S = {"1m": 60.0, "5m": 300.0, "1h": 3600.0}


def _series_name(key: str) -> str:
    return key.split("{", 1)[0]


def quantile_from_buckets(buckets: "dict[str, float]", q: float) -> float:
    """Linear-interpolated quantile from cumulative-count histogram
    buckets ({le_repr: count}); 0.0 on an empty histogram. The +Inf
    bucket clamps to the last finite edge (the reference Prometheus
    histogram_quantile behavior)."""
    edges: list[tuple[float, float]] = []
    for le, c in buckets.items():
        edges.append((float("inf") if le == "+Inf" else float(le), float(c)))
    edges.sort()
    if not edges or edges[-1][1] <= 0:
        return 0.0
    total = edges[-1][1]
    rank = q * total
    prev_edge, prev_count = 0.0, 0.0
    for edge, count in edges:
        if count >= rank:
            if edge == float("inf"):
                return prev_edge
            if count == prev_count:
                return edge
            frac = (rank - prev_count) / (count - prev_count)
            return prev_edge + (edge - prev_edge) * frac
        prev_edge, prev_count = (0.0 if edge == float("inf") else edge), count
    return prev_edge


class _Bucket:
    """Deltas are aggregated by series NAME (labels summed away at fold
    time): every windowed read wants the across-label-sets sum anyway,
    and the by-name index turns rate()/window_hist() into plain dict
    lookups instead of per-key string splitting — forced SLO
    evaluations on /healthz reads stay cheap under the plane lock."""

    __slots__ = ("ts", "counters", "hist_buckets")

    def __init__(self, ts: float):
        self.ts = ts
        self.counters: dict[str, float] = {}  # series name -> delta sum
        # series name -> {le_repr: count_delta}
        self.hist_buckets: dict[str, dict[str, float]] = {}


class _Reporter:
    """Per-(service, instance) state: baseline cumulative values, the
    delta buckets, the latest gauges and structured sections."""

    def __init__(self, service: str, instance: str, shard: str, epoch: str):
        self.service = service
        self.instance = instance
        self.shard = shard
        self.epoch = epoch
        # True until the first FULL payload lands: the ack keeps asking
        # (registered=True) so a LOST registration ack can't leave the
        # reporter changed-only forever — without the full enumeration,
        # a quiet series' later first tick would replay its cumulative
        # history as one spike (fold counts unknown series from zero
        # only once a full baseline exists)
        self.awaiting_full = True
        self.last_seq = 0
        self.first_seen = time.time()
        self.last_report = self.first_seen
        self.interval_s = 15.0
        self.counters_cum: dict[str, float] = {}
        self.hists_cum: dict[str, dict] = {}
        self.gauges: dict[str, float] = {}
        self.sections: dict = {}
        self.buckets: list[_Bucket] = []

    def _bucket(self, now: float) -> _Bucket:
        ts = now - (now % BUCKET_S)
        if self.buckets and self.buckets[-1].ts == ts:
            return self.buckets[-1]
        b = _Bucket(ts)
        self.buckets.append(b)
        if len(self.buckets) > MAX_BUCKETS:
            del self.buckets[: len(self.buckets) - MAX_BUCKETS]
        return b

    def fold(self, payload: dict, now: float, baseline_only: bool = False) -> None:
        """Fold one payload's deltas into the current bucket.

        Series-first-sight semantics guard against history replay: on a
        FULL push (registration/re-registration snapshots) or while
        ``baseline_only`` (the push that registered this reporter), an
        unknown series is baselined, never counted — its cumulative
        value may carry history from before the manager knew this
        reporter. On a changed-only push an unknown series counts from
        zero: the full baseline push already enumerated every series
        that predates it, so a later arrival is genuinely new activity
        (the first failure of a previously clean counter must burn the
        SLO, not vanish into a baseline)."""
        baselining = baseline_only or bool(payload.get("full"))
        bucket = self._bucket(now)
        for key, cum in payload.get("counters", {}).items():
            prev = self.counters_cum.get(key)
            self.counters_cum[key] = cum
            if prev is None:
                if baselining:
                    continue
                prev = 0.0
            d = cum - prev
            if d > 0:
                name = _series_name(key)
                bucket.counters[name] = bucket.counters.get(name, 0.0) + d
        for key, h in payload.get("hists", {}).items():
            prev = self.hists_cum.get(key)
            self.hists_cum[key] = h
            if prev is None:
                if baselining:
                    continue
                prev = {"buckets": {}, "count": 0}
            name = _series_name(key)
            prev_b = prev.get("buckets", {})
            # every edge rides the delta (zeros included) so a window
            # whose observations all landed past the largest finite edge
            # still carries the finite schema — quantile_from_buckets
            # then clamps to the last finite edge instead of reading an
            # +Inf-only dict as "no data" (p99 = 0 mid-incident)
            deltas = {
                le: max(c - prev_b.get(le, 0.0), 0.0)
                for le, c in h.get("buckets", {}).items()
            }
            if any(d > 0 for d in deltas.values()):
                agg = bucket.hist_buckets.setdefault(name, {})
                for le, d in deltas.items():
                    agg[le] = agg.get(le, 0.0) + d
            # the histogram count doubles as a counter series (rate of
            # observations) under <name>_count — labels already summed
            dc = h.get("count", 0) - prev.get("count", 0)
            if dc > 0:
                ck = name + "_count"
                bucket.counters[ck] = bucket.counters.get(ck, 0.0) + dc
        self.gauges.update(payload.get("gauges", {}))
        for k, v in payload.items():
            if k in ("counters", "gauges", "hists", "full"):
                continue
            self.sections[k] = v

    # -- windowed reads -------------------------------------------------
    def _effective_window(self, window_s: float, now: float) -> float:
        # a reporter younger than the window must not under-report rate
        return max(BUCKET_S, min(window_s, now - self.first_seen))

    def rate(self, name: str, window_s: float, now: float) -> float:
        """Per-second rate of metric ``name`` (label sets were summed at
        fold time) within the trailing window."""
        cutoff = now - window_s
        total = 0.0
        for b in reversed(self.buckets):
            if b.ts + BUCKET_S < cutoff:
                break
            total += b.counters.get(name, 0.0)
        return total / self._effective_window(window_s, now)

    def window_hist(self, name: str, window_s: float, now: float) -> dict:
        """Merged bucket deltas of histogram ``name`` within the
        trailing window."""
        cutoff = now - window_s
        merged: dict[str, float] = {}
        for b in reversed(self.buckets):
            if b.ts + BUCKET_S < cutoff:
                break
            deltas = b.hist_buckets.get(name)
            if deltas:
                for le, d in deltas.items():
                    merged[le] = merged.get(le, 0.0) + d
        # cumulative-ize: bucket counts on the wire are already
        # cumulative per le within one snapshot, and deltas of
        # cumulative counts stay cumulative across les — merged is
        # directly usable by quantile_from_buckets
        return merged

    def gauge_sum(self, name: str) -> "float | None":
        # NOT named .gauge(): the dfanalyze metrics census matches any
        # attribute call of that name with a literal first arg as a
        # series registration
        vals = self.gauge_values(name)
        if not vals:
            return None
        return sum(vals)

    def gauge_min(self, name: str) -> "float | None":
        """Min over the series' label children — the right reduction for
        per-model timestamp gauges (the STALEST model is the alarm; a
        sum of unix timestamps is a meaningless 3.4e9)."""
        vals = [v for v in self.gauge_values(name) if v > 0]
        if not vals:
            return None
        return min(vals)

    def gauge_values(self, name: str) -> "list[float]":
        return [v for k, v in self.gauges.items() if _series_name(k) == name]

    def stale(self, now: float) -> bool:
        return (now - self.last_report) > max(3 * self.interval_s, 5.0)


# -- SLO specs -----------------------------------------------------------


@dataclass
class SLOSpec:
    """One declarative objective. ``kind``:

    - ``ratio``: good/bad counter series; error_rate = bad/(good+bad).
    - ``latency``: a histogram series + threshold_s; error_rate =
      fraction of window observations above the threshold.
    - ``freshness``: a unix-timestamp gauge + threshold_s; error_rate is
      1.0 while (now - ts) exceeds the threshold, else 0.0.

    ``objective`` is the good-fraction target (e.g. 0.999 ⇒ 0.1% error
    budget); burn rate = error_rate / (1 - objective). Breach when BOTH
    windows burn above ``burn_threshold``."""

    name: str
    kind: str
    objective: float
    service: str = ""  # restrict to one reporting service ("" = all)
    good_series: str = ""
    bad_series: str = ""
    hist_series: str = ""
    gauge_series: str = ""
    threshold_s: float = 0.0
    fast_window: str = "5m"
    slow_window: str = "1h"
    burn_threshold: float = 1.0
    description: str = ""


def default_slos() -> "list[SLOSpec]":
    return [
        SLOSpec(
            name="download_success",
            kind="ratio",
            objective=0.99,
            service="scheduler",
            good_series="dragonfly_scheduler_download_peer_finished_total",
            bad_series="dragonfly_scheduler_download_peer_failure_total",
            description="peers finish their downloads",
        ),
        SLOSpec(
            name="announce_availability",
            kind="ratio",
            objective=0.99,
            service="scheduler",
            good_series="dragonfly_scheduler_announce_peer_total",
            bad_series="dragonfly_scheduler_announce_peer_failure_total",
            description="announce-plane RPCs succeed",
        ),
        SLOSpec(
            name="schedule_p99",
            kind="latency",
            objective=0.99,
            service="scheduler",
            hist_series="dragonfly_scheduler_schedule_duration_seconds",
            threshold_s=0.5,
            description="schedule decisions land under 500ms",
        ),
        SLOSpec(
            name="fit_freshness",
            kind="freshness",
            objective=0.9,
            service="trainer",
            gauge_series="dragonfly_trainer_last_fit_timestamp_seconds",
            threshold_s=14 * 24 * 3600.0,  # 2× the default train interval
            description="the parent-scorer fit is recent",
        ),
        SLOSpec(
            name="p2p_efficiency",
            kind="ratio",
            objective=0.5,
            service="daemon",
            # flow-ledger rollups (utils/flows): "good" bytes never
            # touched the origin (parent + dedup + local_cache), "bad"
            # bytes did (demand back-to-source + preheat seeding); the
            # ratio error_rate is the origin fraction, so burn > 1 ⇔
            # p2p efficiency below the 0.5 objective
            good_series="dragonfly_flow_p2p_bytes_total",
            bad_series="dragonfly_flow_origin_bytes_total",
            description="bytes are served from the swarm, not the origin",
        ),
    ]


@dataclass
class _SLOState:
    spec: SLOSpec
    breached: bool = False
    burn: dict = field(default_factory=dict)  # window -> burn rate
    since: float = 0.0


class TelemetryPlane:
    """The manager's aggregation point. Thread-safe: gRPC report
    handlers, REST snapshot reads, and /healthz sections all cross it."""

    # a reporter silent this long is dropped entirely: daemons bind
    # ephemeral ports, so every restart mints a new (service, instance)
    # key — without eviction a long-lived manager accumulates dead rows
    # (and their hour of buckets) forever. An hour keeps a killed member
    # visible as a kill on the dashboard, then forgets it.
    EVICT_AFTER_S = 3600.0
    # burn-rate math walks every reporter's buckets; inputs only change
    # at bucket granularity, so per-report evaluation is throttled and
    # snapshot() forces a fresh pass
    EVAL_INTERVAL_S = 5.0

    def __init__(self, slos: "list[SLOSpec] | None" = None):
        # reentrant: snapshot() evaluates SLOs under the same lock it
        # holds for the aggregate walk
        self._lock = threading.RLock()
        self._reporters: dict[tuple[str, str], _Reporter] = {}
        self._seen_services: set[str] = set()
        self._last_eval = 0.0
        self._slos = {
            s.name: _SLOState(spec=s)
            for s in (default_slos() if slos is None else slos)
        }

    # -- ingest ---------------------------------------------------------
    def apply(
        self,
        service: str,
        instance: str,
        shard: str,
        epoch: str,
        seq: int,
        interval_s: float,
        payload: dict,
        now: "float | None" = None,
    ) -> tuple[bool, int]:
        """Fold one report; → (registered, last_seq) for the ack."""
        now = time.time() if now is None else now
        key = (service, instance)
        with self._lock:
            rep = self._reporters.get(key)
            registered = rep is None or rep.epoch != epoch
            if registered:
                # fresh reporter / reporter restart / manager restart:
                # baseline only — fold() counts nothing on first sight
                rep = _Reporter(service, instance, shard, epoch)
                self._reporters[key] = rep
            elif seq <= rep.last_seq:
                # duplicate delivery (retry after a lost ack): cumulative
                # values make re-folding harmless, but skipping is free
                M.TELEMETRY_REPORTS_TOTAL.labels(service, "duplicate").inc()
                return rep.awaiting_full, rep.last_seq
            rep.last_seq = seq
            rep.last_report = now
            rep.shard = shard or rep.shard
            if interval_s > 0:
                rep.interval_s = interval_s
            # until a FULL payload lands, every push may be a
            # changed-only subset carrying history — unknown series are
            # baselined, never counted (known series still delta)
            rep.fold(payload, now, baseline_only=rep.awaiting_full)
            if payload.get("full"):
                rep.awaiting_full = False
            # keep answering registered=True until the full snapshot
            # arrives: a lost registration ack must not strand the
            # reporter changed-only forever
            registered = registered or rep.awaiting_full
            for key_, r in list(self._reporters.items()):
                if (now - r.last_report) > self.EVICT_AFTER_S:
                    del self._reporters[key_]
            self._seen_services.add(service)
            by_service = {svc: 0 for svc in self._seen_services}
            for (svc, _), r in self._reporters.items():
                by_service[svc] = by_service.get(svc, 0) + 1
        for svc, n in by_service.items():
            M.TELEMETRY_REPORTERS.labels(svc).set(n)
        M.TELEMETRY_REPORTS_TOTAL.labels(
            service, "registered" if registered else "applied"
        ).inc()
        # throttled: N reporters pushing must not re-walk every bucket
        # per report; snapshot() forces a fresh pass when queried
        self.evaluate_slos(now, force=False)
        return registered, seq

    # -- SLO engine -----------------------------------------------------
    def _error_rate(self, spec: SLOSpec, window_s: float, now: float) -> float:
        with self._lock:
            reps = [
                r
                for r in self._reporters.values()
                if not spec.service or r.service == spec.service
            ]
        if spec.kind == "ratio":
            good = sum(r.rate(spec.good_series, window_s, now) for r in reps)
            bad = sum(r.rate(spec.bad_series, window_s, now) for r in reps)
            total = good + bad
            return bad / total if total > 0 else 0.0
        if spec.kind == "latency":
            merged: dict[str, float] = {}
            for r in reps:
                for le, d in r.window_hist(spec.hist_series, window_s, now).items():
                    merged[le] = merged.get(le, 0.0) + d
            if not merged:
                return 0.0
            total = max(merged.values())
            below = 0.0
            for le, c in sorted(
                ((float("inf") if k == "+Inf" else float(k), v) for k, v in merged.items())
            ):
                if le <= spec.threshold_s:
                    below = max(below, c)
            return (total - below) / total if total > 0 else 0.0
        if spec.kind == "freshness":
            rates = []
            for r in reps:
                # min over label children: with per-model timestamps the
                # STALEST model is what burns the budget
                ts = r.gauge_min(spec.gauge_series)
                if ts is None:
                    continue  # never fit yet: no budget burned pre-launch
                rates.append(1.0 if (now - ts) > spec.threshold_s else 0.0)
            return max(rates) if rates else 0.0
        return 0.0

    def evaluate_slos(self, now: "float | None" = None, force: bool = True) -> None:
        now = time.time() if now is None else now
        transitions = []
        # the whole evaluation holds the plane lock (reentrant): burn
        # math walks reporter buckets that a concurrent apply() mutates
        with self._lock:
            if not force and (now - self._last_eval) < self.EVAL_INTERVAL_S:
                return
            self._last_eval = now
            states = list(self._slos.values())
            for st in states:
                spec = st.spec
                budget = max(1e-9, 1.0 - spec.objective)
                burns = {}
                for wname in (spec.fast_window, spec.slow_window):
                    err = self._error_rate(spec, WINDOWS_S[wname], now)
                    burns[wname] = err / budget
                    M.SLO_BURN_RATE.labels(spec.name, wname).set(
                        round(burns[wname], 4)
                    )
                breached = all(b > spec.burn_threshold for b in burns.values())
                M.SLO_BREACHED.labels(spec.name).set(1.0 if breached else 0.0)
                was = st.breached
                st.breached = breached
                st.burn = burns
                if breached and not was:
                    st.since = now
                transitions.append((spec, burns, was, breached))
        for spec, burns, was, breached in transitions:
            if breached and not was:
                EV_SLO_BURN(
                    slo=spec.name,
                    burn_fast=round(burns[spec.fast_window], 3),
                    burn_slow=round(burns[spec.slow_window], 3),
                    objective=spec.objective,
                    kind=spec.kind,
                )
                logger.warning(
                    "SLO %s breached: burn %s=%0.2fx %s=%0.2fx (objective %s)",
                    spec.name, spec.fast_window, burns[spec.fast_window],
                    spec.slow_window, burns[spec.slow_window], spec.objective,
                )
            elif was and not breached:
                EV_SLO_CLEAR(slo=spec.name)
                logger.info("SLO %s recovered", spec.name)

    # -- query surfaces -------------------------------------------------
    def health_section(self) -> dict:
        """The /healthz body's ``slo`` section (status-section hook in
        utils.metrics.MetricsServer). A burning SLO is degraded, not
        down — this never flips the 503."""
        # forced refresh, like snapshot(): liveness probes are the
        # cadence of a deploy (seconds apart), and the operator reading
        # /healthz mid-incident must see the current burn, not the last
        # throttled pass
        self.evaluate_slos()
        with self._lock:
            states = list(self._slos.values())
        return {
            "breached": sorted(s.spec.name for s in states if s.breached),
            "slos": {
                s.spec.name: {
                    "breached": s.breached,
                    "burn": {w: round(b, 3) for w, b in s.burn.items()},
                    "objective": s.spec.objective,
                }
                for s in states
            },
        }

    def snapshot(self, now: "float | None" = None) -> dict:
        """The /api/v1/telemetry body: per-service inventory, merged
        swarm table, per-shard and per-trainer/per-daemon windowed
        aggregates, the cluster rollup, and SLO state."""
        now = time.time() if now is None else now
        self.evaluate_slos(now)
        # the whole walk holds the (reentrant) lock: windowed reads
        # iterate reporter buckets that a concurrent apply() mutates
        with self._lock:
            return self._snapshot_locked(now)

    def _snapshot_locked(self, now: float) -> dict:
        reps = list(self._reporters.values())

        def rates(r: _Reporter, name: str) -> dict:
            return {
                w: round(r.rate(name, s, now), 2) for w, s in WINDOWS_S.items()
            }

        services = []
        swarms: dict[str, dict] = {}
        shards = []
        trainers = []
        daemons = []
        cluster_ops = {w: 0.0 for w in WINDOWS_S}
        cluster_peers = cluster_tasks = 0.0
        cluster_flow = {w: 0.0 for w in WINDOWS_S}
        cluster_flow_p2p = {w: 0.0 for w in WINDOWS_S}
        cluster_flow_origin = {w: 0.0 for w in WINDOWS_S}
        for r in reps:
            stale = r.stale(now)
            services.append(
                {
                    "service": r.service,
                    "instance": r.instance,
                    "shard": r.shard,
                    "stale": stale,
                    "age_s": round(now - r.last_report, 1),
                    "interval_s": r.interval_s,
                    "build": r.sections.get("build", {}),
                    "endpoints": r.sections.get("endpoints", {}),
                }
            )
            if r.service == "scheduler":
                ops = rates(r, "dragonfly_scheduler_schedule_total")
                if not stale:
                    for w in cluster_ops:
                        cluster_ops[w] += ops[w]
                peers = r.gauge_sum("dragonfly_scheduler_peers") or 0.0
                tasks = r.gauge_sum("dragonfly_scheduler_tasks") or 0.0
                if not stale:
                    cluster_peers += peers
                    cluster_tasks += tasks
                p99 = quantile_from_buckets(
                    r.window_hist(
                        "dragonfly_scheduler_schedule_duration_seconds",
                        WINDOWS_S["5m"],
                        now,
                    ),
                    0.99,
                )
                shard_row = {
                    "shard": r.shard or r.instance,
                    "instance": r.instance,
                    "stale": stale,
                    F_SHARD_SCHEDULE_OPS: ops,
                    F_SHARD_DECISION_P99: round(p99 * 1e3, 2),
                    F_SHARD_ANNOUNCE_OPS: rates(
                        r, "dragonfly_scheduler_announce_peer_total"
                    ),
                    F_SHARD_PEERS: peers,
                    F_SHARD_TASKS: tasks,
                }
                # swarm-observatory rollup: folded per shard so one
                # dfstat call shows swarm shape across the fleet
                rollup = r.sections.get("swarm_rollup") or {}
                if rollup:
                    shard_row[F_SHARD_SWARM_TASKS] = int(rollup.get("tasks", 0))
                    shard_row[F_SHARD_SWARM_PEERS] = int(rollup.get("peers", 0))
                    shard_row[F_SHARD_SWARM_DEPTHS] = dict(
                        rollup.get("depth_hist", {})
                    )
                    shard_row[F_SHARD_SWARM_STRAGGLERS] = int(
                        rollup.get("stragglers", 0)
                    ) + int(rollup.get("stuck", 0))
                shards.append(shard_row)
                if stale:
                    continue  # a dead shard's last swarm view is history
                for swarm in r.sections.get("swarms", []) or []:
                    tid = swarm.get("task_id", "")
                    if not tid:
                        continue
                    merged = swarms.setdefault(
                        tid,
                        {
                            "task_id": tid,
                            F_SWARM_PEERS: 0,
                            F_SWARM_SEEDERS: 0,
                            F_SWARM_DONE_PIECES: 0,
                            F_SWARM_TOTAL_PIECES: 0,
                            F_SWARM_STRAGGLERS: [],
                            "shards": [],
                        },
                    )
                    merged[F_SWARM_PEERS] += int(swarm.get("peers", 0))
                    merged[F_SWARM_SEEDERS] += int(swarm.get("seeders", 0))
                    merged[F_SWARM_DONE_PIECES] += int(swarm.get("done_pieces", 0))
                    merged[F_SWARM_TOTAL_PIECES] = max(
                        merged[F_SWARM_TOTAL_PIECES], int(swarm.get("total_pieces", 0))
                    )
                    merged[F_SWARM_STRAGGLERS] = (
                        merged[F_SWARM_STRAGGLERS] + list(swarm.get("stragglers", []))
                    )[:8]
                    merged["shards"].append(r.shard or r.instance)
            elif r.service == "trainer":
                fit_ts = r.gauge_min("dragonfly_trainer_last_fit_timestamp_seconds")
                trainers.append(
                    {
                        "instance": r.instance,
                        "stale": stale,
                        F_TRAINER_INGEST_RECORDS: rates(
                            r, "dragonfly_trainer_ingest_records_total"
                        ),
                        F_TRAINER_DATASET_BYTES: rates(
                            r, "dragonfly_trainer_dataset_bytes_total"
                        ),
                        F_TRAINER_FIT_FRESHNESS: (
                            round(now - fit_ts, 1) if fit_ts else None
                        ),
                    }
                )
            elif r.service == "daemon":
                flow = rates(r, "dragonfly_flow_bytes_total")
                flow_p2p = rates(r, "dragonfly_flow_p2p_bytes_total")
                flow_origin = rates(r, "dragonfly_flow_origin_bytes_total")
                if not stale:
                    for w in cluster_flow:
                        cluster_flow[w] += flow[w]
                        cluster_flow_p2p[w] += flow_p2p[w]
                        cluster_flow_origin[w] += flow_origin[w]
                daemons.append(
                    {
                        "instance": r.instance,
                        "stale": stale,
                        F_DAEMON_PIECE_BYTES: rates(
                            r, "dragonfly_daemon_piece_traffic_bytes_total"
                        ),
                        F_DAEMON_BACK_TO_SOURCE: rates(
                            r, "dragonfly_daemon_back_to_source_total"
                        ),
                        F_DAEMON_FLOW_BYTES: flow,
                        F_DAEMON_FLOW_P2P_BYTES: flow_p2p,
                        F_DAEMON_FLOW_ORIGIN_BYTES: flow_origin,
                        # per-plane provenance rollup as reported by the
                        # daemon's own ledger (utils/flows section)
                        "flows": r.sections.get("flows", {}),
                    }
                )
        return {
            "ts": now,
            "windows": sorted(WINDOWS_S, key=WINDOWS_S.get),
            "services": sorted(
                services, key=lambda s: (s["service"], s["instance"])
            ),
            "swarms": sorted(swarms.values(), key=lambda s: s["task_id"]),
            "shards": sorted(shards, key=lambda s: s["shard"]),
            "trainers": sorted(trainers, key=lambda t: t["instance"]),
            "daemons": sorted(daemons, key=lambda d: d["instance"]),
            "cluster": {
                F_CLUSTER_SCHEDULE_OPS: {
                    w: round(v, 2) for w, v in cluster_ops.items()
                },
                F_CLUSTER_PEERS: cluster_peers,
                F_CLUSTER_TASKS: cluster_tasks,
                F_CLUSTER_FLOW_BYTES: {
                    w: round(v, 2) for w, v in cluster_flow.items()
                },
                # good-byte fraction per window; None while the ledger
                # has moved nothing in that window
                F_CLUSTER_P2P_EFFICIENCY: {
                    w: (
                        round(
                            cluster_flow_p2p[w]
                            / (cluster_flow_p2p[w] + cluster_flow_origin[w]),
                            4,
                        )
                        if (cluster_flow_p2p[w] + cluster_flow_origin[w]) > 0
                        else None
                    )
                    for w in WINDOWS_S
                },
            },
            "slos": [
                {
                    "name": s.spec.name,
                    "kind": s.spec.kind,
                    "objective": s.spec.objective,
                    "description": s.spec.description,
                    F_SLO_BREACHED: s.breached,
                    "burn": {w: round(b, 3) for w, b in s.burn.items()},
                }
                for s in sorted(self._slos.values(), key=lambda s: s.spec.name)
            ],
        }


class TelemetryService:
    """The ReportTelemetry gRPC surface, bound on the manager's server
    next to the Manager/Diagnose services (one channel serves all)."""

    def __init__(self, plane: TelemetryPlane):
        self.plane = plane

    def ReportTelemetry(self, request, context):
        from dragonfly2_tpu.rpc import gen  # noqa: F401 — flat imports
        import telemetry_pb2  # noqa: E402

        try:
            payload = json.loads(request.payload_json or "{}")
            if not isinstance(payload, dict):
                raise TypeError("payload is not an object")
        except (ValueError, TypeError) as e:
            import grpc

            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad payload: {e}")
        registered, last_seq = self.plane.apply(
            service=request.service,
            instance=request.instance,
            shard=request.shard,
            epoch=request.epoch,
            seq=int(request.seq),
            interval_s=request.interval_s,
            payload=payload,
        )
        return telemetry_pb2.TelemetryAck(registered=registered, last_seq=last_seq)
