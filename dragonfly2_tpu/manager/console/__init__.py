"""Embedded web console (role parity: reference manager/console React
front-end served by the manager, manager/manager.go:61-85). A single
static page with no build step: it drives the same REST API the CLI and
operators use, so everything visible here is reproducible with curl."""

from __future__ import annotations

from pathlib import Path

_HERE = Path(__file__).parent


def index_html() -> bytes:
    return (_HERE / "index.html").read_bytes()
