"""Read-through cache in front of the manager database (role parity:
reference manager/cache — Redis keys in front of GORM lookups with TTL,
invalidated on writes; manager/database + pkg/cache).

``CachedDatabase`` is a drop-in for ``Database``: ``query``/``query_one``
results are cached by (sql, params) and tagged with the tables the
statement reads; any ``execute`` that changes rows invalidates every
cached result touching the tables it writes. The manager's hot path —
dynconfig polls of GetScheduler/ListSchedulers/GetSchedulerClusterConfig
from every scheduler and daemon in the fleet — hits sqlite once per TTL
instead of once per poll, the same pressure-relief the reference buys
with Redis.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any

from dragonfly2_tpu.manager.database import Database

_TABLE_RX = re.compile(r"(?:FROM|INTO|UPDATE|JOIN)\s+([A-Za-z_][A-Za-z0-9_]*)", re.I)


def tables_of(sql: str) -> frozenset[str]:
    """Tables a statement touches (read or write), for tag invalidation."""
    return frozenset(t.lower() for t in _TABLE_RX.findall(sql))


class CachedDatabase:
    """TTL read cache over ``Database`` with write invalidation.

    Correctness stance: a write through THIS wrapper invalidates
    immediately (read-your-writes within the process); concurrent writers
    sharing the sqlite file are bounded by ``ttl`` staleness, same as the
    reference's Redis TTLs.

    The store path is generation-stamped per table: a reader that fetched
    rows before a write landed can never install them after the write's
    invalidation (the classic read-aside race) — its snapshot of the
    table generations no longer matches, so the store is discarded.
    """

    def __init__(self, db: Database, ttl: float = 30.0):
        self.db = db
        self.ttl = ttl
        self._lock = threading.Lock()
        # key -> (expires_at, tables, rows)
        self._entries: dict[tuple, tuple[float, frozenset[str], list[dict]]] = {}
        self._gens: dict[str, int] = {}  # table -> invalidation generation
        self.hits = 0
        self.misses = 0

    # -- reads -----------------------------------------------------------
    def query(self, sql: str, params: tuple = ()) -> list[dict[str, Any]]:
        key = (sql, params)
        tabs = tables_of(sql)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry[0] > time.monotonic():
                self.hits += 1
                return [dict(r) for r in entry[2]]  # callers may mutate rows
            self.misses += 1
            snapshot = {t: self._gens.get(t, 0) for t in tabs}
        rows = self.db.query(sql, params)
        with self._lock:
            if all(self._gens.get(t, 0) == g for t, g in snapshot.items()):
                self._entries[key] = (time.monotonic() + self.ttl, tabs, rows)
            # else: a write to one of these tables raced the read — the
            # rows may predate it, so they must not outlive this call
        return [dict(r) for r in rows]

    def query_one(self, sql: str, params: tuple = ()) -> dict[str, Any] | None:
        rows = self.query(sql, params)
        return rows[0] if rows else None

    # -- writes ----------------------------------------------------------
    def execute(self, sql: str, params: tuple = ()):
        cur = self.db.execute(sql, params)
        # a 0-row UPDATE/DELETE changed nothing — keep the cache warm
        # (ListSchedulers' _expire_stale sweep runs on every poll and
        # usually matches nothing; unconditional invalidation would make
        # the hot path miss every time). rowcount is -1 for non-DML —
        # invalidate conservatively then.
        if cur.rowcount != 0:
            self.invalidate(*tables_of(sql))
        return cur

    def invalidate(self, *tables: str) -> None:
        """Drop every cached result reading any of ``tables`` (all tables
        when called with none)."""
        targets = {t.lower() for t in tables}
        with self._lock:
            if not targets:
                targets = set(self._gens) | {
                    t for _, tabs, _ in self._entries.values() for t in tabs
                }
            for t in targets:
                self._gens[t] = self._gens.get(t, 0) + 1
            dead = [
                k
                for k, (_, tabs, _) in self._entries.items()
                if not targets or tabs & targets
            ]
            for k in dead:
                del self._entries[k]

    # -- passthrough -----------------------------------------------------
    def transaction(self):
        # leasing-style select-then-update must see live rows: flush all
        # cached reads so queries inside the lock go to the database
        self.invalidate()
        return self.db.transaction()

    def close(self) -> None:
        self.db.close()

    def ensure_default_cluster(self) -> int:
        self.invalidate("scheduler_clusters")
        return self.db.ensure_default_cluster()

    dumps = staticmethod(Database.dumps)
    loads = staticmethod(Database.loads)
