"""Manager gRPC service (reference manager/rpcserver/manager_server_v1.go
+ v2): scheduler/seed-peer registry, keepalive, dynconfig serving, and the
model registry RPCs the trainer and scheduler consume."""

from __future__ import annotations

import json
import time

import grpc

from dragonfly2_tpu.rpc import gen  # noqa: F401
import manager_pb2  # noqa: E402

from dragonfly2_tpu.manager.database import Database
from dragonfly2_tpu.manager.models_registry import ModelRegistry
from dragonfly2_tpu.manager import metrics as M
from dragonfly2_tpu.utils import dflog

logger = dflog.get("manager.rpc")

from dragonfly2_tpu.rpc.glue import MANAGER_SERVICE as SERVICE_NAME

# schedulers silent longer than this flip to inactive (reference keepalive)
KEEPALIVE_TIMEOUT = 60.0


class ManagerService:
    def __init__(
        self,
        db: Database,
        models: ModelRegistry,
        ca=None,
        ca_token: str = "",
        fleet_kv=None,
    ):
        from dragonfly2_tpu.manager.searcher import new_searcher

        self.db = db
        self.models = models
        self.searcher = new_searcher()  # plugin seam (utils/dfplugin)
        self.default_cluster_id = db.ensure_default_cluster()
        # utils.issuer.CertificateAuthority for IssueCertificate; None =
        # dynamic issuance disabled (static cert files only). ca_token:
        # cluster registration secret required from requesters ('' = open
        # — dev mode only; production sets one)
        self.ca = ca
        self.ca_token = ca_token
        # scheduler-fleet view (scheduler/fleet.py): a KV store holding
        # the fleet's leased member set — when live leases exist, the
        # dynconfig scheduler list is scoped to them, so daemons polling
        # the manager also converge within one lease TTL of a member
        # death instead of the 60s keepalive timeout. None/empty fleet →
        # the keepalive-based registry stands alone (compat).
        self.fleet_kv = fleet_kv

    # -- scheduler registry ------------------------------------------------
    def UpdateScheduler(self, request, context):
        now = time.time()
        cluster_id = request.scheduler_cluster_id or self.default_cluster_id
        self.db.execute(
            "INSERT INTO schedulers (hostname, ip, port, idc, location, state,"
            " scheduler_cluster_id, last_keepalive, created_at, updated_at)"
            " VALUES (?, ?, ?, ?, ?, 'active', ?, ?, ?, ?)"
            " ON CONFLICT(hostname, ip, scheduler_cluster_id) DO UPDATE SET"
            " port = excluded.port, idc = excluded.idc, location = excluded.location,"
            " state = 'active', last_keepalive = excluded.last_keepalive,"
            " updated_at = excluded.updated_at",
            (request.hostname, request.ip, request.port, request.idc,
             request.location, cluster_id, now, now, now),
        )
        return self._scheduler(request.hostname, request.ip, cluster_id, context)

    def GetScheduler(self, request, context):
        cluster_id = request.scheduler_cluster_id or self.default_cluster_id
        return self._scheduler(request.hostname, request.ip, cluster_id, context)

    def _scheduler(self, hostname, ip, cluster_id, context):
        r = self.db.query_one(
            "SELECT * FROM schedulers WHERE hostname = ? AND ip = ? AND scheduler_cluster_id = ?",
            (hostname, ip, cluster_id),
        )
        if r is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"scheduler {hostname}/{ip} not found")
        return manager_pb2.Scheduler(
            id=r["id"], hostname=r["hostname"], ip=r["ip"], port=r["port"],
            idc=r["idc"], location=r["location"], state=r["state"],
            scheduler_cluster_id=r["scheduler_cluster_id"],
        )

    def ListSchedulers(self, request, context):
        """Active schedulers for a joining peer. When the peer carries
        location hints and several clusters exist, the searcher picks the
        best-matching cluster and only its schedulers are returned
        (reference searcher.go find-matching-cluster in ListSchedulers)."""
        self._expire_stale()
        rows = self.db.query("SELECT * FROM schedulers WHERE state = 'active'")
        cluster = self._match_cluster(request)
        if cluster is not None:
            scoped = [r for r in rows if r["scheduler_cluster_id"] == cluster.id]
            if scoped:
                rows = scoped
        live = self._fleet_members()
        if live:
            # fleet view in dynconfig: only members holding a live lease
            # are handed to daemons. An empty/unreadable lease plane
            # falls through to the keepalive registry — a KV outage must
            # not strand every daemon schedulerless.
            leased = [r for r in rows if f"{r['ip']}:{r['port']}" in live]
            if leased:
                rows = leased
            elif rows:
                # leases exist but match NO registered row: an
                # address-mismatch misconfiguration (lease advertises a
                # port the registration didn't carry) that silently
                # disables fast convergence — say so instead
                logger.warning(
                    "fleet leases %s match no registered scheduler %s;"
                    " serving the keepalive registry unscoped",
                    sorted(live),
                    sorted(f"{r['ip']}:{r['port']}" for r in rows),
                )
        return manager_pb2.ListSchedulersResponse(
            schedulers=[
                manager_pb2.Scheduler(
                    id=r["id"], hostname=r["hostname"], ip=r["ip"], port=r["port"],
                    idc=r["idc"], location=r["location"], state=r["state"],
                    scheduler_cluster_id=r["scheduler_cluster_id"],
                )
                for r in rows
            ]
        )

    def _match_cluster(self, request):
        if not (request.ip or request.idc or request.location):
            return None
        from dragonfly2_tpu.manager.searcher import Cluster, ClusterScope, PeerInfo

        crows = self.db.query("SELECT * FROM scheduler_clusters ORDER BY id")
        if len(crows) < 2:
            return None
        clusters = []
        for r in crows:
            scopes = Database.loads(r["scopes"]) or {}
            clusters.append(
                Cluster(
                    id=r["id"],
                    name=r["name"],
                    scopes=ClusterScope(
                        idc=scopes.get("idc", ""),
                        location=scopes.get("location", ""),
                        cidrs=scopes.get("cidrs", []),
                    ),
                    is_default=bool(r["is_default"]),
                )
            )
        return self.searcher.find_matching_cluster(
            clusters,
            PeerInfo(ip=request.ip, idc=request.idc, location=request.location),
        )

    def _fleet_members(self) -> "set[str] | None":
        if self.fleet_kv is None:
            return None
        try:
            from dragonfly2_tpu.scheduler.fleet import read_members

            return set(read_members(self.fleet_kv))
        except Exception as e:
            logger.warning("fleet membership read failed: %s", e)
            return None

    def _expire_stale(self) -> None:
        cutoff = time.time() - KEEPALIVE_TIMEOUT
        self.db.execute(
            "UPDATE schedulers SET state = 'inactive' WHERE last_keepalive < ? AND state = 'active'",
            (cutoff,),
        )
        self.db.execute(
            "UPDATE seed_peers SET state = 'inactive' WHERE last_keepalive < ? AND state = 'active'",
            (cutoff,),
        )

    # -- seed peers --------------------------------------------------------
    def UpdateSeedPeer(self, request, context):
        now = time.time()
        cluster_id = request.seed_peer_cluster_id or 1
        self.db.execute(
            "INSERT OR IGNORE INTO seed_peer_clusters (id, name, created_at, updated_at)"
            " VALUES (?, ?, ?, ?)",
            (cluster_id, f"cluster-{cluster_id}", now, now),
        )
        self.db.execute(
            "INSERT INTO seed_peers (hostname, ip, port, download_port, type, idc,"
            " location, state, seed_peer_cluster_id, last_keepalive, created_at, updated_at)"
            " VALUES (?, ?, ?, ?, ?, ?, ?, 'active', ?, ?, ?, ?)"
            " ON CONFLICT(hostname, ip, seed_peer_cluster_id) DO UPDATE SET"
            " port = excluded.port, download_port = excluded.download_port,"
            " type = excluded.type, state = 'active',"
            " last_keepalive = excluded.last_keepalive, updated_at = excluded.updated_at",
            (request.hostname, request.ip, request.port, request.download_port,
             request.type or "super", request.idc, request.location, cluster_id, now, now, now),
        )
        r = self.db.query_one(
            "SELECT * FROM seed_peers WHERE hostname = ? AND ip = ? AND seed_peer_cluster_id = ?",
            (request.hostname, request.ip, cluster_id),
        )
        return manager_pb2.SeedPeer(
            id=r["id"], hostname=r["hostname"], ip=r["ip"], port=r["port"],
            download_port=r["download_port"], type=r["type"], idc=r["idc"],
            location=r["location"], seed_peer_cluster_id=r["seed_peer_cluster_id"],
        )

    # -- keepalive ---------------------------------------------------------
    def KeepAlive(self, request_iterator, context):
        for req in request_iterator:
            now = time.time()
            # cluster-scoped: the same hostname/ip may be registered in
            # several clusters (UNIQUE(hostname, ip, cluster_id)); a
            # keepalive must only revive its own cluster's row.
            # cluster_id 0 (unset) keeps the legacy any-cluster match.
            if req.source_type == "scheduler":
                sql = (
                    "UPDATE schedulers SET last_keepalive = ?, state = 'active'"
                    " WHERE hostname = ? AND ip = ?"
                )
                args: tuple = (now, req.hostname, req.ip)
                if req.cluster_id:
                    sql += " AND scheduler_cluster_id = ?"
                    args += (req.cluster_id,)
                self.db.execute(sql, args)
            elif req.source_type == "seed_peer":
                sql = (
                    "UPDATE seed_peers SET last_keepalive = ?, state = 'active'"
                    " WHERE hostname = ? AND ip = ?"
                )
                args = (now, req.hostname, req.ip)
                if req.cluster_id:
                    sql += " AND seed_peer_cluster_id = ?"
                    args += (req.cluster_id,)
                self.db.execute(sql, args)
        return manager_pb2.Empty()

    # -- dynconfig ---------------------------------------------------------
    def GetSchedulerClusterConfig(self, request, context):
        cluster_id = request.scheduler_cluster_id or self.default_cluster_id
        r = self.db.query_one(
            "SELECT config FROM scheduler_clusters WHERE id = ?", (cluster_id,)
        )
        if r is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"cluster {cluster_id} not found")
        cfg = Database.loads(r["config"])
        return manager_pb2.SchedulerClusterConfig(
            candidate_parent_limit=int(cfg.get("candidate_parent_limit", 0)),
            filter_parent_limit=int(cfg.get("filter_parent_limit", 0)),
            json=r["config"],
        )

    # -- async jobs (manager is the queue of record; scheduler workers
    # poll ListPendingJobs — reference internal/job machinery on Redis) --
    def CreateJob(self, request, context):
        if request.type not in ("preheat", "sync_peers", "recommend_seeds"):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"unknown job type {request.type}")
        now = time.time()
        cur = self.db.execute(
            "INSERT INTO jobs (type, state, args, scheduler_cluster_id, created_at, updated_at)"
            " VALUES (?, 'queued', ?, ?, ?, ?)",
            (
                request.type,
                request.args_json or "{}",
                request.scheduler_cluster_id or self.default_cluster_id,
                now,
                now,
            ),
        )
        return self._job(self.db.query_one("SELECT * FROM jobs WHERE id = ?", (cur.lastrowid,)))

    def GetJob(self, request, context):
        r = self.db.query_one("SELECT * FROM jobs WHERE id = ?", (request.id,))
        if r is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"job {request.id} not found")
        return self._job(r)

    # a worker that leased a job but never posted a result is presumed
    # dead after this long; the job is re-leased to the next poller
    JOB_LEASE_TIMEOUT = 300.0

    def ListPendingJobs(self, request, context):
        """Lease queued jobs (and expired running leases) to the polling
        worker atomically so two workers can't both execute one."""
        cluster_id = request.scheduler_cluster_id or self.default_cluster_id
        worker = f"{request.ip}_{request.hostname}"
        now = time.time()
        stale = now - self.JOB_LEASE_TIMEOUT
        with self.db.transaction():
            rows = self.db.query(
                "SELECT * FROM jobs WHERE scheduler_cluster_id = ? AND"
                " (state = 'queued' OR (state = 'running' AND updated_at < ?))"
                " ORDER BY id LIMIT 16",
                (cluster_id, stale),
            )
            if rows:
                ids = [r["id"] for r in rows]
                self.db.execute(
                    "UPDATE jobs SET state = 'running', leased_by = ?, updated_at = ?"
                    f" WHERE id IN ({','.join('?' * len(ids))})",
                    (worker, now, *ids),
                )
                for r in rows:
                    r["state"] = "running"
        return manager_pb2.ListPendingJobsResponse(jobs=[self._job(r) for r in rows])

    def UpdateJobResult(self, request, context):
        if request.state not in ("succeeded", "failed"):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad job state {request.state}")
        worker = f"{request.ip}_{request.hostname}"
        cur = self.db.execute(
            "UPDATE jobs SET state = ?, result = ?, updated_at = ?"
            " WHERE id = ? AND state = 'running' AND leased_by = ?",
            (request.state, request.result_json or "{}", time.time(), request.id, worker),
        )
        r = self.db.query_one("SELECT * FROM jobs WHERE id = ?", (request.id,))
        if r is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"job {request.id} not found")
        if cur.rowcount == 0:
            # lease lost (timed out and re-leased) — the poster's result
            # is stale; report the authoritative row instead of writing
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"job {request.id} lease not held by {worker} (state {r['state']})",
            )
        if r["type"] == "sync_peers" and request.state == "succeeded":
            self._materialize_peers(r)
        return self._job(r)

    def _materialize_peers(self, job_row) -> None:
        """sync_peers result → the peers table the REST surface reads
        (reference manager/models.Peer refreshed by the sync-peers job,
        handlers/peer.go). Full refresh per cluster: hosts gone from the
        scheduler's view disappear here too.

        The result is WORKER-SUPPLIED data: every row is validated and
        coerced BEFORE the old rows are deleted (execute() auto-commits,
        so a mid-loop crash would otherwise wipe the cluster's peers
        with no rollback), and a malformed result is logged and skipped
        — it must never fail the RPC after the job row committed."""
        try:
            result = json.loads(job_row["result"] or "{}")
            if not isinstance(result, dict):
                raise TypeError(f"result is {type(result).__name__}, not an object")
            # an empty hosts LIST is a legitimate refresh-to-zero (the
            # scheduler sees no hosts); a missing/wrong-shape field is not
            hosts = result.get("hosts")
            if not isinstance(hosts, list):
                raise TypeError("result.hosts is not a list")
            cluster = job_row["scheduler_cluster_id"]
            now = time.time()
            rows = [
                (
                    str(h.get("id", "")), str(h.get("hostname", "")),
                    str(h.get("ip", "")), str(h.get("type", "normal")),
                    int(h.get("peer_count") or 0), int(h.get("upload_count") or 0),
                    cluster, now, now,
                )
                for h in hosts
                if isinstance(h, dict)
            ]
        except (ValueError, TypeError) as e:
            logger.warning(
                "sync_peers job %s result unusable, peers table unchanged: %s",
                job_row["id"], e,
            )
            return
        with self.db.transaction():
            self.db.execute(
                "DELETE FROM peers WHERE scheduler_cluster_id = ?", (cluster,)
            )
            for row in rows:
                self.db.execute(
                    "INSERT OR REPLACE INTO peers (host_id, hostname, ip, type,"
                    " state, peer_count, upload_count, scheduler_cluster_id,"
                    " created_at, updated_at) VALUES (?, ?, ?, ?, 'active', ?, ?, ?, ?, ?)",
                    row,
                )

    @staticmethod
    def _job(r) -> manager_pb2.Job:
        return manager_pb2.Job(
            id=r["id"],
            type=r["type"],
            state=r["state"],
            args_json=r["args"],
            result_json=r["result"],
            scheduler_cluster_id=r["scheduler_cluster_id"],
            created_at_ns=int(r["created_at"] * 1e9),
        )

    # -- model registry ----------------------------------------------------
    def CreateModel(self, request, context):
        M.MODEL_CREATED_TOTAL.labels(request.type or "unknown").inc()
        evaluation = {
            "precision": request.evaluation.precision,
            "recall": request.evaluation.recall,
            "f1": request.evaluation.f1,
            "mse": request.evaluation.mse,
            "mae": request.evaluation.mae,
        }
        row = self.models.create(
            model_id=request.model_id,
            model_type=request.type,
            weights=request.weights,
            evaluation=evaluation,
            ip=request.ip,
            hostname=request.hostname,
            scheduler_cluster_id=request.scheduler_cluster_id or self.default_cluster_id,
        )
        return self._model(row)

    def GetModel(self, request, context):
        row = self.models.get(request.model_id, request.version)
        if row is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"model {request.model_id} v{request.version} not found",
            )
        return self._model(row)

    def GetModelWeights(self, request, context):
        """Weights blob for the serving side (scheduler ml evaluator).
        version 0 = the active version (reference: the scheduler's
        would-be Triton ModelInfer hop — here weights come down once and
        inference runs in-process, manager/service/model.go:109 activation
        gating applies via the version-0 lookup)."""
        row = self.models.get(request.model_id, request.version)
        if row is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"model {request.model_id} v{request.version} not found",
            )
        try:
            weights = self.models.load_weights(request.model_id, row.version)
        except (KeyError, OSError) as e:
            context.abort(grpc.StatusCode.INTERNAL, f"weights load failed: {e}")
        return manager_pb2.ModelWeights(
            model_id=row.model_id,
            version=row.version,
            type=row.type,
            weights=weights,
        )

    def ListModels(self, request, context):
        rows = self.models.list(request.scheduler_cluster_id or None)
        return manager_pb2.ListModelsResponse(models=[self._model(r) for r in rows])

    def UpdateModel(self, request, context):
        if request.state == "active":
            try:
                row = self.models.activate(request.model_id, request.version)
            except KeyError as e:
                context.abort(grpc.StatusCode.NOT_FOUND, str(e))
            return self._model(row)
        if request.state == "inactive":
            # explicit deactivation is an operator decision the serve
            # path must honor (the scheduler's refresher withdraws the
            # model / serving slot on the next poll) — silently ignoring
            # it left "deactivated" models serving forever
            try:
                row = self.models.deactivate(request.model_id, request.version)
            except KeyError as e:
                context.abort(grpc.StatusCode.NOT_FOUND, str(e))
            return self._model(row)
        row = self.models.get(request.model_id, request.version)
        if row is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"model {request.model_id} not found")
        return self._model(row)

    # -- certificate issuance (reference securityv1 CertificateService,
    # pkg/rpc/security/client/client_v1.go:99-117) ----------------------
    def IssueCertificate(self, request, context):
        if self.ca is None:
            context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "dynamic certificate issuance is not enabled on this manager",
            )
        import hmac as _hmac

        if self.ca_token and not _hmac.compare_digest(request.token, self.ca_token):
            # wrong/missing cluster token: whoever asks gets NOTHING
            # signed — a CA that signs arbitrary identities for anyone
            # with network reach hands out cluster-wide impersonation
            context.abort(
                grpc.StatusCode.PERMISSION_DENIED,
                "certificate issuance requires the cluster registration token",
            )
        days = int(request.validity_days) or 180
        if days > 366:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"validity {days}d exceeds the 366d cap",
            )
        try:
            leaf = self.ca.issue_from_csr(request.csr_pem.encode(), validity_days=days)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"unparsable CSR: {e}")
        return manager_pb2.CertificateResponse(
            certificate_chain=[leaf.decode(), self.ca.cert_pem.decode()]
        )

    @staticmethod
    def _model(row) -> manager_pb2.Model:
        ev = row.evaluation
        return manager_pb2.Model(
            model_id=row.model_id,
            type=row.type,
            version=row.version,
            state=row.state,
            evaluation=manager_pb2.ModelEvaluation(
                precision=ev.get("precision", 0.0),
                recall=ev.get("recall", 0.0),
                f1=ev.get("f1", 0.0),
                mse=ev.get("mse", 0.0),
                mae=ev.get("mae", 0.0),
            ),
            object_key=row.object_key,
            created_at_ns=int(row.created_at * 1e9),
            updated_at_ns=int(row.updated_at * 1e9),
        )


class ManagerGrpcClientAdapter:
    """Adapts the trainer's ManagerClient protocol onto the gRPC client —
    serializes params and fills CreateModelRequest."""

    def __init__(self, channel):
        from dragonfly2_tpu.rpc.glue import ServiceClient

        self._client = ServiceClient(channel, SERVICE_NAME)

    def create_model(self, model_id, model_type, ip, hostname, params, evaluation):
        from dragonfly2_tpu.trainer.serving import serialize_params

        self._client.CreateModel(
            manager_pb2.CreateModelRequest(
                model_id=model_id,
                type=model_type,
                ip=ip,
                hostname=hostname,
                weights=serialize_params(params),
                evaluation=manager_pb2.ModelEvaluation(
                    precision=evaluation.get("precision", 0.0),
                    recall=evaluation.get("recall", 0.0),
                    f1=evaluation.get("f1", 0.0),
                    mse=evaluation.get("mse", 0.0),
                    mae=evaluation.get("mae", 0.0),
                ),
            )
        )

    def keepalive(self, source_type, hostname, ip, cluster_id=0):
        self._client.KeepAlive(
            iter(
                [
                    manager_pb2.KeepAliveRequest(
                        source_type=source_type,
                        hostname=hostname,
                        ip=ip,
                        cluster_id=int(cluster_id or 0),
                    )
                ]
            )
        )
