"""Manager database on sqlite3 (role parity: reference manager/database —
GORM over MySQL/Postgres; this environment has no DB server, and sqlite
keeps the same relational shape with zero ops).

Tables: scheduler_clusters, schedulers, seed_peer_clusters, seed_peers,
models (the registry rows; weight blobs live in object storage, reference
manager/models/model.go:19-46), applications, configs.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from pathlib import Path
from typing import Any

_SCHEMA = """
CREATE TABLE IF NOT EXISTS scheduler_clusters (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  config TEXT NOT NULL DEFAULT '{}',
  client_config TEXT NOT NULL DEFAULT '{}',
  scopes TEXT NOT NULL DEFAULT '{}',
  is_default INTEGER NOT NULL DEFAULT 0,
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS schedulers (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  hostname TEXT NOT NULL,
  ip TEXT NOT NULL,
  port INTEGER NOT NULL,
  idc TEXT NOT NULL DEFAULT '',
  location TEXT NOT NULL DEFAULT '',
  state TEXT NOT NULL DEFAULT 'inactive',
  scheduler_cluster_id INTEGER NOT NULL,
  last_keepalive REAL NOT NULL DEFAULT 0,
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL,
  UNIQUE(hostname, ip, scheduler_cluster_id)
);
CREATE TABLE IF NOT EXISTS seed_peer_clusters (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  config TEXT NOT NULL DEFAULT '{}',
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS seed_peers (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  hostname TEXT NOT NULL,
  ip TEXT NOT NULL,
  port INTEGER NOT NULL,
  download_port INTEGER NOT NULL DEFAULT 0,
  type TEXT NOT NULL DEFAULT 'super',
  idc TEXT NOT NULL DEFAULT '',
  location TEXT NOT NULL DEFAULT '',
  state TEXT NOT NULL DEFAULT 'inactive',
  seed_peer_cluster_id INTEGER NOT NULL,
  last_keepalive REAL NOT NULL DEFAULT 0,
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL,
  UNIQUE(hostname, ip, seed_peer_cluster_id)
);
CREATE TABLE IF NOT EXISTS models (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  model_id TEXT NOT NULL,
  type TEXT NOT NULL,
  version INTEGER NOT NULL,
  state TEXT NOT NULL DEFAULT 'inactive',
  evaluation TEXT NOT NULL DEFAULT '{}',
  object_key TEXT NOT NULL,
  ip TEXT NOT NULL DEFAULT '',
  hostname TEXT NOT NULL DEFAULT '',
  scheduler_cluster_id INTEGER NOT NULL DEFAULT 0,
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL DEFAULT 0,
  UNIQUE(model_id, version)
);
CREATE TABLE IF NOT EXISTS jobs (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  type TEXT NOT NULL,
  state TEXT NOT NULL DEFAULT 'queued',
  args TEXT NOT NULL DEFAULT '{}',
  result TEXT NOT NULL DEFAULT '{}',
  scheduler_cluster_id INTEGER NOT NULL DEFAULT 0,
  leased_by TEXT NOT NULL DEFAULT '',
  group_id TEXT NOT NULL DEFAULT '',
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS users (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  email TEXT NOT NULL DEFAULT '',
  password_salt TEXT NOT NULL DEFAULT '',
  password_hash TEXT NOT NULL DEFAULT '',
  role TEXT NOT NULL DEFAULT 'guest',
  state TEXT NOT NULL DEFAULT 'enabled',
  oauth_provider TEXT NOT NULL DEFAULT '',
  oauth_subject TEXT NOT NULL DEFAULT '',
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS personal_access_tokens (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  user_id INTEGER NOT NULL,
  name TEXT NOT NULL,
  token_hash TEXT UNIQUE NOT NULL,
  state TEXT NOT NULL DEFAULT 'active',
  expires_at REAL NOT NULL DEFAULT 0,
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS applications (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  url TEXT NOT NULL DEFAULT '',
  priority TEXT NOT NULL DEFAULT '{}',
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS settings (
  key TEXT PRIMARY KEY,
  value TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS configs (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  value TEXT NOT NULL DEFAULT '',
  bio TEXT NOT NULL DEFAULT '',
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS peers (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  host_id TEXT NOT NULL,
  hostname TEXT NOT NULL DEFAULT '',
  ip TEXT NOT NULL DEFAULT '',
  type TEXT NOT NULL DEFAULT 'normal',
  state TEXT NOT NULL DEFAULT 'active',
  peer_count INTEGER NOT NULL DEFAULT 0,
  upload_count INTEGER NOT NULL DEFAULT 0,
  scheduler_cluster_id INTEGER NOT NULL DEFAULT 0,
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL,
  UNIQUE(host_id, scheduler_cluster_id)
);
CREATE TABLE IF NOT EXISTS oauth (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  bio TEXT NOT NULL DEFAULT '',
  client_id TEXT NOT NULL,
  client_secret TEXT NOT NULL,
  redirect_url TEXT NOT NULL DEFAULT '',
  auth_url TEXT NOT NULL,
  token_url TEXT NOT NULL,
  userinfo_url TEXT NOT NULL,
  scopes TEXT NOT NULL DEFAULT '',
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL
);
"""


class Database:
    def __init__(self, path: str | Path = ":memory:"):
        self._conn = sqlite3.connect(str(path), check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        self._conn.executescript(_SCHEMA)
        self._migrate()
        self._lock = threading.RLock()

    def _migrate(self) -> None:
        """Additive column migrations for databases created by earlier
        versions (CREATE TABLE IF NOT EXISTS never alters existing
        tables)."""
        for table, column, decl in [
            ("models", "updated_at", "REAL NOT NULL DEFAULT 0"),
            # group jobs: one logical job fanned to N scheduler clusters
            # (reference manager/job createGroupJob / machinery groups)
            ("jobs", "group_id", "TEXT NOT NULL DEFAULT ''"),
            # OAuth identity linkage: which provider+subject this user
            # belongs to ('' = local password account). Sign-in matches
            # on these, never on the display name.
            ("users", "oauth_provider", "TEXT NOT NULL DEFAULT ''"),
            ("users", "oauth_subject", "TEXT NOT NULL DEFAULT ''"),
        ]:
            cols = {r[1] for r in self._conn.execute(f"PRAGMA table_info({table})")}
            if column not in cols:
                self._conn.execute(f"ALTER TABLE {table} ADD COLUMN {column} {decl}")
        self._conn.commit()

    def execute(self, sql: str, params: tuple = ()) -> sqlite3.Cursor:
        with self._lock:
            cur = self._conn.execute(sql, params)
            self._conn.commit()
            return cur

    def transaction(self):
        """Hold the DB lock across several statements (e.g. job leasing's
        select-then-update must be atomic against other workers)."""
        return self._lock

    def query(self, sql: str, params: tuple = ()) -> list[dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._conn.execute(sql, params).fetchall()]

    def query_one(self, sql: str, params: tuple = ()) -> dict[str, Any] | None:
        rows = self.query(sql, params)
        return rows[0] if rows else None

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    # -- helpers ----------------------------------------------------------
    def ensure_default_cluster(self) -> int:
        row = self.query_one("SELECT id FROM scheduler_clusters WHERE is_default = 1")
        if row:
            return row["id"]
        now = time.time()
        cur = self.execute(
            "INSERT INTO scheduler_clusters (name, is_default, created_at, updated_at)"
            " VALUES ('default', 1, ?, ?)",
            (now, now),
        )
        return cur.lastrowid

    @staticmethod
    def dumps(obj: Any) -> str:
        return json.dumps(obj, separators=(",", ":"))

    @staticmethod
    def loads(s: str) -> Any:
        return json.loads(s) if s else {}
