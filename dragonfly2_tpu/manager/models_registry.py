"""Model registry: versioned, state-gated storage of trained models
(reference manager/rpcserver/manager_server_v1.go:800-899 CreateModel,
manager/service/model.go:35-190, manager/models/model.go:19-46).

Every upload creates a new *inactive* version with its weights blob in
object storage under ``models/<model_id>/<version>/model.npz`` (the
reference's `models/<id>/<ver>/model.graphdef` + Triton config, minus the
Triton detour — serving here is in-process XLA). Activation flips one
version to active and deactivates the rest; serving only ever loads the
active version, so a failed fit can never poison serving.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from dragonfly2_tpu.manager.database import Database
from dragonfly2_tpu.manager.objectstorage import ObjectStorage

MODELS_BUCKET = "models"

STATE_INACTIVE = "inactive"
STATE_ACTIVE = "active"


@dataclass
class ModelRow:
    model_id: str
    type: str
    version: int
    state: str
    evaluation: dict
    object_key: str
    ip: str = ""
    hostname: str = ""
    scheduler_cluster_id: int = 0
    created_at: float = 0.0
    updated_at: float = 0.0  # last state flip (activation recency)


class ModelRegistry:
    def __init__(self, db: Database, storage: ObjectStorage):
        self.db = db
        self.storage = storage
        self.storage.create_bucket(MODELS_BUCKET)
        import threading

        self._lock = threading.Lock()  # version allocation + state flips
        self._reserved: dict[str, int] = {}  # model_id → highest reserved version

    def create(
        self,
        model_id: str,
        model_type: str,
        weights: bytes,
        evaluation: dict,
        ip: str = "",
        hostname: str = "",
        scheduler_cluster_id: int = 0,
    ) -> ModelRow:
        """New inactive version: weights → object storage, row → DB.
        The version number is *reserved* under the lock, but the (possibly
        slow) weight upload happens outside it so concurrent uploads of
        unrelated models don't serialize behind the slowest put_object;
        the row is only inserted once the blob exists, so an inserted
        version is always loadable. A failed upload just skips a version
        number."""
        with self._lock:
            row = self.db.query_one(
                "SELECT MAX(version) AS v FROM models WHERE model_id = ?", (model_id,)
            )
            version = max(row["v"] or 0, self._reserved.get(model_id, 0)) + 1
            self._reserved[model_id] = version
        key = f"{model_id}/{version}/model.npz"
        self.storage.put_object(MODELS_BUCKET, key, weights)
        with self._lock:
            self.db.execute(
                "INSERT INTO models (model_id, type, version, state, evaluation,"
                " object_key, ip, hostname, scheduler_cluster_id, created_at,"
                " updated_at)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                (
                    model_id,
                    model_type,
                    version,
                    STATE_INACTIVE,
                    Database.dumps(evaluation),
                    key,
                    ip,
                    hostname,
                    scheduler_cluster_id,
                    time.time(),
                    time.time(),
                ),
            )
        return self.get(model_id, version)

    def get(self, model_id: str, version: int = 0) -> ModelRow | None:
        """version 0 → the active version."""
        if version == 0:
            r = self.db.query_one(
                "SELECT * FROM models WHERE model_id = ? AND state = ?",
                (model_id, STATE_ACTIVE),
            )
        else:
            r = self.db.query_one(
                "SELECT * FROM models WHERE model_id = ? AND version = ?",
                (model_id, version),
            )
        return self._row(r) if r else None

    def list(self, scheduler_cluster_id: int | None = None) -> list[ModelRow]:
        if scheduler_cluster_id:
            rows = self.db.query(
                "SELECT * FROM models WHERE scheduler_cluster_id = ? ORDER BY model_id, version",
                (scheduler_cluster_id,),
            )
        else:
            rows = self.db.query("SELECT * FROM models ORDER BY model_id, version")
        return [self._row(r) for r in rows]

    def activate(self, model_id: str, version: int) -> ModelRow:
        """Flip one version active, everything else inactive (reference
        manager/service/model.go:109 updateModelStateToActive).
        ``version=0`` (proto3 default for an unset field) means "the
        currently active version" — resolve it to a concrete version
        first, else the deactivate-all would strand the model with no
        active version."""
        target = self.get(model_id, version)
        if target is None:
            raise KeyError(f"model {model_id} version {version} not found")
        version = target.version
        now = time.time()
        with self._lock:
            self.db.execute(
                "UPDATE models SET state = ? WHERE model_id = ?", (STATE_INACTIVE, model_id)
            )
            # updated_at records ACTIVATION recency: the model refresher
            # must install "most recently activated", not "most recently
            # created" — re-activating an older model is an operator
            # decision that has to take effect (round-2 ADVICE b)
            self.db.execute(
                "UPDATE models SET state = ?, updated_at = ? WHERE model_id = ? AND version = ?",
                (STATE_ACTIVE, now, model_id, version),
            )
        return self.get(model_id, version)

    def deactivate(self, model_id: str, version: int) -> ModelRow:
        """Explicit operator deactivation; stamps updated_at (the 'last
        state flip' the proto documents) under the same lock as
        activate."""
        target = self.get(model_id, version)
        if target is None:
            raise KeyError(f"model {model_id} version {version} not found")
        with self._lock:
            self.db.execute(
                "UPDATE models SET state = ?, updated_at = ? WHERE model_id = ? AND version = ?",
                (STATE_INACTIVE, time.time(), model_id, target.version),
            )
        return self.get(model_id, target.version)

    def delete(self, model_id: str, version: int) -> None:
        row = self.get(model_id, version)
        if row is None:
            return
        self.storage.delete_object(MODELS_BUCKET, row.object_key)
        self.db.execute(
            "DELETE FROM models WHERE model_id = ? AND version = ?", (model_id, version)
        )

    def load_weights(self, model_id: str, version: int = 0) -> bytes:
        row = self.get(model_id, version)
        if row is None:
            raise KeyError(f"model {model_id} v{version} not found")
        return self.storage.get_object(MODELS_BUCKET, row.object_key)

    @staticmethod
    def _row(r: dict) -> ModelRow:
        return ModelRow(
            model_id=r["model_id"],
            type=r["type"],
            version=r["version"],
            state=r["state"],
            evaluation=Database.loads(r["evaluation"]),
            object_key=r["object_key"],
            ip=r["ip"],
            hostname=r["hostname"],
            scheduler_cluster_id=r["scheduler_cluster_id"],
            created_at=r["created_at"],
            updated_at=r.get("updated_at", 0.0),
        )
