"""Transformer encoder with ring attention for long piece sequences.

Sequence-parallel alternative to the GRU for very long download histories
(tasks with tens of thousands of pieces): the sequence is sharded over the
mesh's `sp` axis and attention runs blockwise over the ICI ring
(ops.ring.ring_attention), so context length scales with the number of
chips instead of one chip's HBM.
"""

# dfanalyze: device-hot — jitted/device-feeding compute plane

from __future__ import annotations

import jax
import jax.numpy as jnp

from dragonfly2_tpu.models.mlp import init_mlp
from dragonfly2_tpu.ops.ring import local_attention

Params = dict


def init_transformer(
    key: jax.Array,
    in_dim: int,
    model_dim: int,
    num_heads: int,
    num_layers: int,
    mlp_ratio: int = 4,
    dtype=jnp.float32,
) -> Params:
    assert model_dim % num_heads == 0
    head_dim = model_dim // num_heads

    def dense(k, fan_in, fan_out):
        scale = jnp.sqrt(1.0 / fan_in).astype(dtype)
        return jax.random.normal(k, (fan_in, fan_out), dtype) * scale

    key, ek = jax.random.split(key)
    params: Params = {
        "embed": dense(ek, in_dim, model_dim),
        "layers": [],
        "num_heads": num_heads,
        "head_dim": head_dim,
    }
    for _ in range(num_layers):
        key, *ks = jax.random.split(key, 8)
        params["layers"].append(
            {
                "wq": dense(ks[0], model_dim, model_dim),
                "wk": dense(ks[1], model_dim, model_dim),
                "wv": dense(ks[2], model_dim, model_dim),
                "wo": dense(ks[3], model_dim, model_dim),
                "ln1": {"g": jnp.ones((model_dim,), dtype), "b": jnp.zeros((model_dim,), dtype)},
                "ln2": {"g": jnp.ones((model_dim,), dtype), "b": jnp.zeros((model_dim,), dtype)},
                "w1": dense(ks[4], model_dim, mlp_ratio * model_dim),
                "b1": jnp.zeros((mlp_ratio * model_dim,), dtype),
                "w2": dense(ks[5], mlp_ratio * model_dim, model_dim),
                "b2": jnp.zeros((model_dim,), dtype),
            }
        )
    key, hk = jax.random.split(key)
    params["head"] = init_mlp(hk, [model_dim, model_dim, 1], dtype)
    return params


def _layer_norm(x: jax.Array, g: jax.Array, b: jax.Array) -> jax.Array:
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * g + b


def apply_transformer(
    params: Params,
    x: jax.Array,  # [B, T, F]
    attention_fn=None,
    causal: bool = True,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """→ [B, T, model_dim] encoded sequence.

    ``attention_fn(q, k, v) -> o`` defaults to single-device
    local_attention; pass ops.ring.make_ring_attention(mesh, 'sp') to run
    sequence-parallel (inputs must then be sp-sharded [B, T/sp, ...]).
    """
    nh, hd = params["num_heads"], params["head_dim"]
    if attention_fn is None:
        def attention_fn(q, k, v):
            return local_attention(q, k, v, causal=causal)

    def proj(h, w):
        return jnp.dot(
            h.astype(compute_dtype), w.astype(compute_dtype), preferred_element_type=jnp.float32
        )

    h = proj(x, params["embed"])
    b, t, dm = h.shape
    for layer in params["layers"]:
        u = _layer_norm(h, layer["ln1"]["g"], layer["ln1"]["b"])
        q = proj(u, layer["wq"]).reshape(b, t, nh, hd).astype(compute_dtype)
        k = proj(u, layer["wk"]).reshape(b, t, nh, hd).astype(compute_dtype)
        v = proj(u, layer["wv"]).reshape(b, t, nh, hd).astype(compute_dtype)
        o = attention_fn(q, k, v).reshape(b, t, dm)
        h = h + proj(o, layer["wo"])
        u = _layer_norm(h, layer["ln2"]["g"], layer["ln2"]["b"])
        ff = jax.nn.gelu(proj(u, layer["w1"]) + layer["b1"].astype(jnp.float32))
        h = h + proj(ff, layer["w2"]) + layer["b2"].astype(jnp.float32)
    return h
