"""MLP parent scorer.

The first real implementation of the reference's `trainMLP` stub
(reference trainer/training/training.go:92-98): a regression MLP from the
12 pair features (schema.features.MLP_FEATURE_NAMES) to expected log piece
cost. The scheduler's `ml` evaluator ranks candidate parents by ascending
predicted cost (reference evaluator.go:53's TODO algorithm).
"""

# dfanalyze: device-hot — jitted/device-feeding compute plane

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

Params = dict

# resolved once per process: the backend doesn't change after init, and
# re-querying it at every trace would be noise in the trace cache keys
_compute_dtype_cache: list = []


def default_compute_dtype():
    """bfloat16 where the MXU makes it the native matmul dtype; float32
    on the CPU backend, where XLA lowers bf16 dots to f32 compute plus
    per-layer convert ops on both the forward and backward pass —
    measured 131k → 154k rows/s on the ingest train step (ISSUE 15).
    Accumulation is float32 either way (``preferred_element_type``), so
    this only removes the conversion overhead a backend without native
    bf16 pays for nothing."""
    if not _compute_dtype_cache:
        _compute_dtype_cache.append(
            jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16
        )
    return _compute_dtype_cache[0]


def init_mlp(
    key: jax.Array,
    dims: Sequence[int],
    dtype=jnp.float32,
) -> Params:
    """``dims = [in, hidden..., out]`` → {'layers': [{'w', 'b'}, ...]}."""
    layers = []
    for i, (fan_in, fan_out) in enumerate(zip(dims[:-1], dims[1:])):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(2.0 / fan_in).astype(dtype)
        layers.append(
            {
                "w": jax.random.normal(sub, (fan_in, fan_out), dtype) * scale,
                "b": jnp.zeros((fan_out,), dtype),
            }
        )
    return {"layers": layers}


def apply_mlp(
    params: Params,
    x: jax.Array,
    activation=jax.nn.gelu,
    compute_dtype=None,
) -> jax.Array:
    """Forward pass; hidden matmuls in ``compute_dtype`` (``None`` picks
    the backend-native dtype — bfloat16 on the MXU, float32 on CPU),
    accumulation and residual math in float32."""
    if compute_dtype is None:
        compute_dtype = default_compute_dtype()
    h = x
    n = len(params["layers"])
    for i, layer in enumerate(params["layers"]):
        w = layer["w"].astype(compute_dtype)
        h = jnp.dot(h.astype(compute_dtype), w, preferred_element_type=jnp.float32)
        h = h + layer["b"].astype(jnp.float32)
        if i != n - 1:
            h = activation(h)
    return h


def score_parents(params: Params, features: jax.Array) -> jax.Array:
    """[..., F] pair features → [...] predicted log piece cost (lower is a
    better parent)."""
    return apply_mlp(params, features)[..., 0]
