"""MLP parent scorer.

The first real implementation of the reference's `trainMLP` stub
(reference trainer/training/training.go:92-98): a regression MLP from the
12 pair features (schema.features.MLP_FEATURE_NAMES) to expected log piece
cost. The scheduler's `ml` evaluator ranks candidate parents by ascending
predicted cost (reference evaluator.go:53's TODO algorithm).
"""

# dfanalyze: device-hot — jitted/device-feeding compute plane

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

Params = dict


def init_mlp(
    key: jax.Array,
    dims: Sequence[int],
    dtype=jnp.float32,
) -> Params:
    """``dims = [in, hidden..., out]`` → {'layers': [{'w', 'b'}, ...]}."""
    layers = []
    for i, (fan_in, fan_out) in enumerate(zip(dims[:-1], dims[1:])):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(2.0 / fan_in).astype(dtype)
        layers.append(
            {
                "w": jax.random.normal(sub, (fan_in, fan_out), dtype) * scale,
                "b": jnp.zeros((fan_out,), dtype),
            }
        )
    return {"layers": layers}


def apply_mlp(
    params: Params,
    x: jax.Array,
    activation=jax.nn.gelu,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Forward pass; hidden matmuls in ``compute_dtype`` (bfloat16 on the
    MXU), accumulation and residual math in float32."""
    h = x
    n = len(params["layers"])
    for i, layer in enumerate(params["layers"]):
        w = layer["w"].astype(compute_dtype)
        h = jnp.dot(h.astype(compute_dtype), w, preferred_element_type=jnp.float32)
        h = h + layer["b"].astype(jnp.float32)
        if i != n - 1:
            h = activation(h)
    return h


def score_parents(params: Params, features: jax.Array) -> jax.Array:
    """[..., F] pair features → [...] predicted log piece cost (lower is a
    better parent)."""
    return apply_mlp(params, features)[..., 0]
