"""Model zoo for the TPU trainer (BASELINE.json `configs`):

- mlp        — parent-peer scorer on download-record pair features
- gnn        — GraphSAGE over the probe graph (parent scoring + link prediction)
- gru        — piece-download time-series (back-to-source predictor)
- attention  — transformer encoder w/ ring attention for long piece sequences

All models are pure functional: ``init_*`` returns a params pytree (plain
dicts/lists of jnp arrays — trivially shardable with NamedSharding),
``apply_*`` is jit-traceable with static shapes. Matmuls run bfloat16 with
float32 accumulation.
"""
