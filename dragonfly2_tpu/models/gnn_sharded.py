"""Graph-parallel GraphSAGE: node tables sharded over an ICI mesh axis.

SURVEY.md §5.7's scaling problem: a probe graph with O(hosts²) edges and
its per-node embedding table don't fit one chip's HBM at fleet scale. The
answer mirrors ring attention — shard the node feature/embedding tables
row-wise over a mesh axis and rotate shards around the ICI ring
(ops.ring.ring_gather_rows) for the two places a device needs non-local
rows: neighbor aggregation and edge-endpoint lookup. Per-device memory is
O(N/devices + E/devices); the full tables never materialize.

Semantics match models.gnn.forward_edge_rtt exactly (tested elementwise
in float32): same masked-mean aggregation, same bf16 matmul policy, same
L2-normalized embeddings and pairwise head.
"""

# dfanalyze: device-hot — jitted/device-feeding compute plane

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from dragonfly2_tpu.models.mlp import apply_mlp
from dragonfly2_tpu.ops.ring import ring_gather_rows
from dragonfly2_tpu.ops.segment import masked_mean


def pad_rows(a: np.ndarray, multiple: int) -> np.ndarray:
    """Pad axis 0 up to a multiple so row-sharding divides evenly."""
    from dragonfly2_tpu.parallel.sharding import pad_to_multiple

    padded, _ = pad_to_multiple(a, multiple)
    return padded


def pad_node_arrays(graph, num_shards: int):
    """ProbeGraph → padded NODE arrays sharding-ready over ``num_shards``
    — the serving-side half of :func:`pad_graph` (an embed-at-swap
    forward has no edge blocks to pad). Padded nodes self-neighbor with
    zero mask, inert under the masked mean. Returns (node_features,
    neighbors, neighbor_mask) as numpy arrays."""
    nf = pad_rows(graph.node_features.astype(np.float32), num_shards)
    n_pad = nf.shape[0]
    neighbors = pad_rows(graph.neighbors.astype(np.int32), num_shards)
    # padded nodes' neighbor slots must stay in-bounds: self-index
    if n_pad > graph.num_nodes:
        pad_ids = np.arange(graph.num_nodes, n_pad, dtype=np.int32)
        neighbors[graph.num_nodes :] = pad_ids[:, None]
    mask = pad_rows(graph.neighbor_mask.astype(np.float32), num_shards)
    return nf, neighbors, mask


def pad_graph(graph, num_shards: int):
    """ProbeGraph → padded arrays sharding-ready over ``num_shards``.

    Padded nodes self-neighbor with zero mask (inert under masked mean);
    padded edges point at node 0 with zero weight in the loss mask.
    Returns (node_features, neighbors, neighbor_mask, edge_src, edge_dst,
    edge_y, edge_w) as numpy arrays.
    """
    nf, neighbors, mask = pad_node_arrays(graph, num_shards)

    src = pad_rows(graph.edge_src.astype(np.int32), num_shards)
    dst = pad_rows(graph.edge_dst.astype(np.int32), num_shards)
    y = pad_rows(graph.edge_rtt_log_ms.astype(np.float32), num_shards)
    w = pad_rows(np.ones(len(graph.edge_src), np.float32), num_shards)
    return nf, neighbors, mask, src, dst, y, w


def _embed_local(
    dense: dict,
    embed_shard: jax.Array | None,  # [S, E] or None
    feat_shard: jax.Array,  # [S, F]
    nbr_shard: jax.Array,  # [S, K] global ids
    mask_shard: jax.Array,  # [S, K]
    axis: str,
    compute_dtype,
) -> jax.Array:
    """Per-device SAGE stack under shard_map → this device's [S, H]
    L2-normalized embedding rows."""
    h = feat_shard
    if embed_shard is not None:
        h = jnp.concatenate([h, embed_shard], axis=-1)
    for layer in dense["sage"]:
        nbr_feats = ring_gather_rows(h, nbr_shard, axis)  # [S, K, F]
        agg = masked_mean(nbr_feats, mask_shard)
        z = jnp.dot(
            h.astype(compute_dtype),
            layer["w_self"].astype(compute_dtype),
            preferred_element_type=jnp.float32,
        ) + jnp.dot(
            agg.astype(compute_dtype),
            layer["w_nbr"].astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        h = jax.nn.relu(z + layer["b"].astype(jnp.float32))
    norm = jnp.linalg.norm(h, axis=-1, keepdims=True)
    return h / jnp.maximum(norm, 1e-6)


def _forward_local(
    dense: dict,
    embed_shard: jax.Array | None,  # [S, E] or None
    feat_shard: jax.Array,  # [S, F]
    nbr_shard: jax.Array,  # [S, K] global ids
    mask_shard: jax.Array,  # [S, K]
    src_blk: jax.Array,  # [Eb] global ids
    dst_blk: jax.Array,  # [Eb]
    axis: str,
    compute_dtype,
) -> jax.Array:
    """Per-device body under shard_map → per-edge log-RTT for this
    device's edge block."""
    h = _embed_local(
        dense, embed_shard, feat_shard, nbr_shard, mask_shard, axis, compute_dtype
    )

    # one ring rotation serves both endpoints — stacked indices halve the
    # ppermute volume of the hottest collective in the loop
    ends = ring_gather_rows(h, jnp.stack([src_blk, dst_blk]), axis)  # [2, Eb, H]
    hs, hd = ends[0], ends[1]
    pair = jnp.concatenate([hs, hd, hs * hd], axis=-1)
    return apply_mlp(dense["head"], pair)[..., 0]


def make_sharded_forward(mesh, axis: str = "gp", compute_dtype=jnp.bfloat16):
    """→ fn(dense, embed, node_features, neighbors, mask, src, dst) with
    node tables and edge blocks sharded over ``mesh[axis]``; returns
    per-edge predictions (edge-sharded)."""
    row = P(axis)
    row2 = P(axis, None)

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), row2, row2, row2, row2, row, row),
        out_specs=row,
        check_vma=False,
    )
    def fwd(dense, embed, feats, nbrs, mask, src, dst):
        return _forward_local(
            dense, embed, feats, nbrs, mask, src, dst, axis, compute_dtype
        )

    def apply(dense, embed, feats, nbrs, mask, src, dst):
        if embed is None:
            # shard_map specs are positional — substitute an empty table
            embed = jnp.zeros((feats.shape[0], 0), feats.dtype)
        return fwd(dense, embed, feats, nbrs, mask, src, dst)

    return apply


def make_sharded_embed(mesh, axis: str = "gp", compute_dtype=jnp.bfloat16):
    """→ fn(dense, embed, node_features, neighbors, mask) returning the
    [N, H] embedding table row-sharded over ``mesh[axis]`` — the
    serve-time half of the sharded forward. The scoring service embeds
    ONCE at model-swap time and keeps the (sharded) table resident; per
    query only edge-endpoint indices move (models.gnn.predict_edge
    gathers against the global array)."""
    row2 = P(axis, None)

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), row2, row2, row2, row2),
        out_specs=row2,
        check_vma=False,
    )
    def emb(dense, embed, feats, nbrs, mask):
        return _embed_local(dense, embed, feats, nbrs, mask, axis, compute_dtype)

    def apply(dense, embed, feats, nbrs, mask):
        feats = jnp.asarray(feats)
        if embed is None:
            embed = jnp.zeros((feats.shape[0], 0), feats.dtype)
        return emb(dense, embed, feats, jnp.asarray(nbrs), jnp.asarray(mask))

    return apply


def make_sharded_loss(mesh, axis: str = "gp", compute_dtype=jnp.bfloat16):
    """→ loss(dense, embed, graph arrays, src, dst, y, w): weighted MSE
    over valid edges, psum-reduced across the axis so every device sees
    the global mean."""
    row = P(axis)
    row2 = P(axis, None)

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), row2, row2, row2, row2, row, row, row, row),
        out_specs=P(),
        check_vma=False,
    )
    def loss(dense, embed, feats, nbrs, mask, src, dst, y, w):
        pred = _forward_local(
            dense, embed, feats, nbrs, mask, src, dst, axis, compute_dtype
        )
        se = w * (pred - y) ** 2
        total = lax.psum(se.sum(), axis)
        count = lax.psum(w.sum(), axis)
        return total / jnp.maximum(count, 1.0)

    def apply(dense, embed, feats, nbrs, mask, src, dst, y, w):
        if embed is None:
            embed = jnp.zeros((feats.shape[0], 0), feats.dtype)
        return loss(dense, embed, feats, nbrs, mask, src, dst, y, w)

    return apply


def shard_graph_arrays(mesh, axis: str, *arrays):
    """device_put each array row-sharded over ``mesh[axis]``."""
    out = []
    for a in arrays:
        spec = P(axis) if a.ndim == 1 else P(axis, *([None] * (a.ndim - 1)))
        out.append(jax.device_put(jnp.asarray(a), NamedSharding(mesh, spec)))
    return out
