"""GRU over piece-download time series.

Per-task sequence of piece outcomes (cost, length, parent switch …) →
predicted next-piece cost / back-to-source risk (BASELINE.json config
"GRU piece-download time-series"). The recurrence runs under `lax.scan`
— XLA-friendly sequential control flow, no Python loops in jit.
"""

# dfanalyze: device-hot — jitted/device-feeding compute plane

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from dragonfly2_tpu.models.mlp import apply_mlp, init_mlp

Params = dict


def init_gru(
    key: jax.Array, in_dim: int, hidden_dim: int, head_hidden: int = 32, dtype=jnp.float32
) -> Params:
    def dense(k, fan_in, fan_out):
        scale = jnp.sqrt(1.0 / fan_in).astype(dtype)
        return jax.random.normal(k, (fan_in, fan_out), dtype) * scale

    keys = jax.random.split(key, 7)
    params = {
        "wz": dense(keys[0], in_dim, hidden_dim),
        "uz": dense(keys[1], hidden_dim, hidden_dim),
        "bz": jnp.zeros((hidden_dim,), dtype),
        "wr": dense(keys[2], in_dim, hidden_dim),
        "ur": dense(keys[3], hidden_dim, hidden_dim),
        "br": jnp.zeros((hidden_dim,), dtype),
        "wh": dense(keys[4], in_dim, hidden_dim),
        "uh": dense(keys[5], hidden_dim, hidden_dim),
        "bh": jnp.zeros((hidden_dim,), dtype),
        "head": init_mlp(keys[6], [hidden_dim, head_hidden, 1], dtype),
    }
    return params


def gru_cell(params: Params, h: jax.Array, x: jax.Array) -> jax.Array:
    z = jax.nn.sigmoid(x @ params["wz"] + h @ params["uz"] + params["bz"])
    r = jax.nn.sigmoid(x @ params["wr"] + h @ params["ur"] + params["br"])
    n = jnp.tanh(x @ params["wh"] + (r * h) @ params["uh"] + params["bh"])
    return (1.0 - z) * n + z * h


def apply_gru(
    params: Params, x: jax.Array, lengths: jax.Array | None = None
) -> tuple[jax.Array, jax.Array]:
    """x: [B, T, F] → (hidden states [B, T, H], final hidden [B, H]).

    ``lengths`` masks padded steps: state stops updating past a sequence's
    length so the final hidden is the last *real* step's state.
    """
    b, t, _ = x.shape
    h0 = jnp.zeros((b, params["uz"].shape[0]), x.dtype)

    def step(h, inp):
        xt, keep = inp
        h_new = gru_cell(params, h, xt)
        h = jnp.where(keep[:, None], h_new, h)
        return h, h

    if lengths is None:
        keep = jnp.ones((t, b), bool)
    else:
        keep = (jnp.arange(t)[:, None] < lengths[None, :]).astype(bool)
    final, hs = lax.scan(step, h0, (x.transpose(1, 0, 2), keep))
    return hs.transpose(1, 0, 2), final


def predict_next_cost(params: Params, x: jax.Array, lengths: jax.Array | None = None) -> jax.Array:
    """[B, T, F] piece history → [B] predicted next log piece cost."""
    _, final = apply_gru(params, x, lengths)
    return apply_mlp(params["head"], final)[..., 0]
