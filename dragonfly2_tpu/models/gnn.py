"""GraphSAGE GNN over the probe graph.

The first real implementation of the reference's `trainGNN` stub
(reference trainer/training/training.go:82-88). Hosts are nodes, probe
measurements are edges (EWMA RTT, reference probes.go:174-212). The model
learns host embeddings whose pairwise head predicts edge RTT — usable both
for parent ranking (predict RTT to unprobed candidates) and seed-peer
placement link prediction (BASELINE.json configs).

TPU form: aggregation over a fixed-degree sampled neighbor table [N, K]
(schema.features.sample_neighbors) — dense gathers + masked means, static
shapes, no sparse dynamic ops inside jit. For graphs sharded over devices,
the gather runs through ops.ring.ring_gather_rows so the full feature
table never materializes on one chip.
"""

# dfanalyze: device-hot — jitted/device-feeding compute plane

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from dragonfly2_tpu.models.mlp import apply_mlp, init_mlp
from dragonfly2_tpu.ops.segment import aggregate_neighbors

Params = dict


def init_graphsage(
    key: jax.Array,
    in_dim: int,
    hidden_dims: Sequence[int],
    head_hidden: int = 64,
    num_nodes: int | None = None,
    embed_dim: int = 16,
    dtype=jnp.float32,
) -> Params:
    """SAGE layers + pairwise edge head.

    Layer l: h' = act(W_self·h + W_nbr·mean_{u∈N(v)} h_u + b).
    Edge head: MLP([h_src, h_dst, h_src⊙h_dst]) → scalar log-RTT.

    ``num_nodes`` adds a learnable per-node embedding table concatenated to
    the input features — host stats alone don't localize a host in the RTT
    geometry, the embedding learns its position (transductive over the
    known host set; unseen hosts get the zero embedding).
    """
    params_embed = None
    if num_nodes is not None:
        key, ek = jax.random.split(key)
        params_embed = jax.random.normal(ek, (num_nodes, embed_dim), dtype) * 0.1
        in_dim = in_dim + embed_dim
    layers = []
    d = in_dim
    for h in hidden_dims:
        key, k1, k2 = jax.random.split(key, 3)
        scale = jnp.sqrt(2.0 / d).astype(dtype)
        layers.append(
            {
                "w_self": jax.random.normal(k1, (d, h), dtype) * scale,
                "w_nbr": jax.random.normal(k2, (d, h), dtype) * scale,
                "b": jnp.zeros((h,), dtype),
            }
        )
        d = h
    key, hk = jax.random.split(key)
    head = init_mlp(hk, [3 * d, head_hidden, 1], dtype)
    out: Params = {"sage": layers, "head": head}
    if params_embed is not None:
        out["node_embed"] = params_embed
    return out


def apply_graphsage(
    params: Params,
    node_features: jax.Array,  # [N, F]
    neighbors: jax.Array,  # [N, K] int32
    neighbor_mask: jax.Array,  # [N, K]
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """→ [N, H] node embeddings (L2-normalized, GraphSAGE convention)."""
    h = node_features
    if "node_embed" in params:
        h = jnp.concatenate([h, params["node_embed"]], axis=-1)
    for layer in params["sage"]:
        agg = aggregate_neighbors(h, neighbors, neighbor_mask)
        z = jnp.dot(
            h.astype(compute_dtype),
            layer["w_self"].astype(compute_dtype),
            preferred_element_type=jnp.float32,
        ) + jnp.dot(
            agg.astype(compute_dtype),
            layer["w_nbr"].astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        h = jax.nn.relu(z + layer["b"].astype(jnp.float32))
    norm = jnp.linalg.norm(h, axis=-1, keepdims=True)
    return h / jnp.maximum(norm, 1e-6)


def predict_edge(
    params: Params, embeddings: jax.Array, src: jax.Array, dst: jax.Array
) -> jax.Array:
    """Pairwise head: predicted log-RTT for edges (src[i] → dst[i])."""
    hs = jnp.take(embeddings, src, axis=0)
    hd = jnp.take(embeddings, dst, axis=0)
    pair = jnp.concatenate([hs, hd, hs * hd], axis=-1)
    return apply_mlp(params["head"], pair)[..., 0]


def forward_edge_rtt(
    params: Params,
    node_features: jax.Array,
    neighbors: jax.Array,
    neighbor_mask: jax.Array,
    src: jax.Array,
    dst: jax.Array,
) -> jax.Array:
    """Full forward: features → embeddings → edge log-RTT predictions."""
    emb = apply_graphsage(params, node_features, neighbors, neighbor_mask)
    return predict_edge(params, emb, src, dst)
