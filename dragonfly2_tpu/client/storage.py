"""Piece-level disk store for the peer daemon.

Role parity: reference client/daemon/storage/storage_manager.go:52-962 +
local_storage.go — RegisterTask/WritePiece/ReadPiece/ReadAllPieces/Store/
GetPieces with per-task metadata persisted next to the data file, md5
piece verification, and a disk-usage reclaimer wired into the GC
framework (reference storage_manager.go:80-89).

Layout: ``<data_dir>/<task_id[:3]>/<task_id>/{data,metadata.json}`` —
pieces are written at their offsets into one sparse data file, so a
completed task is a byte-identical copy of the origin object and
``store()`` can hardlink it out.

Content-addressed dedup (docs/data-plane.md): the manager keeps a
digest-keyed :class:`PieceIndex` over every stored piece. A second task
writing a piece whose digest (and length) is already held records a
*reference* instead of duplicating the bytes — its ``PieceMeta.ref_task``
marks the bytes as living in another task's data file, and every read
path (``piece_span``/``read_piece``/``read_range``/``read_all``/serve)
resolves the reference through the index. References are refcounted:
deleting the owning task first *migrates* each still-referenced piece's
bytes into one of the referring tasks (which becomes the new owner), so
shared bytes survive any single task's GC and are reclaimed only when
the last referent goes.
"""

# dfanalyze: hot — write_piece/piece_span run per piece on the data plane

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from dataclasses import asdict, dataclass, field

from dragonfly2_tpu.client import metrics as M
from dragonfly2_tpu.utils import dflog, flight, flows
from dragonfly2_tpu.utils.digest import md5_from_bytes

logger = dflog.get("client.storage")

# flight event: a GC-time owner migration — rare, load-bearing for the
# dedup plane's correctness story, worth a permanent ring entry
EV_DEDUP_MIGRATE = flight.event_type("daemon.dedup_migrate")

_COPY_CHUNK = 1 << 20


@dataclass
class PieceMeta:
    number: int
    offset: int
    length: int
    digest: str = ""  # "md5:<hex>"
    traffic_type: str = ""
    cost_ns: int = 0
    parent_id: str = ""
    # content-addressed reference: non-empty = the bytes live in another
    # task's data file (the task id that owned them at dedup time —
    # provenance only; reads resolve the CURRENT owner via the index)
    ref_task: str = ""


@dataclass
class TaskMeta:
    task_id: str
    peer_id: str
    url: str = ""
    tag: str = ""
    application: str = ""
    content_length: int = -1
    total_piece_count: int = -1
    piece_length: int = 0
    done: bool = False
    access_time: float = field(default_factory=time.time)
    # minimal origin response headers (Content-Type at least), replayed
    # by the P2P transport so proxy clients see proper metadata
    headers: dict[str, str] = field(default_factory=dict)
    pieces: dict[int, PieceMeta] = field(default_factory=dict)

    def to_json(self) -> dict:
        d = asdict(self)
        d["pieces"] = {str(k): asdict(v) for k, v in self.pieces.items()}
        return d

    @classmethod
    def from_json(cls, d: dict) -> "TaskMeta":
        pieces = {int(k): PieceMeta(**v) for k, v in d.pop("pieces", {}).items()}
        return cls(**{**d, "pieces": pieces})


class PieceIndex:
    """Digest-keyed index over every stored piece: which tasks hold the
    bytes physically (*holders*) and which merely reference them
    (*refs*). The refcount for GC purposes is holders + refs; bytes are
    reclaimable only when both hit zero. A leaf lock — never held while
    a task or manager lock is acquired."""

    def __init__(self):
        self._lock = threading.Lock()
        # digest -> (length, holders: set[(task_id, number)],
        #            refs: set[(task_id, number)])
        self._entries: dict[str, tuple[int, set, set]] = {}

    def find_holder(self, digest: str, length: int, exclude_task: str = ""):
        """→ (task_id, number) of a physical holder, or None. Length
        participates so a (theoretical) digest collision of differing
        sizes never aliases."""
        with self._lock:
            e = self._entries.get(digest)
            if e is None or e[0] != length:
                return None
            for task_id, number in e[1]:
                if task_id != exclude_task:
                    return (task_id, number)
            return None

    def record_holder(self, digest: str, length: int, task_id: str, number: int) -> None:
        with self._lock:
            e = self._entries.get(digest)
            if e is None or e[0] != length:
                e = self._entries[digest] = (length, set(), set())
            e[1].add((task_id, number))
            e[2].discard((task_id, number))

    def record_ref(self, digest: str, length: int, task_id: str, number: int) -> None:
        with self._lock:
            e = self._entries.get(digest)
            if e is None or e[0] != length:
                # a ref with no holder entry (crash-recovery edge): keep
                # the entry so drop/resolve see a consistent shape;
                # resolution will fail and the caller refetches
                e = self._entries[digest] = (length, set(), set())
            e[2].add((task_id, number))

    def add_ref_if_held(
        self, digest: str, length: int, task_id: str, number: int
    ):
        """Atomic find-holder + record-ref under ONE index lock — the
        write path's dedup decision. A separate find-then-record pair
        would leave a window where the holder's GC sees no referent and
        reclaims the only copy of bytes a ref is about to point at.
        → the holder (task_id, number) or None (caller writes bytes)."""
        with self._lock:
            e = self._entries.get(digest)
            if e is None or e[0] != length:
                return None
            for holder in e[1]:
                if holder[0] != task_id:
                    e[2].add((task_id, number))
                    return holder
            return None

    def orphaned_by(self, task_id: str) -> list[tuple[str, int, int]]:
        """Digests whose ONLY holders belong to ``task_id`` but that
        other tasks still reference → [(digest, number, length)]: the
        migration work list for deleting ``task_id``."""
        out = []
        with self._lock:
            for digest, (length, holders, refs) in self._entries.items():
                mine = [h for h in holders if h[0] == task_id]
                if not mine or any(h[0] != task_id for h in holders):
                    continue
                if any(r[0] != task_id for r in refs):
                    out.append((digest, mine[0][1], length))
        return out

    def referrers(self, digest: str, exclude_task: str = "") -> list[tuple[str, int]]:
        with self._lock:
            e = self._entries.get(digest)
            if e is None:
                return []
            return [r for r in e[2] if r[0] != exclude_task]

    def drop_task(self, task_id: str) -> list[str]:
        """Remove every entry of ``task_id``. Returns digests STRANDED
        by the removal — still referenced by other tasks but now
        holder-less (a ref recorded between the caller's migration scan
        and this drop): the caller must run one more migration pass for
        them while the bytes are still on disk."""
        stranded = []
        with self._lock:
            dead = []
            for digest, (_, holders, refs) in self._entries.items():
                held_here = any(h[0] == task_id for h in holders)
                holders.difference_update({h for h in holders if h[0] == task_id})
                refs.difference_update({r for r in refs if r[0] == task_id})
                if not holders and not refs:
                    dead.append(digest)
                elif held_here and not holders and refs:
                    stranded.append(digest)
            for digest in dead:
                del self._entries[digest]
        return stranded

    def stats(self) -> dict:
        with self._lock:
            holders = sum(len(e[1]) for e in self._entries.values())
            refs = sum(len(e[2]) for e in self._entries.values())
            return {"digests": len(self._entries), "holders": holders, "refs": refs}


class TaskStorage:
    """One task's on-disk state: sparse data file + metadata."""

    PERSIST_EVERY = 64  # pieces between metadata flushes on the hot path

    def __init__(self, task_dir: str, meta: TaskMeta, manager: "StorageManager | None" = None):
        self.dir = task_dir
        self.meta = meta
        self.lock = threading.RLock()
        self._dirty_pieces = 0
        # a live conductor owns this task (not persisted: after a crash
        # nothing is live, so orphans become reclaimable)
        self.busy = False
        # backref for content-addressed ref resolution; None for
        # standalone (test) construction — dedup is then inert
        self._sm = manager
        # cached count of ref pieces: the read paths take the stitched
        # (slower) route only when nonzero
        self._ref_count = sum(1 for p in meta.pieces.values() if p.ref_task)
        # cached write handle: one open() per piece write measured ~10%
        # of the small-piece write wall; closed on done/delete
        self._wf = None
        os.makedirs(task_dir, exist_ok=True)
        self.data_path = os.path.join(task_dir, "data")
        self.meta_path = os.path.join(task_dir, "metadata.json")
        if not os.path.exists(self.data_path):
            open(self.data_path, "wb").close()

    def persist(self) -> None:
        tmp = self.meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.meta.to_json(), f)
        os.replace(tmp, self.meta_path)

    def _write_handle(self):
        if self._wf is None or self._wf.closed:
            self._wf = open(self.data_path, "r+b")
        return self._wf

    def _close_write_handle(self) -> None:
        if self._wf is not None:
            try:
                self._wf.close()
            except OSError:
                pass
            self._wf = None

    def write_piece(
        self,
        number: int,
        offset: int,
        data: bytes,
        digest: str = "",
        traffic_type: str = "",
        cost_ns: int = 0,
        parent_id: str = "",
    ) -> PieceMeta:
        """Write piece bytes at their offset; verifies md5 when a digest
        is given (advisory ``io.md5`` strategy, reference
        storage_manager.go digest handling). When the manager's
        content-addressed index already holds identical bytes, a
        reference is recorded instead of a second physical copy."""
        if digest:
            got = f"md5:{md5_from_bytes(data)}"
            if got != digest:
                raise StorageError(
                    f"piece {number} digest mismatch: want {digest} got {got}"
                )
        else:
            digest = f"md5:{md5_from_bytes(data)}"
        M.PIECE_DOWNLOADED_TOTAL.labels(traffic_type or "unknown").inc()
        M.PIECE_TRAFFIC_BYTES.labels(traffic_type or "unknown").inc(len(data))
        sm = self._sm
        dedup = sm is not None and sm.dedup_enabled and bool(data)
        with self.lock:
            holder = (
                # find + record in ONE index transaction (and under our
                # task lock, so GC migration — which takes referrer
                # locks — always sees the ref AND its piece meta
                # together): a plain find-then-record would race the
                # holder's delete into bytes stored nowhere
                sm.piece_index.add_ref_if_held(
                    digest, len(data), self.meta.task_id, number
                )
                if dedup
                else None
            )
            if holder is not None:
                M.PIECE_DEDUP_TOTAL.inc()
                M.PIECE_DEDUP_BYTES.inc(len(data))
            else:
                f = self._write_handle()
                f.seek(offset)
                f.write(data)
                f.flush()
                if dedup:
                    sm.piece_index.record_holder(
                        digest, len(data), self.meta.task_id, number
                    )
            pm = PieceMeta(
                number=number,
                offset=offset,
                length=len(data),
                digest=digest,
                traffic_type=traffic_type,
                cost_ns=cost_ns,
                parent_id=parent_id,
                ref_task=holder[0] if holder is not None else "",
            )
            prev = self.meta.pieces.get(number)
            if prev is not None and prev.ref_task and not pm.ref_task:
                self._ref_count -= 1
            if pm.ref_task and (prev is None or not prev.ref_task):
                self._ref_count += 1
            self.meta.pieces[number] = pm
            self.meta.access_time = time.time()
            # amortize metadata persistence: the full JSON rewrite is
            # O(pieces), so flushing per piece would make the hot path
            # O(n²) and skew cost_ns labels; a crash loses at most the
            # last PERSIST_EVERY piece *metadata* entries (bytes are on
            # disk; unlisted pieces are re-fetched on resume)
            self._dirty_pieces += 1
            if self._dirty_pieces >= self.PERSIST_EVERY:
                self._dirty_pieces = 0
                self.persist()
        # Flow-ledger attribution (outside the task lock): this is the
        # single acquisition choke point, and the classes are exclusive
        # — a piece is a dedup ref, a parent transfer, or an origin
        # read, never two — which is what makes per-plane byte
        # conservation checkable. "local_peer" imports are skipped: the
        # bytes were already on this host, nothing was acquired.
        if data and traffic_type != "local_peer":
            if holder is not None:
                prov = "dedup"
            elif traffic_type == "remote_peer":
                prov = "parent"
            elif traffic_type == "back_to_source":
                prov = (
                    "preheat" if flows.is_preheat(self.meta.task_id) else "origin"
                )
            else:
                prov = ""
            if prov:
                flows.account(flows.task_plane(self.meta.task_id), prov, len(data))
        return pm

    # ------------------------------------------------------------------
    # span-resolving reads: the zero-copy serve path asks WHERE bytes
    # live instead of materializing them (docs/data-plane.md)
    # ------------------------------------------------------------------
    def piece_span(self, number: int) -> tuple[str, int, int, str]:
        """→ (path, offset, length, digest) of the piece's bytes,
        resolving content-addressed references to the current physical
        holder. The upload server sendfiles straight from this span."""
        with self.lock:
            pm = self.meta.pieces.get(number)
            if pm is None:
                raise StorageError(f"piece {number} not found in {self.meta.task_id}")
            self.meta.access_time = time.time()
            if not pm.ref_task:
                return self.data_path, pm.offset, pm.length, pm.digest
            digest, length = pm.digest, pm.length
        if self._sm is None:
            raise StorageError(
                f"piece {number} is a dedup ref but no manager is attached"
            )
        span = self._sm.resolve_piece(digest, length, exclude_task=self.meta.task_id)
        if span is None:
            raise StorageError(
                f"piece {number} dedup source for {digest} vanished"
            )
        return span[0], span[1], length, digest

    def range_spans(self, offset: int, length: int) -> list[tuple[str | None, int, int]]:
        """Byte range [offset, offset+length) as a list of
        ``(path, file_offset, n)`` spans; ``path=None`` marks a sparse
        hole (read as zeros). Clamped to the current end-of-data, so a
        still-downloading task yields what exists — the same short-read
        semantics the raw sparse-file read had."""
        if length <= 0:
            return []
        with self.lock:
            self.meta.access_time = time.time()
            if not self._ref_count:
                try:
                    size = os.path.getsize(self.data_path)
                except OSError:
                    size = 0
                n = max(0, min(length, size - offset))
                return [(self.data_path, offset, n)] if n else []
            pieces = sorted(self.meta.pieces.values(), key=lambda p: p.offset)
        spans: list[tuple[str | None, int, int]] = []
        end = offset + length
        pos = offset
        for pm in pieces:
            if pm.offset + pm.length <= pos or pm.offset >= end:
                continue
            if pm.offset > pos:
                gap_end = min(pm.offset, end)
                spans.append((None, 0, gap_end - pos))
                pos = gap_end
            lo, hi = max(pos, pm.offset), min(end, pm.offset + pm.length)
            path, poff, _, _ = self.piece_span(pm.number)
            spans.append((path, poff + (lo - pm.offset), hi - lo))
            pos = hi
        return spans

    def current_end(self) -> int:
        """Highest byte written so far — the honest end-of-data for an
        open-ended Range on a task whose content_length is unknown."""
        with self.lock:
            if self.meta.pieces:
                return max(p.offset + p.length for p in self.meta.pieces.values())
            try:
                return os.path.getsize(self.data_path)
            except OSError:
                return 0

    def read_piece(self, number: int) -> bytes:
        path, off, length, _ = self.piece_span(number)
        with open(path, "rb") as f:
            f.seek(off)
            return f.read(length)

    def read_range(self, offset: int, length: int) -> bytes:
        out = bytearray()
        for path, off, n in self.range_spans(offset, length):
            if path is None:
                out += bytes(n)
            else:
                with open(path, "rb") as f:
                    f.seek(off)
                    out += f.read(n)
        return bytes(out)

    def read_all(self) -> bytes:
        with self.lock:
            if not self.meta.done:
                raise StorageError(f"task {self.meta.task_id} is not complete")
            size = self.meta.content_length
            if size < 0:
                size = self.current_end()
        return self.read_range(0, size)

    def verify_content_digest(self, expected: str) -> None:
        """Whole-task digest check against UrlMeta.digest ('sha256:…' /
        'md5:…'), hashed streaming so large tasks never materialize in
        RAM. The reference declares this check but left it TODO
        (peertask_conductor.go:607). For a RANGE task the pin covers the
        slice (the task's content IS the slice). The hash runs with the
        storage lock released — the task is complete and its data file
        immutable, and holding the lock would stall every peer this
        daemon is serving for the duration."""
        algorithm, want = _parse_digest(expected)
        h = _hashlib.new(algorithm)
        with self.lock:
            length = self.meta.content_length
        if length < 0:
            length = self.current_end()
        for path, off, n in self.range_spans(0, length):
            if path is None:
                zeros = bytes(min(n, _COPY_CHUNK))
                left = n
                while left > 0:
                    step = min(left, _COPY_CHUNK)
                    h.update(zeros[:step])
                    left -= step
                continue
            with open(path, "rb") as f:
                f.seek(off)
                left = n
                while left > 0:
                    chunk = f.read(min(left, _COPY_CHUNK))
                    if not chunk:
                        break
                    h.update(chunk)
                    left -= len(chunk)
        if h.hexdigest() != want.lower():
            raise StorageError(
                f"task {self.meta.task_id} content digest mismatch:"
                f" want {expected}, got {algorithm}:{h.hexdigest()}"
            )

    def mark_done(
        self, content_length: int | None = None, expected_digest: str = ""
    ) -> None:
        """Complete the task. With ``expected_digest`` the content is
        verified FIRST and ``done`` only ever flips on a match — a
        concurrent reuse lookup (which requires done) can never observe
        unverified pinned content, no matter how long the hash takes.
        On mismatch the stored pieces are purged (a retry must
        re-download, not re-fail on the same bytes) and StorageError
        raises."""
        with self.lock:
            if content_length is not None:
                self.meta.content_length = content_length
            if self.meta.content_length >= 0:
                # truncate to exact length (last piece may have been
                # written into a sparse hole). Dedup refs live in holes
                # by design — the truncation only bounds physical bytes.
                self._close_write_handle()
                with open(self.data_path, "r+b") as f:
                    f.truncate(self.meta.content_length)
        if expected_digest:
            try:
                self.verify_content_digest(expected_digest)
            except StorageError:
                self.purge_pieces()
                raise
        with self.lock:
            self.meta.done = True
            self.meta.total_piece_count = len(self.meta.pieces)
            self._close_write_handle()
            self.persist()

    def purge_pieces(self) -> None:
        """Drop every stored piece (verification-failure path). Bytes
        other tasks reference are migrated out FIRST so a purge can
        never strand a dedup referent — migration runs before this
        task's lock is taken (cross-task lock nesting stays one-way)."""
        if self._sm is not None:
            self._sm.release_task_bytes(self)
        with self.lock:
            self.meta.pieces.clear()
            self.meta.total_piece_count = 0
            self._ref_count = 0
            self._close_write_handle()
            open(self.data_path, "wb").close()  # drop the bytes
            self.persist()

    def store(self, dest: str) -> None:
        """Hardlink-or-copy the completed data file to ``dest``
        (reference dfget output handling). A task carrying dedup
        references materializes — its sparse file alone is not the
        content."""
        with self.lock:
            if not self.meta.done:
                raise StorageError(f"task {self.meta.task_id} is not complete")
            has_refs = bool(self._ref_count)
            size = self.meta.content_length
        os.makedirs(os.path.dirname(os.path.abspath(dest)) or ".", exist_ok=True)
        if os.path.exists(dest):
            os.remove(dest)
        if not has_refs:
            try:
                os.link(self.data_path, dest)
            except OSError:
                shutil.copyfile(self.data_path, dest)
            return
        if size < 0:
            size = self.current_end()
        with open(dest, "wb") as out:
            for path, off, n in self.range_spans(0, size):
                if path is None:
                    out.seek(n, os.SEEK_CUR)  # keep dest sparse for holes
                    continue
                with open(path, "rb") as f:
                    f.seek(off)
                    left = n
                    while left > 0:
                        chunk = f.read(min(left, _COPY_CHUNK))
                        if not chunk:
                            break
                        out.write(chunk)
                        left -= len(chunk)
            out.truncate(size)

    def size_on_disk(self) -> int:
        try:
            return os.path.getsize(self.data_path)
        except OSError:
            return 0


class StorageError(Exception):
    pass


class StorageManager:
    """All tasks' disk state + reuse index + reclaimer + the
    content-addressed piece index.

    Reference client/daemon/storage/storage_manager.go:52-124 (API) and
    :80-89 (Reclaimer: evict least-recently-accessed completed tasks when
    disk usage crosses the high watermark).
    """

    def __init__(
        self,
        data_dir: str,
        max_bytes: int = 0,
        abandoned_ttl: float = 3600.0,
        dedup: bool | None = None,
    ):
        self.data_dir = data_dir
        self.max_bytes = max_bytes  # 0 = unbounded
        # incomplete tasks idle this long AND not owned by a live
        # conductor count as abandoned (crash leftovers)
        self.abandoned_ttl = abandoned_ttl
        # content-addressed cross-task dedup (DF_PIECE_DEDUP=0 disables)
        self.dedup_enabled = (
            os.environ.get("DF_PIECE_DEDUP", "1") != "0" if dedup is None else dedup
        )
        self.piece_index = PieceIndex()
        self.tasks: dict[str, TaskStorage] = {}
        self.lock = threading.RLock()
        os.makedirs(data_dir, exist_ok=True)
        self._load_existing()

    def _task_dir(self, task_id: str) -> str:
        return os.path.join(self.data_dir, task_id[:3], task_id)

    def _load_existing(self) -> None:
        """Recover persisted tasks on restart (download-side resume,
        reference client/daemon/peer/peertask_reuse.go) and rebuild the
        content-addressed index from their metadata — holders first,
        then references, dropping any reference whose bytes no longer
        resolve (a crash between a holder's delete-migration and the
        referrer's re-point; the piece is simply re-fetched on resume)."""
        for prefix in os.listdir(self.data_dir):
            pdir = os.path.join(self.data_dir, prefix)
            if not os.path.isdir(pdir):
                continue
            for task_id in os.listdir(pdir):
                meta_path = os.path.join(pdir, task_id, "metadata.json")
                if not os.path.exists(meta_path):
                    continue
                try:
                    with open(meta_path) as f:
                        meta = TaskMeta.from_json(json.load(f))
                    self.tasks[task_id] = TaskStorage(
                        os.path.join(pdir, task_id), meta, manager=self
                    )
                except Exception:
                    logger.exception("failed to recover task %s", task_id)
        for ts in self.tasks.values():
            for pm in ts.meta.pieces.values():
                if not pm.ref_task and pm.digest:
                    self.piece_index.record_holder(
                        pm.digest, pm.length, ts.meta.task_id, pm.number
                    )
        for ts in self.tasks.values():
            broken = []
            for pm in ts.meta.pieces.values():
                if not pm.ref_task:
                    continue
                if (
                    self.piece_index.find_holder(
                        pm.digest, pm.length, exclude_task=ts.meta.task_id
                    )
                    is None
                ):
                    broken.append(pm.number)
                else:
                    self.piece_index.record_ref(
                        pm.digest, pm.length, ts.meta.task_id, pm.number
                    )
            if broken:
                logger.warning(
                    "task %s: %d dedup refs lost their source; dropped for refetch",
                    ts.meta.task_id[:16], len(broken),
                )
                with ts.lock:
                    for n in broken:
                        ts.meta.pieces.pop(n, None)
                        ts._ref_count -= 1
                    # a 'done' task missing pieces is no longer complete
                    if ts.meta.done:
                        ts.meta.done = False
                    ts.persist()

    def register_task(
        self,
        task_id: str,
        peer_id: str,
        url: str = "",
        piece_length: int = 0,
        content_length: int = -1,
        tag: str = "",
        application: str = "",
    ) -> TaskStorage:
        with self.lock:
            ts = self.tasks.get(task_id)
            if ts is None:
                meta = TaskMeta(
                    task_id=task_id,
                    peer_id=peer_id,
                    url=url,
                    tag=tag,
                    application=application,
                    piece_length=piece_length,
                    content_length=content_length,
                )
                ts = TaskStorage(self._task_dir(task_id), meta, manager=self)
                ts.persist()
                self.tasks[task_id] = ts
            else:
                if piece_length and not ts.meta.piece_length:
                    ts.meta.piece_length = piece_length
                if content_length >= 0 and ts.meta.content_length < 0:
                    ts.meta.content_length = content_length
            return ts

    def load(self, task_id: str) -> TaskStorage | None:
        with self.lock:
            return self.tasks.get(task_id)

    def find_completed_task(self, task_id: str) -> TaskStorage | None:
        ts = self.load(task_id)
        return ts if ts is not None and ts.meta.done else None

    def resolve_piece(
        self, digest: str, length: int, exclude_task: str = ""
    ) -> tuple[str, int] | None:
        """→ (data_path, offset) of the physical bytes for ``digest``,
        or None when no holder survives (the referrer refetches)."""
        holder = self.piece_index.find_holder(digest, length, exclude_task=exclude_task)
        if holder is None:
            return None
        ts = self.load(holder[0])
        if ts is None:
            return None
        pm = ts.meta.pieces.get(holder[1])
        if pm is None or pm.digest != digest or pm.ref_task:
            return None
        return (ts.data_path, pm.offset)

    def _migrate_digest(
        self, victim: TaskStorage, digest: str, number: int, length: int
    ) -> bool:
        """Copy ``victim``'s piece ``number`` into one of the digest's
        referrers, which becomes the new physical holder (remaining
        refs re-point through the index automatically)."""
        src_pm = victim.meta.pieces.get(number)
        if src_pm is None or src_pm.ref_task:
            return False
        for ref_task_id, ref_number in self.piece_index.referrers(
            digest, exclude_task=victim.meta.task_id
        ):
            heir = self.load(ref_task_id)
            if heir is None:
                continue
            try:
                with heir.lock:
                    heir_pm = heir.meta.pieces.get(ref_number)
                    if heir_pm is None or heir_pm.digest != digest:
                        continue
                    _copy_span(
                        victim.data_path, src_pm.offset,
                        heir.data_path, heir_pm.offset, length,
                    )
                    heir_pm.ref_task = ""
                    heir._ref_count -= 1
                    heir.persist()
            except OSError as e:
                logger.warning(
                    "dedup migration %s -> %s failed: %s",
                    victim.meta.task_id[:16], ref_task_id[:16], e,
                )
                continue
            self.piece_index.record_holder(digest, length, ref_task_id, ref_number)
            EV_DEDUP_MIGRATE(
                digest=digest,
                from_task=victim.meta.task_id,
                to_task=ref_task_id,
                bytes=length,
            )
            M.PIECE_DEDUP_MIGRATE_TOTAL.inc()
            return True
        return False

    def migrate_owned_pieces(self, victim: TaskStorage) -> int:
        """Before ``victim``'s bytes go away, copy every piece that other
        tasks still reference into one of its referrers. Returns
        migrated count."""
        if not self.dedup_enabled:
            return 0
        migrated = 0
        for digest, number, length in self.piece_index.orphaned_by(victim.meta.task_id):
            migrated += int(self._migrate_digest(victim, digest, number, length))
        return migrated

    def release_task_bytes(self, victim: TaskStorage) -> None:
        """Refcount-safe removal of ``victim`` from the index: migrate
        referenced bytes out, drop its entries, then run ONE more
        migration pass for digests a racing ``add_ref_if_held`` attached
        to between the scan and the drop (the bytes are still on disk —
        the caller reclaims them only after this returns)."""
        self.migrate_owned_pieces(victim)
        for digest in self.piece_index.drop_task(victim.meta.task_id):
            pm = next(
                (
                    p
                    for p in victim.meta.pieces.values()
                    if p.digest == digest and not p.ref_task
                ),
                None,
            )
            if pm is not None:
                self._migrate_digest(victim, digest, pm.number, pm.length)

    def delete_task(self, task_id: str) -> None:
        with self.lock:
            ts = self.tasks.pop(task_id, None)
        if ts is not None:
            # refcount-safe GC: shared bytes move to a surviving
            # referrer before this task's files go
            self.release_task_bytes(ts)
            ts._close_write_handle()
            shutil.rmtree(ts.dir, ignore_errors=True)

    def total_bytes(self) -> int:
        with self.lock:
            return sum(t.size_on_disk() for t in self.tasks.values())

    def reclaim(self) -> int:
        """Evict least-recently-accessed completed tasks until under the
        byte budget. Returns the number of tasks evicted."""
        if not self.max_bytes:
            return 0
        evicted = 0
        while self.total_bytes() > self.max_bytes:
            with self.lock:
                now = time.time()
                candidates = [
                    t
                    for t in self.tasks.values()
                    # completed tasks, plus ABANDONED incomplete ones
                    # (crash leftovers would otherwise leak disk
                    # forever). A live conductor's task is never a
                    # candidate no matter how slowly its origin
                    # trickles — busy says someone owns it.
                    if t.meta.done
                    or (not t.busy and now - t.meta.access_time > self.abandoned_ttl)
                ]
                if not candidates:
                    break
                victim = min(candidates, key=lambda t: t.meta.access_time)
            self.delete_task(victim.meta.task_id)
            evicted += 1
        return evicted


def _copy_span(src_path: str, src_off: int, dst_path: str, dst_off: int, n: int) -> None:
    """Kernel-side span copy where the OS offers it (copy_file_range —
    reflink-capable filesystems share the extent outright), buffered
    read/write otherwise."""
    with open(src_path, "rb") as src, open(dst_path, "r+b") as dst:
        if hasattr(os, "copy_file_range"):
            left, soff, doff = n, src_off, dst_off
            try:
                while left > 0:
                    moved = os.copy_file_range(
                        src.fileno(), dst.fileno(), left, soff, doff
                    )
                    if moved == 0:
                        break
                    left -= moved
                    soff += moved
                    doff += moved
                if left == 0:
                    return
            except OSError:
                pass  # cross-device / unsupported fs: buffered fallback
        src.seek(src_off)
        dst.seek(dst_off)
        left = n
        while left > 0:
            chunk = src.read(min(left, _COPY_CHUNK))
            if not chunk:
                break
            dst.write(chunk)
            left -= len(chunk)


# hoisted (dfanalyze hot-module hygiene): verify_content_digest ran these
# imports per call
import hashlib as _hashlib  # noqa: E402

from dragonfly2_tpu.utils.digest import parse_digest as _parse_digest  # noqa: E402
