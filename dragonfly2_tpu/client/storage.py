"""Piece-level disk store for the peer daemon.

Role parity: reference client/daemon/storage/storage_manager.go:52-962 +
local_storage.go — RegisterTask/WritePiece/ReadPiece/ReadAllPieces/Store/
GetPieces with per-task metadata persisted next to the data file, md5
piece verification, and a disk-usage reclaimer wired into the GC
framework (reference storage_manager.go:80-89).

Layout: ``<data_dir>/<task_id[:3]>/<task_id>/{data,metadata.json}`` —
pieces are written at their offsets into one sparse data file, so a
completed task is a byte-identical copy of the origin object and
``store()`` can hardlink it out.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from dataclasses import asdict, dataclass, field

from dragonfly2_tpu.client import metrics as M
from dragonfly2_tpu.utils import dflog
from dragonfly2_tpu.utils.digest import md5_from_bytes

logger = dflog.get("client.storage")


@dataclass
class PieceMeta:
    number: int
    offset: int
    length: int
    digest: str = ""  # "md5:<hex>"
    traffic_type: str = ""
    cost_ns: int = 0
    parent_id: str = ""


@dataclass
class TaskMeta:
    task_id: str
    peer_id: str
    url: str = ""
    tag: str = ""
    application: str = ""
    content_length: int = -1
    total_piece_count: int = -1
    piece_length: int = 0
    done: bool = False
    access_time: float = field(default_factory=time.time)
    # minimal origin response headers (Content-Type at least), replayed
    # by the P2P transport so proxy clients see proper metadata
    headers: dict[str, str] = field(default_factory=dict)
    pieces: dict[int, PieceMeta] = field(default_factory=dict)

    def to_json(self) -> dict:
        d = asdict(self)
        d["pieces"] = {str(k): asdict(v) for k, v in self.pieces.items()}
        return d

    @classmethod
    def from_json(cls, d: dict) -> "TaskMeta":
        pieces = {int(k): PieceMeta(**v) for k, v in d.pop("pieces", {}).items()}
        return cls(**{**d, "pieces": pieces})


class TaskStorage:
    """One task's on-disk state: sparse data file + metadata."""

    PERSIST_EVERY = 64  # pieces between metadata flushes on the hot path

    def __init__(self, task_dir: str, meta: TaskMeta):
        self.dir = task_dir
        self.meta = meta
        self.lock = threading.RLock()
        self._dirty_pieces = 0
        # a live conductor owns this task (not persisted: after a crash
        # nothing is live, so orphans become reclaimable)
        self.busy = False
        os.makedirs(task_dir, exist_ok=True)
        self.data_path = os.path.join(task_dir, "data")
        self.meta_path = os.path.join(task_dir, "metadata.json")
        if not os.path.exists(self.data_path):
            open(self.data_path, "wb").close()

    def persist(self) -> None:
        tmp = self.meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.meta.to_json(), f)
        os.replace(tmp, self.meta_path)

    def write_piece(
        self,
        number: int,
        offset: int,
        data: bytes,
        digest: str = "",
        traffic_type: str = "",
        cost_ns: int = 0,
        parent_id: str = "",
    ) -> PieceMeta:
        """Write piece bytes at their offset; verifies md5 when a digest
        is given (advisory ``io.md5`` strategy, reference
        storage_manager.go digest handling)."""
        if digest:
            got = f"md5:{md5_from_bytes(data)}"
            if got != digest:
                raise StorageError(
                    f"piece {number} digest mismatch: want {digest} got {got}"
                )
        else:
            digest = f"md5:{md5_from_bytes(data)}"
        M.PIECE_DOWNLOADED_TOTAL.labels(traffic_type or "unknown").inc()
        M.PIECE_TRAFFIC_BYTES.labels(traffic_type or "unknown").inc(len(data))
        with self.lock:
            with open(self.data_path, "r+b") as f:
                f.seek(offset)
                f.write(data)
            pm = PieceMeta(
                number=number,
                offset=offset,
                length=len(data),
                digest=digest,
                traffic_type=traffic_type,
                cost_ns=cost_ns,
                parent_id=parent_id,
            )
            self.meta.pieces[number] = pm
            self.meta.access_time = time.time()
            # amortize metadata persistence: the full JSON rewrite is
            # O(pieces), so flushing per piece would make the hot path
            # O(n²) and skew cost_ns labels; a crash loses at most the
            # last PERSIST_EVERY piece *metadata* entries (bytes are on
            # disk; unlisted pieces are re-fetched on resume)
            self._dirty_pieces += 1
            if self._dirty_pieces >= self.PERSIST_EVERY:
                self._dirty_pieces = 0
                self.persist()
            return pm

    def read_piece(self, number: int) -> bytes:
        with self.lock:
            pm = self.meta.pieces.get(number)
            if pm is None:
                raise StorageError(f"piece {number} not found in {self.meta.task_id}")
            self.meta.access_time = time.time()
            with open(self.data_path, "rb") as f:
                f.seek(pm.offset)
                return f.read(pm.length)

    def read_range(self, offset: int, length: int) -> bytes:
        with self.lock:
            self.meta.access_time = time.time()
            with open(self.data_path, "rb") as f:
                f.seek(offset)
                return f.read(length)

    def read_all(self) -> bytes:
        with self.lock:
            if not self.meta.done:
                raise StorageError(f"task {self.meta.task_id} is not complete")
            with open(self.data_path, "rb") as f:
                return f.read()

    def verify_content_digest(self, expected: str) -> None:
        """Whole-task digest check against UrlMeta.digest ('sha256:…' /
        'md5:…'), hashed streaming so large tasks never materialize in
        RAM. The reference declares this check but left it TODO
        (peertask_conductor.go:607). For a RANGE task the pin covers the
        slice (the task's content IS the slice). The hash runs with the
        storage lock released — the task is complete and its data file
        immutable, and holding the lock would stall every peer this
        daemon is serving for the duration."""
        import hashlib

        from dragonfly2_tpu.utils.digest import parse_digest

        algorithm, want = parse_digest(expected)
        h = hashlib.new(algorithm)
        with self.lock:
            length = self.meta.content_length
            path = self.data_path
        with open(path, "rb") as f:
            remaining = length if length >= 0 else None
            while True:
                n = 1 << 20 if remaining is None else min(1 << 20, remaining)
                if n == 0:
                    break
                chunk = f.read(n)
                if not chunk:
                    break
                h.update(chunk)
                if remaining is not None:
                    remaining -= len(chunk)
        if h.hexdigest() != want.lower():
            raise StorageError(
                f"task {self.meta.task_id} content digest mismatch:"
                f" want {expected}, got {algorithm}:{h.hexdigest()}"
            )


    def mark_done(
        self, content_length: int | None = None, expected_digest: str = ""
    ) -> None:
        """Complete the task. With ``expected_digest`` the content is
        verified FIRST and ``done`` only ever flips on a match — a
        concurrent reuse lookup (which requires done) can never observe
        unverified pinned content, no matter how long the hash takes.
        On mismatch the stored pieces are purged (a retry must
        re-download, not re-fail on the same bytes) and StorageError
        raises."""
        with self.lock:
            if content_length is not None:
                self.meta.content_length = content_length
            if self.meta.content_length >= 0:
                # truncate to exact length (last piece may have been
                # written into a sparse hole)
                with open(self.data_path, "r+b") as f:
                    f.truncate(self.meta.content_length)
        if expected_digest:
            try:
                self.verify_content_digest(expected_digest)
            except StorageError:
                with self.lock:
                    self.meta.pieces.clear()
                    self.meta.total_piece_count = 0
                    open(self.data_path, "wb").close()  # drop the bytes
                    self.persist()
                raise
        with self.lock:
            self.meta.done = True
            self.meta.total_piece_count = len(self.meta.pieces)
            self.persist()

    def store(self, dest: str) -> None:
        """Hardlink-or-copy the completed data file to ``dest``
        (reference dfget output handling)."""
        with self.lock:
            if not self.meta.done:
                raise StorageError(f"task {self.meta.task_id} is not complete")
            os.makedirs(os.path.dirname(os.path.abspath(dest)) or ".", exist_ok=True)
            if os.path.exists(dest):
                os.remove(dest)
            try:
                os.link(self.data_path, dest)
            except OSError:
                shutil.copyfile(self.data_path, dest)

    def size_on_disk(self) -> int:
        try:
            return os.path.getsize(self.data_path)
        except OSError:
            return 0


class StorageError(Exception):
    pass


class StorageManager:
    """All tasks' disk state + reuse index + reclaimer.

    Reference client/daemon/storage/storage_manager.go:52-124 (API) and
    :80-89 (Reclaimer: evict least-recently-accessed completed tasks when
    disk usage crosses the high watermark).
    """

    def __init__(self, data_dir: str, max_bytes: int = 0, abandoned_ttl: float = 3600.0):
        self.data_dir = data_dir
        self.max_bytes = max_bytes  # 0 = unbounded
        # incomplete tasks idle this long AND not owned by a live
        # conductor count as abandoned (crash leftovers)
        self.abandoned_ttl = abandoned_ttl
        self.tasks: dict[str, TaskStorage] = {}
        self.lock = threading.RLock()
        os.makedirs(data_dir, exist_ok=True)
        self._load_existing()

    def _task_dir(self, task_id: str) -> str:
        return os.path.join(self.data_dir, task_id[:3], task_id)

    def _load_existing(self) -> None:
        """Recover persisted tasks on restart (download-side resume,
        reference client/daemon/peer/peertask_reuse.go)."""
        for prefix in os.listdir(self.data_dir):
            pdir = os.path.join(self.data_dir, prefix)
            if not os.path.isdir(pdir):
                continue
            for task_id in os.listdir(pdir):
                meta_path = os.path.join(pdir, task_id, "metadata.json")
                if not os.path.exists(meta_path):
                    continue
                try:
                    with open(meta_path) as f:
                        meta = TaskMeta.from_json(json.load(f))
                    self.tasks[task_id] = TaskStorage(os.path.join(pdir, task_id), meta)
                except Exception:
                    logger.exception("failed to recover task %s", task_id)

    def register_task(
        self,
        task_id: str,
        peer_id: str,
        url: str = "",
        piece_length: int = 0,
        content_length: int = -1,
        tag: str = "",
        application: str = "",
    ) -> TaskStorage:
        with self.lock:
            ts = self.tasks.get(task_id)
            if ts is None:
                meta = TaskMeta(
                    task_id=task_id,
                    peer_id=peer_id,
                    url=url,
                    tag=tag,
                    application=application,
                    piece_length=piece_length,
                    content_length=content_length,
                )
                ts = TaskStorage(self._task_dir(task_id), meta)
                ts.persist()
                self.tasks[task_id] = ts
            else:
                if piece_length and not ts.meta.piece_length:
                    ts.meta.piece_length = piece_length
                if content_length >= 0 and ts.meta.content_length < 0:
                    ts.meta.content_length = content_length
            return ts

    def load(self, task_id: str) -> TaskStorage | None:
        with self.lock:
            return self.tasks.get(task_id)

    def find_completed_task(self, task_id: str) -> TaskStorage | None:
        ts = self.load(task_id)
        return ts if ts is not None and ts.meta.done else None

    def delete_task(self, task_id: str) -> None:
        with self.lock:
            ts = self.tasks.pop(task_id, None)
        if ts is not None:
            shutil.rmtree(ts.dir, ignore_errors=True)

    def total_bytes(self) -> int:
        with self.lock:
            return sum(t.size_on_disk() for t in self.tasks.values())

    def reclaim(self) -> int:
        """Evict least-recently-accessed completed tasks until under the
        byte budget. Returns the number of tasks evicted."""
        if not self.max_bytes:
            return 0
        evicted = 0
        while self.total_bytes() > self.max_bytes:
            with self.lock:
                now = time.time()
                candidates = [
                    t
                    for t in self.tasks.values()
                    # completed tasks, plus ABANDONED incomplete ones
                    # (crash leftovers would otherwise leak disk
                    # forever). A live conductor's task is never a
                    # candidate no matter how slowly its origin
                    # trickles — busy says someone owns it.
                    if t.meta.done
                    or (not t.busy and now - t.meta.access_time > self.abandoned_ttl)
                ]
                if not candidates:
                    break
                victim = min(candidates, key=lambda t: t.meta.access_time)
            self.delete_task(victim.meta.task_id)
            evicted += 1
        return evicted
