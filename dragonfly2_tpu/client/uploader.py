"""HTTP upload server — the parent side of piece transfer.

Role parity: reference client/daemon/upload/upload_manager.go:59-196 —
``GET /download/<task_id>?peerId=&number=`` serves piece bytes out of the
local piece store, with Range support for arbitrary byte windows. Piece
bytes ride HTTP between daemons (the gRPC plane carries only piece
*metadata*), exactly like the reference (upload_manager.go:149-196).
"""

from __future__ import annotations

import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from dragonfly2_tpu.client.piece_manager import RateLimiter
from dragonfly2_tpu.client.storage import StorageManager
from dragonfly2_tpu.client import metrics as M
from dragonfly2_tpu.utils import dflog

logger = dflog.get("client.upload")

_RANGE_RE = re.compile(r"bytes=(\d+)-(\d*)")


class UploadServer:
    """Serves pieces to child peers over HTTP."""

    def __init__(
        self,
        storage: StorageManager,
        host: str = "127.0.0.1",
        port: int = 0,
        delay_s: float = 0.0,
        cold_piece_delay_s: float = 0.0,
        rate_limit_bps: float = 0.0,
    ):
        self.storage = storage
        # synthetic per-piece serving latency — benchmarking/AB-harness
        # knob to model slow hosts; 0 in production
        self.delay_s = delay_s
        # extra latency on piece 0 only — models the benign cold-piece
        # effect (TCP slow start / cold cache on a task's first chunk)
        # the GRU bad-node A/B scenario relies on; 0 in production
        self.cold_piece_delay_s = cold_piece_delay_s
        # global upload bandwidth budget shared by all child peers
        # (reference upload_manager totalRateLimit); 0 = unlimited
        self.limiter = RateLimiter(rate_limit_bps)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route to dflog, not stderr
                logger.debug("upload: " + fmt % args)

            def do_GET(self):
                outer._handle(self)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        return f"{self._server.server_address[0]}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="upload-server", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # ------------------------------------------------------------------
    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(req.path)
        parts = parsed.path.strip("/").split("/")
        if len(parts) != 2 or parts[0] != "download":
            req.send_error(404, "unknown path")
            return
        task_id = parts[1]
        qs = parse_qs(parsed.query)
        ts = self.storage.load(task_id)
        if ts is None:
            req.send_error(404, f"task {task_id} not found")
            return

        if self.delay_s > 0:
            time.sleep(self.delay_s)
        number = qs.get("number", [None])[0]
        if number is not None:
            # piece fetch by number — parsed ONCE, with the malformed
            # case answered 404 like every other bad-request path (not a
            # handler crash)
            try:
                piece_number = int(number)
            except ValueError:
                req.send_error(404, f"bad piece number {number!r}")
                return
            if self.cold_piece_delay_s > 0 and piece_number == 0:
                time.sleep(self.cold_piece_delay_s)
            try:
                data = ts.read_piece(piece_number)
            except Exception as e:
                req.send_error(404, str(e))
                return
            pm = ts.meta.pieces[piece_number]
            M.PIECE_UPLOADED_TOTAL.inc()
            M.PIECE_UPLOAD_BYTES.inc(len(data))
            req.send_response(200)
            req.send_header("Content-Length", str(len(data)))
            req.send_header("X-Dragonfly-Piece-Digest", pm.digest)
            # origin response metadata travels with the pieces so every
            # peer in the swarm can replay it (transport Content-Type)
            ct = ts.meta.headers.get("Content-Type", "")
            if ct:
                req.send_header("X-Dragonfly-Origin-Content-Type", ct)
            req.end_headers()
            self._write_limited(req, data)
            return

        rng = req.headers.get("Range")
        if rng:
            m = _RANGE_RE.match(rng)
            if not m:
                req.send_error(416, "bad range")
                return
            start = int(m.group(1))
            total = ts.meta.content_length
            end = int(m.group(2)) if m.group(2) else (total - 1 if total >= 0 else -1)
            if end < start:
                req.send_error(416, "bad range")
                return
            data = ts.read_range(start, end - start + 1)
            req.send_response(206)
            req.send_header("Content-Length", str(len(data)))
            req.send_header(
                "Content-Range", f"bytes {start}-{start + len(data) - 1}/{total}"
            )
            req.end_headers()
            self._write_limited(req, data)
            return

        # whole object (requires completion)
        try:
            data = ts.read_all()
        except Exception as e:
            req.send_error(409, str(e))
            return
        req.send_response(200)
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        self._write_limited(req, data)

    def _write_limited(self, req: BaseHTTPRequestHandler, data: bytes) -> None:
        """Write the body through the shared upload-rate token bucket in
        64 KiB chunks — concurrent child peers split the budget rather
        than each getting the full rate."""
        if self.limiter.rate <= 0:
            req.wfile.write(data)
            return
        chunk = 64 * 1024
        mv = memoryview(data)  # zero-copy slicing — no per-chunk bytes alloc
        for off in range(0, len(data), chunk):
            part = mv[off : off + chunk]
            self.limiter.acquire(len(part))
            req.wfile.write(part)
