"""HTTP upload server — the parent side of piece transfer.

Role parity: reference client/daemon/upload/upload_manager.go:59-196 —
``GET /download/<task_id>?peerId=&number=`` serves piece bytes out of the
local piece store, with Range support for arbitrary byte windows. Piece
bytes ride HTTP between daemons (the gRPC plane carries only piece
*metadata*), exactly like the reference (upload_manager.go:149-196).

Zero-copy data plane (docs/data-plane.md): one readiness-based selector
loop holds every child connection — no thread per transfer — and piece
bodies go ``os.sendfile`` straight from the task's sparse data file at
the piece's span, never materializing through Python ``bytes``. The
upload rate limiter still applies: the body is windowed through the
shared token bucket in ``WINDOW``-sized sendfile calls, so concurrent
children split the budget exactly as before. The synthetic ``delay_s``/
``cold_piece_delay_s`` knobs become loop timers (a delayed response
parks its connection; nothing sleeps). ``use_sendfile=False`` (or
``DF_UPLOAD_SENDFILE=0``) selects the buffered fallback — same loop,
bodies copied through userspace — which bench races against the
zero-copy path.
"""

# dfanalyze: hot — the serve loop runs per child request at swarm scale

from __future__ import annotations

import os
import re
import selectors
import socket
import time
from urllib.parse import parse_qs, urlparse

from dragonfly2_tpu.client import metrics as M
from dragonfly2_tpu.client.piece_manager import RateLimiter
from dragonfly2_tpu.client.storage import StorageError, StorageManager
from dragonfly2_tpu.client.transfer import EventLoop
from dragonfly2_tpu.utils import dflog, flight, flows, profiling

logger = dflog.get("client.upload")

_RANGE_RE = re.compile(r"bytes=(\d+)-(\d*)")

# dfprof phases: wall per served piece response (parse → last body byte)
# and the slice of it spent inside the kernel send path
PH_PIECE_SERVE = profiling.phase_type("daemon.piece_serve")
PH_PIECE_SENDFILE = profiling.phase_type("daemon.piece_sendfile")

# flight event: a child dropping mid-body — normal churn at swarm scale,
# but the postmortem ring should know who vanished and when
EV_CHILD_DISCONNECT = flight.event_type("daemon.child_disconnect")

WINDOW = 256 * 1024  # body bytes per sendfile window (unlimited path)
RATE_WINDOW = 64 * 1024  # window under a rate cap (token granularity)
_MAX_REQUEST = 32 * 1024


class _Conn:
    """One child connection's state machine: parse request → (optional
    deferred start) → stream response spans → next request (keep-alive)."""

    __slots__ = (
        "sock", "peer", "buf", "head", "spans", "span_file", "span_off",
        "span_left", "body_done", "close_after", "serving_piece",
        "serve_t0", "flow_plane", "writing", "zero_left", "pending",
    )

    def __init__(self, sock: socket.socket, peer):
        self.sock = sock
        self.peer = peer
        self.buf = b""
        self.head = b""  # pending response header bytes
        # body plan: list of (path|None, offset, length) spans, consumed
        # front to back; path None = synthesized zeros (sparse hole)
        self.spans: list = []
        self.span_file = None  # open fd for the span being sent
        self.span_off = 0
        self.span_left = 0
        self.zero_left = 0
        self.body_done = True
        self.close_after = False
        self.serving_piece = False  # counts toward piece metrics/phases
        self.serve_t0 = 0.0
        self.flow_plane = "file"  # demanded plane of the piece's task
        self.writing = False
        # a response parked on a delay timer: requests pipelined behind
        # it must wait (HTTP/1.1 ordering), and the timer must find the
        # connection in the state it left it
        self.pending = False

    def close_file(self) -> None:
        if self.span_file is not None:
            try:
                os.close(self.span_file)
            except OSError:
                pass
            self.span_file = None


class UploadServer:
    """Serves pieces to child peers from one readiness-based loop."""

    def __init__(
        self,
        storage: StorageManager,
        host: str = "127.0.0.1",
        port: int = 0,
        delay_s: float = 0.0,
        cold_piece_delay_s: float = 0.0,
        rate_limit_bps: float = 0.0,
        use_sendfile: bool | None = None,
    ):
        self.storage = storage
        # synthetic per-piece serving latency — benchmarking/AB-harness
        # knob to model slow hosts; 0 in production
        self.delay_s = delay_s
        # extra latency on piece 0 only — models the benign cold-piece
        # effect (TCP slow start / cold cache on a task's first chunk)
        # the GRU bad-node A/B scenario relies on; 0 in production
        self.cold_piece_delay_s = cold_piece_delay_s
        # global upload bandwidth budget shared by all child peers
        # (reference upload_manager totalRateLimit); 0 = unlimited
        self.limiter = RateLimiter(rate_limit_bps)
        # DF_UPLOAD_SENDFILE=0 is a kill switch (it can only disable),
        # and platform availability always gates — an explicit
        # config True must not force sendfile onto an os without it
        self.use_sendfile = (
            (True if use_sendfile is None else bool(use_sendfile))
            and hasattr(os, "sendfile")
            and os.environ.get("DF_UPLOAD_SENDFILE", "1") != "0"
        )
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(1024)
        self._lsock.setblocking(False)
        self.host = self._lsock.getsockname()[0]
        self.port = self._lsock.getsockname()[1]
        self.loop = EventLoop(f"upload-{self.port}")
        self._conns: set[_Conn] = set()
        self._started = False
        self._stopped = False

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self.loop.call_soon(
            lambda: self.loop.register(
                self._lsock, selectors.EVENT_READ, self._accept
            )
        )
        self.loop.start()

    def stop(self) -> None:
        if self._stopped or not self._started:
            # never started: still close the listener so the port frees
            if not self._started and not self._stopped:
                self._stopped = True
                try:
                    self._lsock.close()
                except OSError:
                    pass
            return
        self._stopped = True
        self.loop.stop(on_stop=self._teardown)

    def _teardown(self) -> None:
        self.loop.unregister(self._lsock)
        try:
            self._lsock.close()
        except OSError:
            pass
        for conn in list(self._conns):
            self._drop(conn)

    # ------------------------------------------------------------------
    # loop handlers
    # ------------------------------------------------------------------
    def _accept(self, mask) -> None:
        while True:
            try:
                sock, peer = self._lsock.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock, peer)
            self._conns.add(conn)
            M.UPLOAD_CONNECTIONS.inc()
            self.loop.register(
                sock, selectors.EVENT_READ, lambda m, c=conn: self._on_event(c, m)
            )

    def _drop(self, conn: _Conn, mid_body: bool = False) -> None:
        if conn not in self._conns:
            return
        self._conns.discard(conn)
        M.UPLOAD_CONNECTIONS.dec()
        if mid_body:
            M.CHILD_DISCONNECT_TOTAL.inc()
            EV_CHILD_DISCONNECT(
                peer=f"{conn.peer[0]}:{conn.peer[1]}" if conn.peer else "?",
                bytes_left=conn.span_left + conn.zero_left
                + sum(s[2] for s in conn.spans),
            )
            logger.debug("child %s disconnected mid-body", conn.peer)
        conn.close_file()
        self.loop.unregister(conn.sock)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _on_event(self, conn: _Conn, mask) -> None:
        try:
            if mask & selectors.EVENT_WRITE:
                self._send_some(conn)
            if mask & selectors.EVENT_READ:
                self._read_request(conn)
        except (BrokenPipeError, ConnectionResetError):
            # a child dropping mid-transfer is swarm churn, not an error:
            # count it, log at debug, never traceback (satellite #1)
            self._drop(conn, mid_body=not conn.body_done)
        except (BlockingIOError, InterruptedError):
            pass
        except OSError as e:
            self._drop(conn, mid_body=not conn.body_done)
            logger.debug("child %s connection error: %s", conn.peer, e)

    def _read_request(self, conn: _Conn) -> None:
        data = conn.sock.recv(_MAX_REQUEST)
        if not data:
            self._drop(conn, mid_body=not conn.body_done)
            return
        conn.buf += data
        if len(conn.buf) > _MAX_REQUEST:
            self._drop(conn)
            return
        if not conn.body_done or conn.head or conn.pending:
            return  # request pipelined ahead of our response; parse later
        self._maybe_parse(conn)

    def _maybe_parse(self, conn: _Conn) -> None:
        end = conn.buf.find(b"\r\n\r\n")
        if end < 0:
            return
        head, conn.buf = conn.buf[:end], conn.buf[end + 4:]
        lines = head.split(b"\r\n")
        try:
            method, target, _ = lines[0].decode("latin1").split(" ", 2)
        except ValueError:
            self._drop(conn)
            return
        headers = {}
        for line in lines[1:]:
            k, _, v = line.partition(b":")
            headers[k.strip().decode("latin1").lower()] = v.strip().decode("latin1")
        conn.close_after = headers.get("connection", "").lower() == "close"
        if method != "GET":
            self._error(conn, 405, "method not allowed", close=True)
            return
        delay = self.delay_s
        piece_q = None
        parsed = urlparse(target)
        parts = parsed.path.strip("/").split("/")
        if len(parts) == 2 and parts[0] == "download":
            piece_q = parse_qs(parsed.query).get("number", [None])[0]
            if self.cold_piece_delay_s > 0 and piece_q == "0":
                delay += self.cold_piece_delay_s
        if delay > 0:
            # the synthetic-latency knobs park the connection on a loop
            # timer — no thread sleeps, so 1000 delayed children cost
            # 1000 timer entries, not 1000 blocked threads
            conn.pending = True
            self.loop.call_at(
                time.monotonic() + delay,
                lambda: self._respond_safe(conn, parsed, headers),
            )
            return
        self._respond(conn, parsed, headers)

    def _respond_safe(self, conn: _Conn, parsed, headers) -> None:
        if conn not in self._conns:
            return  # child gave up during the synthetic delay
        conn.pending = False
        try:
            self._respond(conn, parsed, headers)
        except (BlockingIOError, InterruptedError):
            pass  # EVENT_WRITE is armed; the loop resumes the send
        except (BrokenPipeError, ConnectionResetError, OSError):
            self._drop(conn, mid_body=not conn.body_done)

    # ------------------------------------------------------------------
    # request → response plan
    # ------------------------------------------------------------------
    def _respond(self, conn: _Conn, parsed, req_headers: dict) -> None:
        parts = parsed.path.strip("/").split("/")
        if len(parts) != 2 or parts[0] != "download":
            self._error(conn, 404, "unknown path")
            return
        task_id = parts[1]
        qs = parse_qs(parsed.query)
        ts = self.storage.load(task_id)
        if ts is None:
            self._error(conn, 404, f"task {task_id} not found")
            return

        number = qs.get("number", [None])[0]
        if number is not None:
            # piece fetch by number — parsed ONCE, with the malformed
            # case answered 404 like every other bad-request path
            try:
                piece_number = int(number)
            except ValueError:
                self._error(conn, 404, f"bad piece number {number!r}")
                return
            try:
                path, off, length, digest = ts.piece_span(piece_number)
            except StorageError as e:
                self._error(conn, 404, str(e))
                return
            extra = [("X-Dragonfly-Piece-Digest", digest)]
            # origin response metadata travels with the pieces so every
            # peer in the swarm can replay it (transport Content-Type)
            ct = ts.meta.headers.get("Content-Type", "")
            if ct:
                extra.append(("X-Dragonfly-Origin-Content-Type", ct))
            conn.serving_piece = True
            conn.serve_t0 = time.perf_counter()
            conn.flow_plane = flows.task_plane(task_id)
            self._start_response(
                conn, 200, [(path, off, length)], length, extra
            )
            return

        rng = req_headers.get("range")
        if rng:
            m = _RANGE_RE.match(rng)
            if not m:
                self._error(conn, 416, "bad range")
                return
            start = int(m.group(1))
            total = ts.meta.content_length
            if m.group(2):
                end = int(m.group(2))
            elif total >= 0:
                end = total - 1
            else:
                # open-ended range on a task whose length is still
                # unknown: serve to the current end-of-data instead of
                # refusing a valid request (satellite #2)
                end = ts.current_end() - 1
            if end < start:
                self._error(conn, 416, "bad range")
                return
            try:
                spans = ts.range_spans(start, end - start + 1)
            except StorageError as e:
                # a dedup ref whose physical holder vanished mid-plan:
                # an answered 404 beats a silently hung child
                self._error(conn, 404, str(e))
                return
            n = sum(s[2] for s in spans)
            self._start_response(
                conn, 206, spans, n,
                [("Content-Range", f"bytes {start}-{start + n - 1}/{total}")],
            )
            return

        # whole object (requires completion) — streamed span by span in
        # WINDOW chunks, never materialized via read_all()
        with ts.lock:
            done = ts.meta.done
            size = ts.meta.content_length
        if not done:
            self._error(conn, 409, f"task {ts.meta.task_id} is not complete")
            return
        if size < 0:
            size = ts.current_end()
        try:
            spans = ts.range_spans(0, size)
        except StorageError as e:
            self._error(conn, 404, str(e))
            return
        got = sum(s[2] for s in spans)
        if got < size:
            spans.append((None, 0, size - got))  # trailing sparse hole
        self._start_response(conn, 200, spans, size, [])

    def _error(self, conn: _Conn, code: int, msg: str, close: bool = False) -> None:
        # bad-request answers stay keep-alive (a child probing for a
        # piece its in-progress parent hasn't written yet 404s MANY
        # times — reconnect churn per probe would swamp the swarm);
        # protocol-level errors still close
        body = f"{code}: {msg}\n".encode()
        reason = {404: "Not Found", 405: "Method Not Allowed",
                  409: "Conflict", 416: "Range Not Satisfiable"}.get(code, "Error")
        conn.close_after = conn.close_after or close
        conn.head = (
            f"HTTP/1.1 {code} {reason}\r\n"
            f"Content-Length: {len(body)}\r\n"
            "Content-Type: text/plain\r\n"
            + ("Connection: close\r\n" if conn.close_after else "")
            + "\r\n"
        ).encode() + body
        conn.body_done = True
        conn.spans = []
        self._arm_write(conn)

    def _start_response(
        self, conn: _Conn, code: int, spans: list, content_length: int, extra
    ) -> None:
        reason = {200: "OK", 206: "Partial Content"}[code]
        lines = [f"HTTP/1.1 {code} {reason}", f"Content-Length: {content_length}"]
        for k, v in extra:
            lines.append(f"{k}: {v}")
        if conn.close_after:
            lines.append("Connection: close")
        conn.head = ("\r\n".join(lines) + "\r\n\r\n").encode()
        conn.spans = [s for s in spans if s[2] > 0]
        conn.body_done = not conn.spans
        self._arm_write(conn)

    # ------------------------------------------------------------------
    # response pump
    # ------------------------------------------------------------------
    def _arm_write(self, conn: _Conn) -> None:
        if not conn.writing:
            conn.writing = True
            self.loop.modify(
                conn.sock,
                selectors.EVENT_READ | selectors.EVENT_WRITE,
                lambda m, c=conn: self._on_event(c, m),
            )
        self._send_some(conn)

    def _disarm_write(self, conn: _Conn) -> None:
        if conn.writing:
            conn.writing = False
            self.loop.modify(
                conn.sock, selectors.EVENT_READ,
                lambda m, c=conn: self._on_event(c, m),
            )

    def _park(self, conn: _Conn, wait_s: float) -> None:
        """Rate-limit stall: stop watching EVENT_WRITE and resume on a
        timer — the loop stays free for every other child."""
        self._disarm_write(conn)
        self.loop.call_at(
            time.monotonic() + wait_s, lambda: self._resume(conn)
        )

    def _resume(self, conn: _Conn) -> None:
        if conn in self._conns and not conn.body_done:
            try:
                self._arm_write(conn)
            except (BrokenPipeError, ConnectionResetError, OSError):
                self._drop(conn, mid_body=True)

    def _send_some(self, conn: _Conn) -> None:
        # 1) response headers
        while conn.head:
            sent = conn.sock.send(conn.head)
            conn.head = conn.head[sent:]
            if conn.head:
                return  # socket full — EVENT_WRITE re-fires
        # 2) body spans
        while not conn.body_done:
            if conn.span_left == 0 and conn.zero_left == 0:
                conn.close_file()
                if not conn.spans:
                    self._finish_response(conn)
                    return
                path, off, length = conn.spans.pop(0)
                if path is None:
                    conn.zero_left = length
                else:
                    try:
                        conn.span_file = os.open(path, os.O_RDONLY)
                    except OSError as e:
                        # span vanished mid-plan (task GC'd): the header
                        # promised Content-Length, so the only honest
                        # move is to cut the connection
                        logger.warning("serve span %s failed: %s", path, e)
                        self._drop(conn, mid_body=True)
                        return
                    conn.span_off = off
                    conn.span_left = length
            window = min(
                WINDOW, conn.span_left if conn.span_left else conn.zero_left
            )
            if self.limiter.rate > 0:
                # finer windows under a rate cap: the debt-based bucket
                # admits one oversized window whole, which would let a
                # single child burst far past its share
                window = min(window, RATE_WINDOW)
                wait = self.limiter.acquire_nowait(window)
                if wait > 0:
                    self._park(conn, wait)
                    return
            try:
                sent = self._send_window(conn, window)
            except BlockingIOError:
                if self.limiter.rate > 0:
                    # socket full after tokens were debited: refund what
                    # we couldn't send so the budget stays honest
                    self.limiter.refund(window)
                return
            if self.limiter.rate > 0 and sent < window:
                self.limiter.refund(window - sent)
            if sent == 0:
                return
        self._finish_response(conn)

    def _send_window(self, conn: _Conn, window: int) -> int:
        """Send up to ``window`` body bytes; returns bytes sent. Raises
        BlockingIOError when the socket can't take any."""
        if conn.zero_left:
            n = conn.sock.send(b"\0" * min(window, conn.zero_left))
            conn.zero_left -= n
            if conn.zero_left == 0 and not conn.spans and conn.span_left == 0:
                conn.body_done = True
            return n
        t0 = time.perf_counter()
        if self.use_sendfile:
            n = os.sendfile(
                conn.sock.fileno(), conn.span_file, conn.span_off, window
            )
        else:
            # buffered fallback: same loop, bytes copied through
            # userspace — what the bench races the zero-copy path against
            data = os.pread(conn.span_file, window, conn.span_off)
            n = conn.sock.send(data)
        if conn.serving_piece:
            PH_PIECE_SENDFILE.observe(time.perf_counter() - t0)
        if n == 0 and window > 0:
            raise BrokenPipeError("sendfile returned 0")
        conn.span_off += n
        conn.span_left -= n
        if conn.serving_piece:
            M.PIECE_UPLOAD_BYTES.inc(n)
            flows.upload(conn.flow_plane, n)
        if conn.span_left == 0 and not conn.spans and conn.zero_left == 0:
            conn.body_done = True
        return n

    def _finish_response(self, conn: _Conn) -> None:
        conn.body_done = True
        conn.close_file()
        if conn.serving_piece:
            M.PIECE_UPLOADED_TOTAL.inc()
            PH_PIECE_SERVE.observe(time.perf_counter() - conn.serve_t0)
            conn.serving_piece = False
        if conn.close_after:
            self._drop(conn)
            return
        self._disarm_write(conn)
        # keep-alive: a pipelined next request may already be buffered —
        # scheduled, not recursed, so a deep pipeline can't stack-dive
        if conn.buf:
            self.loop.call_soon(lambda: self._pipeline_next(conn))

    def _pipeline_next(self, conn: _Conn) -> None:
        if (
            conn in self._conns
            and conn.body_done
            and not conn.head
            and not conn.pending
        ):
            try:
                self._maybe_parse(conn)
            except (BlockingIOError, InterruptedError):
                pass
            except (BrokenPipeError, ConnectionResetError, OSError):
                self._drop(conn, mid_body=not conn.body_done)
