"""dfcache — P2P cache CLI: stat/import/export/delete of cached blobs.

Role parity: reference client/dfcache/ + cmd/dfcache/cmd/root.go:42 —
thin client of the local daemon's dfdaemon gRPC cache ops.
"""

from __future__ import annotations

import argparse
import os
import sys

from dragonfly2_tpu.rpc import gen  # noqa: F401
import common_pb2  # noqa: E402
import dfdaemon_pb2  # noqa: E402

import grpc

from dragonfly2_tpu.rpc import glue

from dragonfly2_tpu.rpc.glue import DFDAEMON_SERVICE


def _client(daemon_address: str) -> glue.ServiceClient:
    return glue.ServiceClient(glue.dial(daemon_address), DFDAEMON_SERVICE)


def _meta(tag: str, application: str) -> common_pb2.UrlMeta:
    return common_pb2.UrlMeta(tag=tag, application=application)


def stat(daemon_address: str, url: str, tag: str = "", application: str = "") -> bool:
    try:
        _client(daemon_address).StatTask(
            dfdaemon_pb2.StatTaskRequest(url=url, url_meta=_meta(tag, application), local_only=True)
        )
        return True
    except grpc.RpcError as e:
        if e.code() == grpc.StatusCode.NOT_FOUND:
            return False
        raise


def import_file(daemon_address: str, path: str, url: str, tag: str = "", application: str = "") -> None:
    _client(daemon_address).ImportTask(
        dfdaemon_pb2.ImportTaskRequest(
            path=os.path.abspath(path), url=url, url_meta=_meta(tag, application)
        )
    )


def export_file(
    daemon_address: str, url: str, output: str, tag: str = "",
    application: str = "", local_only: bool = False,
) -> None:
    _client(daemon_address).ExportTask(
        dfdaemon_pb2.ExportTaskRequest(
            url=url, output=os.path.abspath(output),
            url_meta=_meta(tag, application), local_only=local_only,
        )
    )


def delete(daemon_address: str, url: str, tag: str = "", application: str = "") -> None:
    _client(daemon_address).DeleteTask(
        dfdaemon_pb2.DeleteTaskRequest(url=url, url_meta=_meta(tag, application))
    )


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="dfcache", description="P2P cache ops")
    p.add_argument("command", choices=["stat", "import", "export", "delete"])
    p.add_argument("url")
    p.add_argument("--daemon", default=os.environ.get("DFDAEMON_ADDR", "127.0.0.1:65000"))
    p.add_argument("--path", default="", help="local file (import)")
    p.add_argument("--output", default="", help="destination path (export)")
    p.add_argument("--tag", default="")
    p.add_argument("--application", default="")
    p.add_argument("--local-only", action="store_true")
    # spawn-or-reuse, same as dfget (reference dfcache also spawns the
    # daemon over the unix socket when none answers)
    from dragonfly2_tpu.client.dfget import add_spawn_daemon_args

    add_spawn_daemon_args(p)
    args = p.parse_args(argv)

    if args.spawn_daemon:
        from dragonfly2_tpu.client.dfget import ensure_daemon

        ensure_daemon(args.daemon, args.scheduler, args.daemon_data_dir)

    if args.command == "stat":
        ok = stat(args.daemon, args.url, args.tag, args.application)
        print("cached" if ok else "not cached")
        return 0 if ok else 1
    if args.command == "import":
        import_file(args.daemon, args.path, args.url, args.tag, args.application)
    elif args.command == "export":
        export_file(args.daemon, args.url, args.output, args.tag, args.application, args.local_only)
    elif args.command == "delete":
        delete(args.daemon, args.url, args.tag, args.application)
    return 0


if __name__ == "__main__":
    sys.exit(main())
