"""Object-storage gateway: the daemon's HTTP front for bucket/object ops.

Role parity: reference client/daemon/objectstorage/objectstorage.go:138-724
— a gin HTTP server on the daemon: GET/HEAD/PUT/DELETE object + create
bucket; GETs ride the P2P pipeline (shared swarm across daemons that
front the same backend), PUTs fan out by replication mode. The backend is
any pkg-style ObjectStorage driver (manager.objectstorage — filesystem in
this environment, S3-shaped interface).

API (dfstore speaks this):
  PUT    /buckets/<bucket>                       create bucket
  GET    /buckets/<bucket>/objects/<key>         fetch (via P2P)
  HEAD   /buckets/<bucket>/objects/<key>         existence/length
  PUT    /buckets/<bucket>/objects/<key>?mode=N  store (0=backend only,
                                                 1=also import locally as
                                                 a completed task: the
                                                 writing daemon becomes
                                                 the object's first seed)
  DELETE /buckets/<bucket>/objects/<key>         delete from backend
  GET    /buckets/<bucket>/objects?prefix=       list keys (JSON)
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dragonfly2_tpu.manager.objectstorage import ObjectStorage
from dragonfly2_tpu.utils import dflog, flight, flows, profiling

logger = dflog.get("client.objectstorage")

# dfprof phase: one gateway op (route + backend/transport leg)
PH_OBJECT_OP = profiling.phase_type("daemon.object_op")

# provenance anomaly: an object GET that should have ridden P2P but
# fell back to a direct fetch — carries the swallowed cause
EV_OBJECT_FALLBACK = flight.event_type("daemon.object_fallback")

# replication modes (reference objectstorage.go WriteBack / AsyncWriteBack)
MODE_BACKEND_ONLY = 0
MODE_IMPORT_LOCAL = 1

# content-digest sidecar suffix: the digest participates in the P2P task
# id, so an overwritten object gets a fresh task identity instead of the
# swarm serving stale cached bytes forever
DIGEST_SUFFIX = ".df-digest"


def _slice_stream(chunks, offset: int, length: int):
    """Skip ``offset`` bytes of a chunk iterator, then yield exactly
    ``length`` — range semantics over a whole-object stream without
    buffering it."""
    remaining_skip, remaining = offset, length
    for chunk in chunks:
        if remaining_skip:
            if len(chunk) <= remaining_skip:
                remaining_skip -= len(chunk)
                continue
            chunk = chunk[remaining_skip:]
            remaining_skip = 0
        if remaining <= 0:
            break
        if len(chunk) > remaining:
            chunk = chunk[:remaining]
        remaining -= len(chunk)
        yield chunk


def _sha256(data: bytes) -> str:
    return "sha256:" + hashlib.sha256(data).hexdigest()


class ObjectStorageGateway:
    """HTTP gateway bound to a daemon: backend + P2P transport."""

    def __init__(
        self,
        backend: ObjectStorage,
        transport=None,  # client.transport.P2PTransport; None = direct reads
        importer=None,  # callable(url, data) registering a local seed copy
        url_for=None,  # callable(bucket, key) -> origin URL for P2P fetch
        address: str = "127.0.0.1",
        port: int = 0,
    ):
        self.backend = backend
        self.transport = transport
        self.plane = "object"
        if transport is not None:
            # the gateway IS the object plane front: stamp its transport
            # so piece-level flow attribution agrees with the gateway's
            # own request-level accounting
            transport.plane = self.plane
        self.importer = importer
        self.url_for = url_for
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                logger.debug("gateway: " + fmt, *args)

            def do_PUT(self):
                outer._route(self, "PUT")

            def do_GET(self):
                outer._route(self, "GET")

            def do_HEAD(self):
                outer._route(self, "HEAD")

            def do_DELETE(self):
                outer._route(self, "DELETE")

        self._server = ThreadingHTTPServer((address, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="os-gateway", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # ------------------------------------------------------------------
    def _route(self, h: BaseHTTPRequestHandler, method: str) -> None:
        try:
            with PH_OBJECT_OP:
                return self._route_inner(h, method)
        except FileNotFoundError:
            h.send_error(404, "object not found")
        except Exception as e:
            logger.exception("gateway %s %s failed", method, h.path)
            try:
                h.send_error(500, str(e))
            except Exception:
                pass

    def _route_inner(self, h: BaseHTTPRequestHandler, method: str) -> None:
        parts = urllib.parse.urlsplit(h.path)
        segs = [s for s in parts.path.split("/") if s]
        query = dict(urllib.parse.parse_qsl(parts.query))
        if len(segs) >= 1 and segs[0] == "buckets":
            if len(segs) == 2 and method == "PUT":
                return self._create_bucket(h, segs[1])
            if len(segs) == 3 and segs[2] == "objects" and method == "GET":
                return self._list_objects(h, segs[1], query.get("prefix", ""))
            if len(segs) >= 4 and segs[2] == "objects":
                key = "/".join(segs[3:])
                if method == "PUT":
                    return self._put_object(h, segs[1], key, query)
                if method == "GET":
                    return self._get_object(h, segs[1], key)
                if method == "HEAD":
                    return self._head_object(h, segs[1], key)
                if method == "DELETE":
                    return self._delete_object(h, segs[1], key)
        h.send_error(404, "no such route")

    # ------------------------------------------------------------------
    def _create_bucket(self, h, bucket: str) -> None:
        self.backend.create_bucket(bucket)
        h.send_response(201)
        h.send_header("Content-Length", "0")
        h.end_headers()

    def _put_object(self, h, bucket: str, key: str, query: dict) -> None:
        if "chunked" in h.headers.get("Transfer-Encoding", "").lower():
            # reading a chunked body as length-0 would silently store an
            # empty object with a 201
            h.send_error(411, "Content-Length required (chunked not supported)")
            return
        length = int(h.headers.get("Content-Length", 0))
        data = h.rfile.read(length)
        digest = _sha256(data)
        self.backend.put_object(bucket, key, data)
        self.backend.put_object(bucket, key + DIGEST_SUFFIX, digest.encode())
        mode = int(query.get("mode", MODE_BACKEND_ONLY))
        if mode == MODE_IMPORT_LOCAL and self.importer is not None and self.url_for:
            # writing daemon becomes the first P2P seed of the object
            try:
                self.importer(self.url_for(bucket, key), data, digest)
            except Exception:
                logger.exception("local import of %s/%s failed", bucket, key)
        h.send_response(201)
        h.send_header("Content-Length", "0")
        h.end_headers()

    def _digest_of(self, bucket: str, key: str) -> str:
        try:
            return self.backend.get_object(bucket, key + DIGEST_SUFFIX).decode()
        except FileNotFoundError:
            return ""

    def _get_object(self, h, bucket: str, key: str) -> None:
        from dragonfly2_tpu.client.pieces import resolve_byte_range

        if not self.backend.head_object(bucket, key):
            raise FileNotFoundError(key)
        # resolve the client Range ONCE against the known total (shared
        # by every route below); RFC 7233: an unparsable Range header is
        # IGNORED (whole object, 200), an unsatisfiable one is 416
        rng = h.headers.get("Range", "")
        total = self.backend.stat_object(bucket, key)
        rr = None
        if rng:
            try:
                rr = resolve_byte_range(rng, total)
            except ValueError:
                rng = ""
            else:
                if rr is None:
                    # RFC 7233: the 416 carries the total so resume
                    # logic can recover the object size
                    h.send_response(416)
                    h.send_header("Content-Range", f"bytes */{total}")
                    h.send_header("Content-Length", "0")
                    h.end_headers()
                    return
        t0 = time.monotonic()
        if self.transport is not None and self.url_for is not None:
            # client Range rides through the transport, which serves it
            # as a P2P ranged task or goes direct. A whole-object digest
            # pin can't gate a slice, so ranged GETs drop it (the
            # transport would refuse the combination).
            # the digest ALWAYS rides along: for unranged GETs it pins
            # content; for ranged ones the transport converts it into
            # task-identity salt so overwrites never serve stale slices
            result = self.transport.round_trip(
                self.url_for(bucket, key),
                headers={"Range": rng} if rng else None,
                digest=self._digest_of(bucket, key),
            )
            if result.fallback_cause:
                # the P2P leg failed and the transport went direct —
                # name the cause instead of swallowing it
                logger.warning(
                    "object get %s/%s skipped the swarm: %s",
                    bucket, key, result.fallback_cause,
                )
                EV_OBJECT_FALLBACK(
                    bucket=bucket, key=key, cause=result.fallback_cause
                )
            if result.status == 404:
                raise FileNotFoundError(key)
            if result.status not in (200, 206):
                # upstream error stays an error — never relabeled 200,
                # never sliced into a fake successful partial read
                h.send_error(502, f"upstream returned {result.status}")
                return
            length = result.content_length
            body = result.body
            status = result.status
            content_range = result.headers.get("Content-Range", "")
            if rr and status == 200:
                # the transport couldn't serve the range itself (suffix
                # form, direct file fetch) and returned the whole object
                # — slice it HERE so S3 semantics hold on every route
                off, end = rr
                body = _slice_stream(result.body, off, end - off + 1)
                length = end - off + 1
                status = 206
                content_range = f"bytes {off}-{end}/{total}"
            elif status == 206 and content_range.endswith("/*"):
                # the transport doesn't know the total; the gateway does
                # (size probes like 'bytes=0-0' read it from here)
                content_range = content_range[:-1] + str(total)
            if length < 0:
                # unknown-length stream on keep-alive HTTP/1.1 would
                # hang the client waiting for EOF
                length = (rr[1] - rr[0] + 1) if rr else total
            h.send_response(status)
            h.send_header("Content-Length", str(length))
            if content_range:
                h.send_header("Content-Range", content_range)
            if result.headers.get("Content-Type"):
                h.send_header("Content-Type", result.headers["Content-Type"])
            h.send_header("Accept-Ranges", "bytes")
            h.send_header("X-Dragonfly-Via-P2P", "1" if result.via_p2p else "0")
            if result.task_id:
                h.send_header("X-Dragonfly-Task-Id", result.task_id)
            h.end_headers()
            # stream — multi-GB objects must not be buffered per request
            served = 0
            for chunk in body:
                h.wfile.write(chunk)
                served += len(chunk)
            # flow ledger: P2P rides were attributed at the piece write;
            # local reuse and direct responses are acquired here
            if result.via_p2p and not result.local_cache:
                provenance = "parent"
            elif result.local_cache:
                provenance = "local_cache"
            else:
                provenance = "origin"
            if served:
                flows.serve(self.plane, served)
                if provenance != "parent":
                    flows.account(self.plane, provenance, served)
            flows.request(self.plane, provenance, latency_s=time.monotonic() - t0)
            return
        body = self.backend.get_object(bucket, key)
        if rr:
            off, end = rr
            h.send_response(206)
            h.send_header("Content-Range", f"bytes {off}-{end}/{total}")
            body = body[off : end + 1]
        else:
            h.send_response(200)
        h.send_header("Content-Length", str(len(body)))
        h.send_header("Accept-Ranges", "bytes")
        h.send_header("X-Dragonfly-Via-P2P", "0")
        h.end_headers()
        h.wfile.write(body)
        if body:
            # no transport: bytes come straight off the backend (origin)
            flows.serve(self.plane, len(body))
            flows.account(self.plane, "origin", len(body))
        flows.request(self.plane, "origin", latency_s=time.monotonic() - t0)

    def _head_object(self, h, bucket: str, key: str) -> None:
        if not self.backend.head_object(bucket, key):
            h.send_error(404, "object not found")
            return
        h.send_response(200)
        h.send_header("Content-Length", str(self.backend.stat_object(bucket, key)))
        h.send_header("Accept-Ranges", "bytes")  # SDK transfer managers probe this
        h.end_headers()

    def _delete_object(self, h, bucket: str, key: str) -> None:
        self.backend.delete_object(bucket, key)
        self.backend.delete_object(bucket, key + DIGEST_SUFFIX)
        h.send_response(204)
        h.send_header("Content-Length", "0")
        h.end_headers()

    def _list_objects(self, h, bucket: str, prefix: str) -> None:
        keys = [
            k
            for k in self.backend.list_objects(bucket, prefix)
            if not k.endswith(DIGEST_SUFFIX)
        ]
        body = json.dumps({"keys": keys}).encode()
        h.send_response(200)
        h.send_header("Content-Type", "application/json")
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)
