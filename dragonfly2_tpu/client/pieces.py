"""Piece geometry helpers.

Role parity: reference pkg/source piece sizing + client piece math —
pieces are fixed-length slices of the object; the last piece may be
short. Default 4 MiB, scaled up for very large objects so piece count
stays bounded (reference util.ComputePieceSize behavior).
"""

from __future__ import annotations

from dataclasses import dataclass

DEFAULT_PIECE_LENGTH = 4 * 1024 * 1024
MAX_PIECE_COUNT = 2048


def parse_byte_range(spec: str) -> tuple[int, int]:
    """UrlMeta.range → (offset, length); '' → (0, -1) = whole object.
    Accepts the RFC 7233 forms 'lo-hi' (inclusive), 'lo-' (to end), and
    the suffix form '-n' (last n bytes — returned as offset=-n,
    length=-1; resolved against the object length at fetch time), each
    with an optional 'bytes=' prefix (reference dfget --range passes
    HTTP-style specs)."""
    spec = (spec or "").strip()
    if not spec:
        return 0, -1
    spec = spec.removeprefix("bytes=")
    lo, sep, hi = spec.partition("-")
    lo, hi = lo.strip(), hi.strip()
    if not sep:
        raise ValueError(f"malformed byte range {spec!r}")
    if not lo:
        if not hi.isdigit() or int(hi) == 0:
            raise ValueError(f"malformed suffix range {spec!r}")
        return -int(hi), -1
    if not lo.isdigit() or (hi and not hi.isdigit()):
        raise ValueError(f"malformed byte range {spec!r}")
    start = int(lo)
    if not hi:
        return start, -1
    end = int(hi)
    if end < start:
        raise ValueError(f"range end before start: {spec!r}")
    return start, end - start + 1


def resolve_byte_range(spec: str, total: int) -> "tuple[int, int] | None":
    """Resolve a range spec against a known object size → inclusive
    (offset, end), or None when unsatisfiable (HTTP 416: start past the
    end, or an empty object). Raises ValueError on malformed specs —
    RFC 7233 callers IGNORE those (serve the whole object), they don't
    error."""
    off, ln = parse_byte_range(spec)
    if off < 0:  # suffix: last n bytes, clamped to the object
        off = max(0, total + off)
    if off >= total:
        return None
    end = total - 1 if ln < 0 else min(off + ln - 1, total - 1)
    return off, end


def normalize_byte_range(spec: str) -> str:
    """Canonical form for task identity: '0-1023', 'bytes=0-1023', and
    ' 0-1023' are the SAME slice and must hash to the same task id (the
    cache would otherwise split per spelling); '0-'/'bytes=0-' IS the
    whole object and canonicalizes to '' (one task, not a duplicate
    cache entry). Malformed specs raise here — at task registration,
    not deep in back-to-source."""
    off, ln = parse_byte_range(spec)
    if off == 0 and ln < 0:
        return ""  # whole object — identical to the unranged task
    if off < 0:
        return f"-{-off}"  # suffix form
    return f"{off}-{off + ln - 1}" if ln >= 0 else f"{off}-"


def compute_piece_length(content_length: int) -> int:
    """Default piece size, doubled until piece count ≤ MAX_PIECE_COUNT."""
    if content_length <= 0:
        return DEFAULT_PIECE_LENGTH
    pl = DEFAULT_PIECE_LENGTH
    while content_length / pl > MAX_PIECE_COUNT:
        pl *= 2
    return pl


def piece_count(content_length: int, piece_length: int) -> int:
    if content_length <= 0:
        return 0
    return (content_length + piece_length - 1) // piece_length


@dataclass(frozen=True)
class PieceRange:
    number: int
    offset: int
    length: int


def piece_ranges(content_length: int, piece_length: int) -> list[PieceRange]:
    out = []
    for n in range(piece_count(content_length, piece_length)):
        off = n * piece_length
        out.append(
            PieceRange(number=n, offset=off, length=min(piece_length, content_length - off))
        )
    return out
