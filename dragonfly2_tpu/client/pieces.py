"""Piece geometry helpers.

Role parity: reference pkg/source piece sizing + client piece math —
pieces are fixed-length slices of the object; the last piece may be
short. Default 4 MiB, scaled up for very large objects so piece count
stays bounded (reference util.ComputePieceSize behavior).
"""

from __future__ import annotations

from dataclasses import dataclass

DEFAULT_PIECE_LENGTH = 4 * 1024 * 1024
MAX_PIECE_COUNT = 2048


def compute_piece_length(content_length: int) -> int:
    """Default piece size, doubled until piece count ≤ MAX_PIECE_COUNT."""
    if content_length <= 0:
        return DEFAULT_PIECE_LENGTH
    pl = DEFAULT_PIECE_LENGTH
    while content_length / pl > MAX_PIECE_COUNT:
        pl *= 2
    return pl


def piece_count(content_length: int, piece_length: int) -> int:
    if content_length <= 0:
        return 0
    return (content_length + piece_length - 1) // piece_length


@dataclass(frozen=True)
class PieceRange:
    number: int
    offset: int
    length: int


def piece_ranges(content_length: int, piece_length: int) -> list[PieceRange]:
    out = []
    for n in range(piece_count(content_length, piece_length)):
        off = n * piece_length
        out.append(
            PieceRange(number=n, offset=off, length=min(piece_length, content_length - off))
        )
    return out
