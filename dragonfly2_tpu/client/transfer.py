"""Readiness-based transfer plane: the shared event loop and the bounded
keep-alive connection pool the daemon's piece paths ride
(docs/data-plane.md).

Two consumers:

- :class:`TransferPool` — the CHILD side. ``downloader.download_piece``
  submits piece fetches here; the pool multiplexes them over a bounded
  set of persistent HTTP/1.1 connections (one keep-alive socket per
  parent, reused across pieces) driven by one selector thread, instead
  of urllib opening and tearing down a TCP connection per piece. Callers
  stay synchronous (they block on a per-job event), so the conductor's
  piece/retry/back-to-source semantics are untouched — only the I/O
  under them is multiplexed.
- ``uploader.UploadServer`` — the PARENT side builds its sendfile serve
  loop on the same :class:`EventLoop` primitive.

``DF_TRANSFER_LOOP=0`` disables the pool; the downloader then falls back
to per-request urllib exactly as before.
"""

# dfanalyze: hot — every piece transfer crosses this loop

from __future__ import annotations

import heapq
import os
import selectors
import socket
import threading
import time
from collections import deque

from dragonfly2_tpu.utils import dflog

logger = dflog.get("client.transfer")

_RECV_CHUNK = 256 * 1024
_MAX_HEADER = 64 * 1024


class TransferError(Exception):
    """Transport-level fetch failure (connect/timeout/protocol)."""


class EventLoop:
    """Minimal selectors-based reactor: register(fileobj, mask, cb),
    timers, and thread-safe ``call_soon``. Handlers run on the single
    loop thread; they must never block."""

    def __init__(self, name: str):
        self.name = name
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, self._drain_wake)
        self._pending: deque = deque()
        self._timers: list = []  # heap of (when, seq, callback)
        self._seq = 0
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None

    # -- control ------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"daemon.{self.name}", daemon=True
        )
        self._thread.start()

    def stop(self, on_stop=None) -> None:
        """Idempotent; ``on_stop`` (loop thread) runs before exit so
        owners can close their sockets on the thread that owns them."""
        if self._stopped.is_set():
            return
        if on_stop is not None:
            self.call_soon(on_stop)
        self._stopped.set()
        self.wake()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)

    def wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except (BlockingIOError, OSError):
            pass  # already pending / closing — either way the loop runs

    def _drain_wake(self, mask) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def call_soon(self, fn) -> None:
        with self._lock:
            self._pending.append(fn)
        self.wake()

    def call_at(self, when: float, fn) -> None:
        """Loop-thread only (timers are serviced between select rounds)."""
        self._seq += 1
        heapq.heappush(self._timers, (when, self._seq, fn))

    # -- selector facade (loop thread only) ---------------------------
    def register(self, fileobj, mask, cb) -> None:
        self._sel.register(fileobj, mask, cb)

    def modify(self, fileobj, mask, cb) -> None:
        self._sel.modify(fileobj, mask, cb)

    def unregister(self, fileobj) -> None:
        try:
            self._sel.unregister(fileobj)
        except (KeyError, ValueError):
            pass

    # -- core ---------------------------------------------------------
    def _run(self) -> None:
        while not self._stopped.is_set():
            now = time.monotonic()
            timeout = None
            while self._timers and self._timers[0][0] <= now:
                _, _, fn = heapq.heappop(self._timers)
                self._safe(fn)
            if self._timers:
                timeout = max(0.0, self._timers[0][0] - time.monotonic())
            while True:
                with self._lock:
                    if not self._pending:
                        break
                    fn = self._pending.popleft()
                self._safe(fn)
                timeout = 0.0  # a callback may have armed timers/events
            try:
                events = self._sel.select(timeout)
            except OSError:
                continue  # fd closed under us during stop
            for key, mask in events:
                self._safe(key.data, mask)
        # drain callbacks queued by stop() (owner teardown closes its
        # sockets HERE, on the thread that owns them) before the
        # selector goes away
        while True:
            with self._lock:
                if not self._pending:
                    break
                fn = self._pending.popleft()
            self._safe(fn)
        try:
            self._sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    def _safe(self, fn, *args) -> None:
        try:
            fn(*args)
        except Exception:
            logger.exception("transfer loop %s: handler failed", self.name)


# ---------------------------------------------------------------------------
# child-side fetch pool
# ---------------------------------------------------------------------------


class _Job:
    __slots__ = (
        "addr", "target", "deadline", "event", "status", "headers", "body",
        "error", "retried",
    )

    def __init__(self, addr: str, target: str, deadline: float):
        self.addr = addr
        self.target = target
        self.deadline = deadline
        self.event = threading.Event()
        self.status = 0
        self.headers: dict[str, str] = {}
        self.body = b""
        self.error: str | None = None
        self.retried = False

    def finish(self) -> None:
        self.event.set()

    def fail(self, msg: str) -> None:
        self.error = msg
        self.event.set()


class _PoolConn:
    """One pooled HTTP/1.1 connection to a parent's upload server."""

    __slots__ = ("sock", "addr", "job", "out", "buf", "body", "body_len",
                 "body_got", "connected", "fresh")

    def __init__(self, sock: socket.socket, addr: str):
        self.sock = sock
        self.addr = addr
        self.job: _Job | None = None
        self.out = b""
        self.buf = b""  # response header accumulation
        self.body: bytearray | None = None
        self.body_len = 0
        self.body_got = 0
        self.connected = False
        self.fresh = True  # first request on this socket

    def reset_for(self, job: _Job) -> None:
        self.job = job
        req = (
            f"GET {job.target} HTTP/1.1\r\n"
            f"Host: {self.addr}\r\n"
            "\r\n"
        )
        self.out = req.encode("ascii")
        self.buf = b""
        self.body = None
        self.body_len = 0
        self.body_got = 0


class TransferPool:
    """Bounded keep-alive connection pool for piece fetches. Thread-safe
    ``fetch`` from any thread; all socket work happens on the loop."""

    def __init__(
        self,
        loop: EventLoop | None = None,
        max_connections: int = 0,
        connect_timeout: float = 5.0,
    ):
        self.loop = loop or EventLoop("transfer")
        self._own_loop = loop is None
        self.max_connections = max_connections or int(
            os.environ.get("DF_TRANSFER_POOL", "64")
        )
        self.connect_timeout = connect_timeout
        # loop-thread state
        self._idle: dict[str, list[_PoolConn]] = {}
        self._active: set[_PoolConn] = set()
        self._queue: deque[_Job] = deque()
        self._watchdog_armed = False
        self._started = False
        self._start_lock = threading.Lock()

    # -- public -------------------------------------------------------
    def fetch(
        self, addr: str, target: str, timeout: float = 30.0
    ) -> tuple[int, dict[str, str], bytes]:
        """Blocking GET ``http://addr``+``target`` → (status, lowercase
        headers, body). Raises :class:`TransferError` on wire failure."""
        self._ensure_started()
        job = _Job(addr, target, time.monotonic() + timeout)
        self.loop.call_soon(lambda: self._admit(job))
        if not job.event.wait(timeout + 5.0):
            job.error = job.error or f"fetch {addr}{target}: pool watchdog timeout"
        if job.error is not None:
            raise TransferError(job.error)
        return job.status, job.headers, job.body

    def release_idle(self, addrs) -> None:
        """Drop idle keep-alive connections to ``addrs`` — called when a
        task finishes so a 10k-parent swarm doesn't pin fds forever."""
        if not self._started:
            return
        addrs = set(addrs)

        def _drop():
            for addr in addrs:
                for conn in self._idle.pop(addr, []):
                    self._close_conn(conn)

        self.loop.call_soon(_drop)

    def stop(self) -> None:
        if self._own_loop:
            self.loop.stop(on_stop=self._close_all)

    def _close_all(self) -> None:
        for conns in self._idle.values():
            for conn in conns:
                self._close_conn(conn)
        self._idle.clear()
        for conn in list(self._active):
            if conn.job is not None:
                conn.job.fail("transfer pool stopped")
            self._close_conn(conn)

    # -- loop-thread internals ---------------------------------------
    def _ensure_started(self) -> None:
        if self._started:
            return
        with self._start_lock:
            if not self._started:
                self.loop.start()
                self._started = True

    def _admit(self, job: _Job) -> None:
        self._queue.append(job)
        if not self._watchdog_armed:
            self._watchdog_armed = True
            self.loop.call_at(time.monotonic() + 0.5, self._watchdog)
        self._dispatch()

    def _watchdog(self) -> None:
        """Expire jobs (queued or in flight) past their deadline."""
        now = time.monotonic()
        for job in [j for j in self._queue if j.deadline <= now]:
            self._queue.remove(job)
            job.fail(f"fetch {job.addr}{job.target}: timed out in queue")
        for conn in [c for c in self._active if c.job and c.job.deadline <= now]:
            job = conn.job
            self._abort_conn(conn, f"fetch {job.addr}{job.target}: timed out")
        if self._queue or self._active:
            self.loop.call_at(now + 0.5, self._watchdog)
        else:
            self._watchdog_armed = False

    def _dispatch(self) -> None:
        while self._queue:
            job = self._queue[0]
            idle = self._idle.get(job.addr)
            if idle:
                conn = idle.pop()
                if not idle:
                    del self._idle[job.addr]
                self._queue.popleft()
                self._attach(conn, job)
                continue
            if len(self._active) + sum(len(v) for v in self._idle.values()) \
                    >= self.max_connections:
                # at the bound: evict an idle conn to any OTHER addr
                victim_addr = next(iter(self._idle), None)
                if victim_addr is None:
                    return  # every socket busy — wait for a completion
                conn = self._idle[victim_addr].pop()
                if not self._idle[victim_addr]:
                    del self._idle[victim_addr]
                self._close_conn(conn)
                continue
            self._queue.popleft()
            self._connect(job)

    def _connect(self, job: _Job) -> None:
        try:
            host, port = job.addr.rsplit(":", 1)
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                sock.connect((host, int(port)))
            except BlockingIOError:
                pass
        except OSError as e:
            job.fail(f"connect {job.addr}: {e}")
            return
        conn = _PoolConn(sock, job.addr)
        conn.reset_for(job)
        self._active.add(conn)
        self.loop.register(
            sock, selectors.EVENT_WRITE, lambda mask, c=conn: self._on_event(c, mask)
        )
        self.loop.call_at(
            time.monotonic() + self.connect_timeout,
            lambda c=conn: self._connect_deadline(c),
        )

    def _connect_deadline(self, conn: _PoolConn) -> None:
        if conn in self._active and not conn.connected:
            self._abort_conn(conn, f"connect {conn.addr}: timed out")

    def _attach(self, conn: _PoolConn, job: _Job) -> None:
        conn.fresh = False
        conn.connected = True
        conn.reset_for(job)
        self._active.add(conn)
        self.loop.register(
            conn.sock, selectors.EVENT_WRITE,
            lambda mask, c=conn: self._on_event(c, mask),
        )

    def _close_conn(self, conn: _PoolConn) -> None:
        self.loop.unregister(conn.sock)
        self._active.discard(conn)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _abort_conn(self, conn: _PoolConn, msg: str) -> None:
        job = conn.job
        conn.job = None
        self._close_conn(conn)
        if job is not None:
            job.fail(msg)
        self._dispatch()

    def _on_event(self, conn: _PoolConn, mask: int) -> None:
        if conn not in self._active:
            return
        job = conn.job
        try:
            if conn.out:
                if not conn.connected:
                    err = conn.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
                    if err:
                        raise OSError(err, os.strerror(err))
                    conn.connected = True
                sent = conn.sock.send(conn.out)
                conn.out = conn.out[sent:]
                if not conn.out:
                    self.loop.modify(
                        conn.sock, selectors.EVENT_READ,
                        lambda m, c=conn: self._on_event(c, m),
                    )
                return
            self._on_readable(conn)
        except (BlockingIOError, InterruptedError):
            return
        except OSError as e:
            self._wire_failure(conn, f"{job.addr if job else conn.addr}: {e}")

    def _on_readable(self, conn: _PoolConn) -> None:
        job = conn.job
        if conn.body is not None:
            view = memoryview(conn.body)[conn.body_got:]
            n = conn.sock.recv_into(view, len(view))
            if n == 0:
                self._wire_failure(conn, f"{conn.addr}: connection closed mid-body")
                return
            conn.body_got += n
            if conn.body_got >= conn.body_len:
                self._complete(conn)
            return
        data = conn.sock.recv(_RECV_CHUNK)
        if not data:
            self._wire_failure(conn, f"{conn.addr}: connection closed")
            return
        conn.buf += data
        head_end = conn.buf.find(b"\r\n\r\n")
        if head_end < 0:
            # one recv can deliver headers AND a body chunk — only an
            # actually-unterminated header block is oversized
            if len(conn.buf) > _MAX_HEADER:
                self._abort_conn(conn, f"{conn.addr}: response headers too large")
            return
        head, rest = conn.buf[:head_end], conn.buf[head_end + 4:]
        lines = head.split(b"\r\n")
        try:
            parts = lines[0].split(None, 2)
            status = int(parts[1])
        except (IndexError, ValueError):
            self._abort_conn(conn, f"{conn.addr}: malformed status line")
            return
        headers: dict[str, str] = {}
        for line in lines[1:]:
            k, _, v = line.partition(b":")
            headers[k.strip().decode("latin1").lower()] = v.strip().decode("latin1")
        if job is None:
            self._close_conn(conn)
            return
        job.status = status
        job.headers = headers
        try:
            body_len = int(headers.get("content-length", "0") or "0")
        except ValueError:
            self._abort_conn(conn, f"{conn.addr}: bad content-length")
            return
        conn.body = bytearray(body_len)
        conn.body_len = body_len
        if rest:
            take = min(len(rest), body_len)
            conn.body[:take] = rest[:take]
            conn.body_got = take
        if conn.body_got >= conn.body_len:
            self._complete(conn)

    def _complete(self, conn: _PoolConn) -> None:
        job = conn.job
        keep = job.headers.get("connection", "").lower() != "close"
        conn.job = None
        job.body = bytes(conn.body)
        conn.body = None
        self.loop.unregister(conn.sock)
        self._active.discard(conn)
        if keep:
            self._idle.setdefault(conn.addr, []).append(conn)
        else:
            try:
                conn.sock.close()
            except OSError:
                pass
        job.finish()
        self._dispatch()

    def _wire_failure(self, conn: _PoolConn, msg: str) -> None:
        """A reused keep-alive socket can die between requests (the
        parent closed it while idle — the classic stale-connection
        race). If nothing of the response arrived yet, retry ONCE on a
        fresh connection before surfacing the error."""
        job = conn.job
        stale = (
            job is not None
            and not conn.fresh
            and not job.retried
            and conn.buf == b""
            and conn.body is None
        )
        conn.job = None
        self._close_conn(conn)
        if job is None:
            return
        if stale:
            job.retried = True
            self._queue.appendleft(job)
            self._dispatch()
            return
        job.fail(msg)
        self._dispatch()


# ---------------------------------------------------------------------------
# process-wide default pool
# ---------------------------------------------------------------------------

_default_pool: TransferPool | None = None
_default_lock = threading.Lock()


def enabled() -> bool:
    return os.environ.get("DF_TRANSFER_LOOP", "1") != "0"


def default_pool() -> TransferPool | None:
    """The process-wide pool (None when DF_TRANSFER_LOOP=0)."""
    if not enabled():
        return None
    global _default_pool
    if _default_pool is None:
        with _default_lock:
            if _default_pool is None:
                _default_pool = TransferPool()
    return _default_pool
