"""Dfdaemon gRPC service: the daemon's RPC surface.

Role parity: reference client/daemon/rpcserver/rpcserver.go:129-1123 —
``Download`` server-stream for dfget (:379-401), ``GetPieceTasks``
(:151), ``SyncPieceTasks`` bidi (:268), ``StatTask`` / ``ImportTask`` /
``ExportTask`` / ``DeleteTask`` (dfcache ops).
"""

from __future__ import annotations

import os

import grpc

from dragonfly2_tpu.rpc import gen  # noqa: F401
import common_pb2  # noqa: E402
import dfdaemon_pb2  # noqa: E402

from dragonfly2_tpu.client.peertask import FileTaskRequest, TaskManager
from dragonfly2_tpu.client.storage import StorageManager
from dragonfly2_tpu.utils import dflog, flows

logger = dflog.get("client.rpc")

from dragonfly2_tpu.rpc.glue import DFDAEMON_SERVICE as SERVICE_NAME


class DfdaemonService:
    def __init__(
        self,
        task_manager: TaskManager,
        storage: StorageManager,
        upload_addr: str,
    ):
        self.tasks = task_manager
        self.storage = storage
        self.upload_addr = upload_addr

    # ------------------------------------------------------------------
    def Download(self, request, context):
        """Server-stream of progress results for dfget
        (reference rpcserver.go:379-401)."""
        req = FileTaskRequest(
            url=request.url,
            output=request.output,
            # UrlMeta.header (dfget --header origin auth) is applied
            # centrally in TaskManager.start_file_task
            url_meta=request.url_meta,
            disable_back_source=request.disable_back_source,
            need_back_to_source=request.need_back_to_source,
        )
        if request.need_back_to_source:
            # the preheat plane is the only caller that forces
            # back-to-source over this RPC (scheduler seed trigger) —
            # mark the task so the ledger attributes its origin bytes
            # to "preheat" seeding, not demand
            flows.mark_preheat(
                self.tasks.task_id_for(request.url, request.url_meta)
            )
        task_id, peer_id, conductor = self.tasks.start_file_task(req)
        if conductor is None:  # reuse path — start_file_task already stored
            ts = self.storage.load(task_id)
            if ts.meta.content_length > 0:
                flows.serve(flows.task_plane(task_id), ts.meta.content_length)
                flows.account(
                    flows.task_plane(task_id),
                    "local_cache",
                    ts.meta.content_length,
                )
            yield dfdaemon_pb2.DownloadResult(
                task_id=task_id,
                peer_id=peer_id,
                done=True,
                completed_length=ts.meta.content_length,
                content_length=ts.meta.content_length,
                output=request.output,
            )
            return

        sub = conductor.subscribe()
        while True:
            p = sub.get()
            if p.error:
                context.abort(grpc.StatusCode.INTERNAL, p.error)
            if p.done and request.output:
                # write the output before the terminal result goes out —
                # the client treats done=True as "bytes are on disk"
                self.storage.load(task_id).store(request.output)
            yield dfdaemon_pb2.DownloadResult(
                task_id=task_id,
                peer_id=peer_id,
                done=p.done,
                completed_length=p.completed_length,
                content_length=p.content_length,
                output=request.output,
            )
            if p.done:
                if p.completed_length > 0:
                    flows.serve(flows.task_plane(task_id), p.completed_length)
                return

    # ------------------------------------------------------------------
    def GetPieceTasks(self, request, context):
        return self._piece_packet(request)

    def SyncPieceTasks(self, request_iterator, context):
        """Bidi metadata sync between daemons (reference
        peertask_piecetask_synchronizer.go): each request is answered
        with the current piece inventory."""
        for req in request_iterator:
            yield self._piece_packet(req)

    def _piece_packet(self, request) -> dfdaemon_pb2.PiecePacket:
        ts = self.storage.load(request.task_id)
        if ts is None:
            return dfdaemon_pb2.PiecePacket(
                task_id=request.task_id, dst_addr=self.upload_addr
            )
        start = request.start_num or 0
        # limit=0 = whole inventory (the synchronizer streams the full
        # piece set; GetPieceTasks geometry probes pass limit=1)
        limit = request.limit if request.limit else None
        infos = []
        for n in sorted(ts.meta.pieces):
            if n < start or (limit is not None and len(infos) >= limit):
                continue
            pm = ts.meta.pieces[n]
            infos.append(
                common_pb2.PieceInfo(
                    number=pm.number,
                    offset=pm.offset,
                    length=pm.length,
                    digest=pm.digest,
                    traffic_type=pm.traffic_type,
                    cost_ns=pm.cost_ns,
                )
            )
        return dfdaemon_pb2.PiecePacket(
            task_id=request.task_id,
            dst_peer_id=ts.meta.peer_id,
            dst_addr=self.upload_addr,
            piece_infos=infos,
            content_length=ts.meta.content_length,
            total_piece_count=ts.meta.total_piece_count,
            piece_md5_sign_ok=True,
        )

    # ------------------------------------------------------------------
    def StatTask(self, request, context):
        task_id = self.tasks.task_id_for(request.url, request.url_meta)
        ts = self.storage.find_completed_task(task_id)
        if ts is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"task {task_id} not cached")
        return dfdaemon_pb2.Empty()

    def ImportTask(self, request, context):
        """Load a local file into the piece store as a completed task and
        announce it so the importer is discoverable as the first parent
        (dfcache import, reference rpcserver.go ImportTask)."""
        task_id = self.tasks.task_id_for(request.url, request.url_meta)
        if self.storage.find_completed_task(task_id) is not None:
            return dfdaemon_pb2.Empty()
        try:
            size = os.path.getsize(request.path)
        except OSError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        with open(request.path, "rb") as f:
            self.tasks.import_completed_task(
                task_id, request.url, f.read, size,
                task_type=common_pb2.TASK_TYPE_DFCACHE,
            )
        return dfdaemon_pb2.Empty()

    def ExportTask(self, request, context):
        task_id = self.tasks.task_id_for(request.url, request.url_meta)
        ts = self.storage.find_completed_task(task_id)
        if ts is None:
            if request.local_only:
                context.abort(grpc.StatusCode.NOT_FOUND, f"task {task_id} not cached")
            _, _, progress = self.tasks.wait_file_task(
                FileTaskRequest(url=request.url, output=request.output, url_meta=request.url_meta)
            )
            if not progress.done:
                context.abort(grpc.StatusCode.INTERNAL, progress.error)
            return dfdaemon_pb2.Empty()
        ts.store(request.output)
        return dfdaemon_pb2.Empty()

    def DeleteTask(self, request, context):
        task_id = self.tasks.task_id_for(request.url, request.url_meta)
        self.storage.delete_task(task_id)
        return dfdaemon_pb2.Empty()
