"""Client plane: peer daemon (piece store, P2P piece pipeline, upload
server, gRPC surface) + thin CLIs (dfget/dfcache/dfstore).

Role parity: reference client/ tree — daemon assembly
(client/daemon/daemon.go), conductor hot path
(client/daemon/peer/peertask_conductor.go), piece disk store
(client/daemon/storage/storage_manager.go), upload server
(client/daemon/upload/upload_manager.go), CLIs (client/dfget,
client/dfcache, client/dfstore).
"""
