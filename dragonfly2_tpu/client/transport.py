"""P2P transport: route eligible HTTP requests through the peer-task
pipeline with back-source fallback; client Range requests become ranged
tasks (206 + Content-Range) when their absolute start is known.

Role parity: reference client/daemon/transport/transport.go — an
http.RoundTripper that sends matching GET requests through P2P (stream
peer task) and everything else (or any P2P failure) straight to the
origin. The proxy (client/proxy.py) and the object-storage gateway ride
this same layer. Responses are streamed — bodies are chunk iterators,
never whole-blob buffers — and upstream status/headers are preserved so
206/404/Content-Type survive the proxy hop.
"""

from __future__ import annotations

import os
import re
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Iterator

from dragonfly2_tpu.rpc import gen  # noqa: F401
import common_pb2  # noqa: E402

from dragonfly2_tpu.client import metrics as M
from dragonfly2_tpu.client import source
from dragonfly2_tpu.client.peertask import FileTaskRequest, TaskManager
from dragonfly2_tpu.utils import dflog, flows

logger = dflog.get("client.transport")

_CHUNK = 256 * 1024


@dataclass
class ProxyRule:
    """One routing rule (reference proxy config Rules): requests whose URL
    matches ``regex`` are served via P2P unless ``direct``; ``use_https``
    upgrades the scheme before fetching."""

    regex: str
    direct: bool = False
    use_https: bool = False
    redirect: str = ""  # replacement host, e.g. a registry mirror

    def __post_init__(self):
        self._re = re.compile(self.regex)

    def matches(self, url: str) -> bool:
        return bool(self._re.search(url))

    def rewrite(self, url: str) -> str:
        if self.use_https:
            url = url.replace("http://", "https://", 1)
        if self.redirect:
            url = self._re.sub(self.redirect, url, count=1)
        return url


@dataclass
class TransportResult:
    status: int
    headers: dict  # upstream response headers (Content-Type etc.)
    body: Iterator[bytes]  # streamed chunks; empty iterator for HEAD
    content_length: int = -1
    via_p2p: bool = False
    task_id: str = ""
    # the task was already complete in local storage — bytes stream from
    # disk with no new acquisition (flow provenance "local_cache")
    local_cache: bool = False
    # non-empty when this is a direct response produced by a P2P
    # failure: the swallowed cause, surfaced for logs + flight events
    fallback_cause: str = ""

    def read_all(self) -> bytes:
        return b"".join(self.body)


class _Permit:
    """One in-flight P2P slot. Released explicitly when the response
    body is exhausted; the finalizer is the backstop for a caller that
    abandons the TransportResult without ever touching the body."""

    __slots__ = ("_sem", "_done")

    def __init__(self, sem: threading.BoundedSemaphore):
        self._sem = sem
        self._done = False

    def release(self) -> None:
        if not self._done:
            self._done = True
            self._sem.release()

    def __del__(self):  # pragma: no cover - GC backstop
        self.release()


def _releasing_iter(body: Iterator[bytes], permit: _Permit) -> Iterator[bytes]:
    try:
        yield from body
    finally:
        permit.release()


class P2PTransport:
    """Route a request: matching rule → peer task (P2P swarm + scheduler
    + back-to-source); no match or failure → direct origin fetch."""

    NO_RANGE_TTL = 60.0  # negative cache for range-refusing origins

    def __init__(
        self,
        task_manager: TaskManager,
        rules: list[ProxyRule] | None = None,
        default_tag: str = "",
        timeout: float = 300.0,
        max_inflight: int | None = None,
        plane: str = "file",
    ):
        self.tasks = task_manager
        self.rules = rules or []
        self.default_tag = default_tag
        self.timeout = timeout
        # flow-ledger traffic plane every task started through this
        # transport belongs to ("image" for the registry proxy,
        # "object" for the dfstore gateway)
        self.plane = plane
        self._no_range: dict[str, float] = {}
        self._no_range_lock = threading.Lock()
        # bound on concurrent P2P stream tasks: each one costs piece
        # workers + an announce stream, so an unbounded proxy fan-in
        # would amplify 10k client requests into 40k threads. At the
        # bound, new requests shed to a DIRECT fetch (graceful
        # degradation, counted) instead of queueing behind the swarm.
        if max_inflight is None:
            max_inflight = int(os.environ.get("DF_P2P_MAX_INFLIGHT", "512"))
        self._inflight = (
            threading.BoundedSemaphore(max_inflight) if max_inflight > 0 else None
        )

    def match_rule(self, url: str) -> ProxyRule | None:
        for rule in self.rules:
            if rule.matches(url):
                return rule
        return None

    def p2p_task_context(self, url: str) -> "tuple[str, str, str] | None":
        """(task_id, target_url, tag) of the swarm an unranged GET of
        ``url`` joins under this transport's routing — the identity a
        preheat must reproduce for its seeded content to be findable —
        or None when the request would go direct (no rule / direct
        rule), where no swarm exists to preheat into."""
        rule = self.match_rule(url)
        if rule is None or rule.direct or self.tasks is None:
            return None
        target = rule.rewrite(url)
        task_id = self.tasks.task_id_for(
            target, common_pb2.UrlMeta(tag=self.default_tag)
        )
        return task_id, target, self.default_tag

    def round_trip(
        self,
        url: str,
        headers: dict | None = None,
        head: bool = False,
        digest: str = "",
    ) -> TransportResult:
        rule = self.match_rule(url)
        if rule is None or rule.direct:
            target = url if rule is None else rule.rewrite(url)
            return self._direct(target, headers, head)
        target = rule.rewrite(url)
        if head:
            return self._direct(target, headers, head)
        # a client Range request rides P2P as a RANGED task (the slice
        # IS the task — client/pieces.py semantics), so resumed pulls
        # and ranged layer fetches still hit the swarm. Suffix ('-n')
        # and multi-range forms fall back to a direct fetch: their
        # absolute start is unknown without the total, which
        # Content-Range needs.
        range_spec = next(
            (v for k, v in (headers or {}).items() if k.lower() == "range"), ""
        )
        byte_range = ""
        tag_salt = ""
        if range_spec:
            from dragonfly2_tpu.client.pieces import normalize_byte_range

            # If-Range is a VALIDATOR the swarm cache cannot honor (task
            # identity is url+range, not etag) — serving a stale slice
            # would splice old bytes onto a newer partial file: direct,
            # as are suffix forms (absolute start unknown) and recently
            # range-refusing origins.
            if any(k.lower() == "if-range" for k in (headers or {})):
                return self._direct(target, headers, head)
            try:
                byte_range = normalize_byte_range(range_spec)
            except ValueError:
                return self._direct(target, headers, head)
            if byte_range.startswith("-"):
                return self._direct(target, headers, head)
            if byte_range == "":
                # 'bytes=0-' IS the whole object — plain unranged
                # semantics (incl. the digest pin); anything else would
                # mint a duplicate full-object cache entry
                range_spec = ""
            else:
                # a whole-object digest can't VERIFY a slice, but it must
                # still VERSION the cache — as task-identity salt — or an
                # object overwrite would serve stale slice bytes forever
                tag_salt, digest = digest, ""
                # read the verdict under the lock, fetch OUTSIDE it — a
                # direct origin fetch under _no_range_lock would serialize
                # every range-fallback request behind one slow origin
                with self._no_range_lock:
                    range_refused = (
                        self._no_range.get(target, 0.0) > time.monotonic()
                    )
                if range_refused:
                    return self._direct(target, headers, head)
        permit = None
        if self._inflight is not None:
            if not self._inflight.acquire(blocking=False):
                # at the in-flight bound: shed to a direct fetch —
                # bounded degradation beats queueing behind the swarm
                M.P2P_INFLIGHT_SHED_TOTAL.inc()
                logger.warning("p2p in-flight bound hit for %s; going direct", url)
                return self._direct(target, headers, head)
            permit = _Permit(self._inflight)
        try:
            return self._via_p2p(
                target, headers, digest, byte_range=byte_range,
                tag_salt=tag_salt, permit=permit,
            )
        except Exception as e:
            if permit is not None:
                permit.release()
            # P2P failure degrades to a direct fetch, never a user error
            # (reference transport.go back-source fallback)
            logger.warning("p2p round-trip for %s failed (%s); going direct", url, e)
            if byte_range and "support" in str(e) and "range" in str(e).lower():
                # negative-cache RANGE-REFUSING origins only (a transient
                # scheduler hiccup must not unroute a capable origin):
                # they'd pay register→schedule→fail on every request
                with self._no_range_lock:
                    now = time.monotonic()
                    if len(self._no_range) > 256:  # drop expired entries
                        self._no_range = {
                            u: t for u, t in self._no_range.items() if t > now
                        }
                    self._no_range[target] = now + self.NO_RANGE_TTL
            res = self._direct(target, headers, head)
            res.fallback_cause = f"{type(e).__name__}: {e}"
            return res

    # ------------------------------------------------------------------
    def _via_p2p(
        self,
        url: str,
        headers: dict | None,
        digest: str = "",
        byte_range: str = "",
        tag_salt: str = "",
        permit: "_Permit | None" = None,
    ) -> TransportResult:
        # the digest participates in the task id: rewritten content gets a
        # fresh task identity instead of serving stale cached bytes. For
        # ranged tasks the whole-object digest rides the TAG instead —
        # identity versioning without slice-verification semantics.
        fwd = {k: v for k, v in (headers or {}).items() if k.lower() != "range"}
        tag = f"{self.default_tag}|{tag_salt}" if tag_salt else self.default_tag
        url_meta = common_pb2.UrlMeta(tag=tag, digest=digest, range=byte_range)
        req = FileTaskRequest(url=url, url_meta=url_meta, headers=fwd)
        # stamp the task's traffic plane BEFORE the task starts so the
        # first pieces never race to the implicit "file" plane; the
        # completed-task check tells the caller the bytes come from
        # local storage with no new acquisition
        task_id = self.tasks.task_id_for(url, url_meta)
        flows.set_task_plane(task_id, self.plane)
        local_reuse = self.tasks.storage.find_completed_task(task_id) is not None
        # stream frontend: the response starts at first byte, not last —
        # a multi-GB layer pull begins flowing while later pieces are
        # still in flight (reference peertask_stream.go)
        task_id, _, content_length, origin_headers, body = self.tasks.start_stream_task(
            req, timeout=self.timeout
        )
        status = 200
        if byte_range:
            # the task's content IS the slice; HTTP semantics for the
            # ranged client are 206 + Content-Range (total unknown: '*')
            status = 206
            lo = int(byte_range.split("-", 1)[0])
            origin_headers = dict(origin_headers)
            origin_headers["Content-Range"] = (
                f"bytes {lo}-{lo + content_length - 1}/*"
                if content_length >= 0
                else f"bytes {lo}-/*"
            )
        return TransportResult(
            status=status,
            # replay persisted origin headers (Content-Type) so registry
            # clients get proper metadata on P2P-served responses
            headers=origin_headers,
            body=body if permit is None else _releasing_iter(body, permit),
            content_length=content_length,
            via_p2p=True,
            task_id=task_id,
            local_cache=local_reuse,
        )

    def _direct(self, url: str, headers: dict | None, head: bool) -> TransportResult:
        if url.startswith(("http://", "https://")):
            req = urllib.request.Request(
                url, headers=dict(headers or {}), method="HEAD" if head else "GET"
            )
            try:
                # honors DF_ORIGIN_CA for origins behind a private CA
                resp = source.open_url(req, self.timeout)
            except urllib.error.HTTPError as e:
                # 404 from a blob-existence probe is an answer, not a
                # proxy failure — pass the upstream status through
                body = e.read()
                return TransportResult(
                    status=e.code,
                    headers=dict(e.headers),
                    body=iter([body] if body else []),
                    content_length=len(body),
                )
            length = int(resp.headers.get("Content-Length", -1) or -1)

            def chunks() -> Iterator[bytes]:
                with resp:
                    while True:
                        chunk = resp.read(_CHUNK)
                        if not chunk:
                            return
                        yield chunk

            if head:
                resp.close()
            return TransportResult(
                status=resp.status,
                headers=dict(resp.headers),
                body=iter(()) if head else chunks(),
                content_length=length,
            )
        # non-HTTP schemes (file:// in tests, s3:// etc.) via source clients
        client = source.client_for(url)
        if head:
            meta = client.metadata(url, headers)
            return TransportResult(
                status=200, headers={}, body=iter(()), content_length=meta.content_length
            )
        return TransportResult(
            status=200, headers={}, body=iter(client.download(url, headers))
        )
