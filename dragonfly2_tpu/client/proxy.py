"""HTTP proxy with P2P redirection — registry/artifact acceleration.

Role parity: reference client/daemon/proxy/proxy.go:268-766 — an HTTP
proxy in front of container registries / artifact stores: plain-HTTP
requests matching the configured rules are converted into peer tasks
(P2P swarm with back-to-source), everything else passes through;
``CONNECT`` is tunneled raw (the reference can also MITM TLS with a
spoofed CA — here CONNECT bytes are relayed opaquely, so HTTPS rules
belong on the registry-mirror path instead). A registry mirror rewrites
request URLs onto the mirror remote before routing, which is how blob
and layer GETs become shared P2P downloads.
"""

from __future__ import annotations

import dataclasses
import select
import socket
import threading
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlsplit, urlunsplit

from dragonfly2_tpu.client.transport import P2PTransport, ProxyRule
from dragonfly2_tpu.client import metrics as M
from dragonfly2_tpu.utils import dflog

logger = dflog.get("client.proxy")

_HOP_HEADERS = {
    # accept-encoding is stripped so origins reply identity-encoded — the
    # proxy streams bodies as-is and must not re-label compressed bytes
    "accept-encoding",
    "connection",
    "proxy-connection",
    "keep-alive",
    "te",
    "trailers",
    "transfer-encoding",
    "upgrade",
    "host",
}


@dataclass
class RegistryMirror:
    """Resolves mirror-relative request paths onto a mirror remote
    (reference proxy config registryMirror.url). Scope matches the
    reference (client/daemon/proxy/proxy.go): the mirror serves requests
    addressed *to the proxy as a host* (non-absolute URIs, the container
    engine's registry-mirror mode); absolute-URI proxy requests are routed
    by rules, never silently redirected onto the mirror."""

    remote: str = ""  # e.g. "https://mirror.example.com"

    def resolve(self, path: str) -> str:
        remote = urlsplit(self.remote)
        parts = urlsplit(path)
        # keep the mirror remote's own path prefix (e.g. /registry)
        full = remote.path.rstrip("/") + parts.path
        return urlunsplit(
            (remote.scheme, remote.netloc, full, parts.query, parts.fragment)
        )


class ProxyServer:
    """Threaded HTTP proxy; GETs matching the transport's rules ride P2P."""

    def __init__(
        self,
        transport: P2PTransport,
        mirror: RegistryMirror | None = None,
        address: str = "127.0.0.1",
        port: int = 0,
    ):
        self.transport = transport
        self.mirror = mirror or RegistryMirror()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route into our logger
                logger.debug("proxy: " + fmt, *args)

            def do_GET(self):
                outer._handle_get(self)

            def do_HEAD(self):
                outer._handle_get(self, head=True)

            def do_CONNECT(self):
                outer._handle_connect(self)

        self._server = ThreadingHTTPServer((address, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="proxy", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # ------------------------------------------------------------------
    def _handle_get(self, handler: BaseHTTPRequestHandler, head: bool = False) -> None:
        url = handler.path
        if not url.startswith(("http://", "https://")):
            # non-absolute URI: treat as mirror-relative (registry mirror
            # mode fronting one remote)
            if not self.mirror.remote:
                handler.send_error(400, "absolute URI required")
                return
            url = self.mirror.resolve(url)

        headers = {
            k: v for k, v in handler.headers.items() if k.lower() not in _HOP_HEADERS
        }
        try:
            result = self.transport.round_trip(url, headers, head=head)
        except Exception as e:
            handler.send_error(502, f"upstream fetch failed: {e}")
            return
        handler.send_response(result.status)
        # forward upstream headers (Content-Type matters to registry
        # clients); hop-by-hop and length/encoding are re-derived here
        for k, v in result.headers.items():
            if k.lower() not in _HOP_HEADERS and k.lower() != "content-length":
                handler.send_header(k, v)
        if result.content_length >= 0:
            handler.send_header("Content-Length", str(result.content_length))
        else:
            # unknown length: fall back to buffering this response
            body = result.read_all()
            result = dataclasses.replace(
                result, body=iter([body]), content_length=len(body)
            )
            handler.send_header("Content-Length", str(len(body)))
        M.PROXY_REQUEST_TOTAL.labels("p2p" if result.via_p2p else "direct").inc()
        handler.send_header("X-Dragonfly-Via-P2P", "1" if result.via_p2p else "0")
        if result.task_id:
            handler.send_header("X-Dragonfly-Task-Id", result.task_id)
        handler.end_headers()
        if not head:
            # stream chunk-by-chunk — a multi-GB layer must not be
            # buffered whole per request
            for chunk in result.body:
                handler.wfile.write(chunk)

    # ------------------------------------------------------------------
    def _handle_connect(self, handler: BaseHTTPRequestHandler) -> None:
        """Opaque CONNECT tunnel: relay bytes both ways until either side
        closes (no TLS interception)."""
        try:
            host, _, port_s = handler.path.partition(":")
            upstream = socket.create_connection((host, int(port_s or 443)), timeout=10)
        except OSError as e:
            handler.send_error(502, f"CONNECT failed: {e}")
            return
        handler.send_response(200, "Connection Established")
        handler.end_headers()
        client = handler.connection
        try:
            self._relay(client, upstream)
        finally:
            upstream.close()
            # the socket carried opaque TLS bytes — never loop back into
            # HTTP parsing on it (a cleartext 400 mid-TLS breaks clients)
            handler.close_connection = True

    @staticmethod
    def _relay(a: socket.socket, b: socket.socket) -> None:
        sockets = [a, b]
        while True:
            readable, _, _ = select.select(sockets, [], [], 60)
            if not readable:
                return  # idle timeout
            for s in readable:
                try:
                    data = s.recv(65536)
                except OSError:
                    return
                if not data:
                    return
                (b if s is a else a).sendall(data)
