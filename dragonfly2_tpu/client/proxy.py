"""HTTP proxy with P2P redirection — registry/artifact acceleration.

Role parity: reference client/daemon/proxy/proxy.go:268-766 — an HTTP
proxy in front of container registries / artifact stores: plain-HTTP
requests matching the configured rules are converted into peer tasks
(P2P swarm with back-to-source), everything else passes through;
``CONNECT`` is either tunneled raw or — with an issuer configured —
TLS-intercepted with per-host spoofed certificates signed by the local
CA (reference proxy.go cert spoofing), so HTTPS registry traffic rides
P2P too. A registry mirror rewrites
request URLs onto the mirror remote before routing, which is how blob
and layer GETs become shared P2P downloads.
"""

from __future__ import annotations

import dataclasses
import re
import select
import socket
import ssl
import tempfile
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlsplit, urlunsplit

from dragonfly2_tpu.client.transport import P2PTransport, ProxyRule
from dragonfly2_tpu.client import metrics as M
from dragonfly2_tpu.utils import dflog, faults, flight, flows, profiling, tracing

logger = dflog.get("client.proxy")

# registry layer fetch observed through the proxy — the preheat demand
# window consumes these as per-layer-digest demand signal
EV_LAYER_DEMAND = flight.event_type("daemon.layer_demand")

# provenance anomaly: a P2P-capable pull that skipped the swarm — the
# event carries the swallowed cause so dfdoctor incidents can name WHY
# a layer went to the origin (satellite: no more silent fallbacks)
EV_PROXY_FALLBACK = flight.event_type("daemon.proxy_fallback")

# dfprof phase: one registry-proxy pull end to end (route + transfer)
PH_PROXY_PULL = profiling.phase_type("daemon.proxy_pull")

# fault point: the proxy pull path — chaos schedules model a wedged
# proxy front here (deterministic 502, never a hang)
FP_PROXY_PULL = faults.point("daemon.proxy_pull")

# `/v2/<name>/blobs/<digest>` — the layer-blob GET shape every OCI
# registry dialect shares
_BLOB_PATH_RX = re.compile(r"/v2/[^?#]+/blobs/([a-z0-9]+:[a-f0-9]+)")

_HOP_HEADERS = {
    # accept-encoding is stripped so origins reply identity-encoded — the
    # proxy streams bodies as-is and must not re-label compressed bytes
    "accept-encoding",
    "connection",
    "proxy-connection",
    "keep-alive",
    "te",
    "trailers",
    "transfer-encoding",
    "upgrade",
    "host",
}


def _read_chunked_body(rfile, max_bytes: int = 1 << 30) -> bytes:
    """Decode an RFC 7230 chunked request body from ``rfile``; consuming
    it fully also keeps the keep-alive connection in sync."""
    out = []
    total = 0
    while True:
        size_line = rfile.readline(1024).strip()
        size = int(size_line.split(b";", 1)[0], 16)  # chunk-ext ignored
        if size == 0:
            # trailer section (if any) ends at the blank line
            while rfile.readline(1024).strip():
                pass
            break
        total += size
        if total > max_bytes:
            raise ValueError("chunked body exceeds the forwarding cap")
        chunk = rfile.read(size)
        if len(chunk) != size:
            raise ValueError("truncated chunk in request body")
        rfile.read(2)  # trailing CRLF
        out.append(chunk)
    return b"".join(out)


@dataclass
class RegistryMirror:
    """Resolves mirror-relative request paths onto a mirror remote
    (reference proxy config registryMirror.url). Scope matches the
    reference (client/daemon/proxy/proxy.go): the mirror serves requests
    addressed *to the proxy as a host* (non-absolute URIs, the container
    engine's registry-mirror mode); absolute-URI proxy requests are routed
    by rules, never silently redirected onto the mirror."""

    remote: str = ""  # e.g. "https://mirror.example.com"

    def resolve(self, path: str) -> str:
        remote = urlsplit(self.remote)
        parts = urlsplit(path)
        # keep the mirror remote's own path prefix (e.g. /registry)
        full = remote.path.rstrip("/") + parts.path
        return urlunsplit(
            (remote.scheme, remote.netloc, full, parts.query, parts.fragment)
        )


class ProxyServer:
    """Threaded HTTP proxy; GETs matching the transport's rules ride P2P."""

    def __init__(
        self,
        transport: P2PTransport,
        mirror: RegistryMirror | None = None,
        address: str = "127.0.0.1",
        port: int = 0,
        issuer=None,  # utils.issuer.SpoofingIssuer → enables HTTPS MITM
        intercept: list[str] | None = None,  # host regexes; None = all hosts
        plane: str = "image",
    ):
        self.transport = transport
        # the proxy IS the registry plane front: stamp its transport so
        # piece-level flow attribution and the proxy's own request-level
        # accounting agree on the plane
        self.plane = plane
        transport.plane = plane
        self.mirror = mirror or RegistryMirror()
        self.issuer = issuer
        self.intercept = [re.compile(rx) for rx in intercept] if intercept else None
        # optional callable(digest, url, task_id="", meta=None) fired per
        # layer-blob GET served WITHOUT riding P2P — the scheduler's
        # preheat demand window subscribes here so direct-served layer
        # pulls still count as demand (P2P-served pulls fold through the
        # scheduler's own DownloadRecord sink; emitting here too would
        # double-count them)
        self.on_layer_demand = None
        self._ssl_ctx_cache: dict[str, ssl.SSLContext] = {}
        self._ssl_lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route into our logger
                logger.debug("proxy: " + fmt, *args)

            def do_GET(self):
                outer._handle_get(self)

            def do_HEAD(self):
                outer._handle_get(self, head=True)

            def do_CONNECT(self):
                outer._handle_connect(self)

        self._server = ThreadingHTTPServer((address, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="proxy", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # ------------------------------------------------------------------
    def _handle_get(self, handler: BaseHTTPRequestHandler, head: bool = False) -> None:
        url = handler.path
        if not url.startswith(("http://", "https://")):
            # non-absolute URI: treat as mirror-relative (registry mirror
            # mode fronting one remote)
            if not self.mirror.remote:
                handler.send_error(400, "absolute URI required")
                return
            url = self.mirror.resolve(url)

        headers = {
            k: v for k, v in handler.headers.items() if k.lower() not in _HOP_HEADERS
        }
        try:
            FP_PROXY_PULL()
        except faults.InjectedFault as e:
            handler.send_error(502, f"proxy pull fault: {e}")
            return
        # continue the caller's trace through the proxy hop; the span's
        # own context rides the outbound headers, so a direct origin
        # fetch carries it upstream (trace-context propagation)
        parent_ctx = tracing.parse_traceparent(
            handler.headers.get(tracing.TRACEPARENT_HEADER)
        )
        t0 = time.monotonic()
        with tracing.get("daemon").span(
            "daemon.proxy_pull", parent=parent_ctx, url=url, head=head
        ) as sp, PH_PROXY_PULL:
            headers[tracing.TRACEPARENT_HEADER] = tracing.format_traceparent(sp)
            try:
                result = self.transport.round_trip(url, headers, head=head)
            except Exception as e:
                handler.send_error(502, f"upstream fetch failed: {e}")
                return
            if result.fallback_cause:
                # the P2P leg failed and the transport degraded to a
                # direct fetch — name the cause instead of swallowing it
                ctx = self.transport.p2p_task_context(url)
                logger.warning(
                    "proxy pull %s skipped the swarm: %s", url, result.fallback_cause
                )
                EV_PROXY_FALLBACK(
                    url=url,
                    cause=result.fallback_cause,
                    task_id=ctx[0] if ctx is not None else "",
                )
            handler.send_response(result.status)
            # forward upstream headers (Content-Type matters to registry
            # clients); hop-by-hop and length/encoding are re-derived here
            for k, v in result.headers.items():
                if k.lower() not in _HOP_HEADERS and k.lower() != "content-length":
                    handler.send_header(k, v)
            if result.content_length >= 0:
                handler.send_header("Content-Length", str(result.content_length))
            else:
                # unknown length: fall back to buffering this response
                body = result.read_all()
                result = dataclasses.replace(
                    result, body=iter([body]), content_length=len(body)
                )
                handler.send_header("Content-Length", str(len(body)))
            M.PROXY_REQUEST_TOTAL.labels("p2p" if result.via_p2p else "direct").inc()
            self._note_layer_demand(url, result, head=head)
            handler.send_header("X-Dragonfly-Via-P2P", "1" if result.via_p2p else "0")
            if result.task_id:
                handler.send_header("X-Dragonfly-Task-Id", result.task_id)
            handler.end_headers()
            served = 0
            if not head:
                # stream chunk-by-chunk — a multi-GB layer must not be
                # buffered whole per request
                for chunk in result.body:
                    handler.wfile.write(chunk)
                    served += len(chunk)
            # flow ledger: a P2P ride's bytes were already attributed at
            # the piece write (origin/parent/dedup); the request-level
            # cases — completed-task local reuse and direct origin
            # responses — are acquired here, where the bytes move
            if result.via_p2p and not result.local_cache:
                provenance = "parent"
            elif result.local_cache:
                provenance = "local_cache"
            else:
                provenance = "origin"
            if served:
                flows.serve(self.plane, served)
                if provenance != "parent":
                    flows.account(self.plane, provenance, served)
            if 200 <= result.status < 400:
                flows.request(
                    self.plane, provenance, latency_s=time.monotonic() - t0
                )

    def _note_layer_demand(self, url: str, result, head: bool = False) -> None:
        """Emit the per-layer-digest demand signal for a served blob GET
        (HEADs are existence probes, not demand). Only successful (2xx)
        pulls count — repeated 404/401 probes of a missing layer must not
        rank it forecast-hot — and only pulls that did NOT ride P2P emit:
        a P2P ride lands a DownloadRecord at the scheduler, which folds
        the same pull there (emitting both would double-count it). When
        the transport can resolve the swarm identity the pull WOULD ride
        (task id + tag), it rides along so the preheat loop seeds the
        exact task demanded clients join. Advisory: a raising subscriber
        must never fail the response path."""
        if head or self.on_layer_demand is None:
            return
        if not 200 <= result.status < 300 or result.via_p2p:
            return
        m = _BLOB_PATH_RX.search(urlsplit(url).path)
        if m is None:
            return
        digest = m.group(1)
        task_id, target, meta = "", url, None
        ctx = self.transport.p2p_task_context(url)
        if ctx is not None:
            task_id, target, tag = ctx
            meta = {"tag": tag} if tag else {}
        EV_LAYER_DEMAND(digest=digest, task_id=task_id)
        try:
            self.on_layer_demand(digest, target, task_id=task_id, meta=meta)
        except Exception:
            logger.exception("layer-demand subscriber failed")

    # ------------------------------------------------------------------
    def _should_intercept(self, host: str) -> bool:
        if self.issuer is None:
            return False
        if self.intercept is None:
            return True
        return any(rx.search(host) for rx in self.intercept)

    def _server_ctx(self, host: str) -> ssl.SSLContext:
        """TLS server context presenting a spoofed cert for ``host``
        (cached; load_cert_chain needs files, so the pair lands in a
        private tmpdir once per host)."""
        with self._ssl_lock:
            ctx = self._ssl_ctx_cache.get(host)
            if ctx is not None:
                return ctx
        pair = self.issuer.for_host(host)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        with tempfile.TemporaryDirectory(prefix="df-mitm-") as d:
            cert_f, key_f = f"{d}/c.pem", f"{d}/k.pem"
            with open(cert_f, "wb") as f:
                f.write(pair.cert_pem)
            with open(key_f, "wb") as f:
                f.write(pair.key_pem)
            ctx.load_cert_chain(cert_f, key_f)
        with self._ssl_lock:
            self._ssl_ctx_cache[host] = ctx
        return ctx

    def _handle_connect(self, handler: BaseHTTPRequestHandler) -> None:
        """CONNECT: TLS-intercept (issuer configured and host matches)
        or relay the bytes opaquely."""
        host, _, port_s = handler.path.partition(":")
        if self._should_intercept(host):
            self._mitm(handler, host, port_s or "443")
            return
        try:
            upstream = socket.create_connection((host, int(port_s or 443)), timeout=10)
        except OSError as e:
            handler.send_error(502, f"CONNECT failed: {e}")
            return
        handler.send_response(200, "Connection Established")
        handler.end_headers()
        client = handler.connection
        try:
            self._relay(client, upstream)
        finally:
            upstream.close()
            # the socket carried opaque TLS bytes — never loop back into
            # HTTP parsing on it (a cleartext 400 mid-TLS breaks clients)
            handler.close_connection = True

    def _mitm(self, handler: BaseHTTPRequestHandler, host: str, port: str) -> None:
        """Terminate the client's TLS with a spoofed cert and serve the
        decrypted requests through the normal P2P routing (reference
        proxy.go:268-766 interceptor)."""
        handler.send_response(200, "Connection Established")
        handler.end_headers()
        handler.wfile.flush()
        outer = self
        origin = host if port == "443" else f"{host}:{port}"
        try:
            tls = self._server_ctx(host).wrap_socket(
                handler.connection, server_side=True
            )
        except (ssl.SSLError, OSError) as e:
            logger.debug("mitm handshake with %s failed: %s", origin, e)
            handler.close_connection = True
            return

        class MitmHandler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                logger.debug("mitm: " + fmt, *args)

            def do_GET(self):
                self.path = f"https://{origin}{self.path}"
                outer._handle_get(self)

            def do_HEAD(self):
                self.path = f"https://{origin}{self.path}"
                outer._handle_get(self, head=True)

            # write/auth traffic (docker push POSTs, token exchanges)
            # forwards to the origin untouched — only GETs ride P2P
            def do_POST(self):
                outer._forward_upstream(self, origin)

            def do_PUT(self):
                outer._forward_upstream(self, origin)

            def do_PATCH(self):
                outer._forward_upstream(self, origin)

            def do_DELETE(self):
                outer._forward_upstream(self, origin)

        try:
            MitmHandler(tls, handler.client_address, handler.server)
        except (ssl.SSLError, OSError, ConnectionError) as e:
            logger.debug("mitm session with %s ended: %s", origin, e)
        finally:
            try:
                tls.close()
            except OSError:
                pass
            handler.close_connection = True

    def _forward_upstream(self, handler: BaseHTTPRequestHandler, origin: str) -> None:
        """Non-GET MITM traffic: forward verbatim to the real origin and
        stream the response back (the opaque-tunnel behavior, minus the
        tunnel)."""
        import urllib.error
        import urllib.request

        from dragonfly2_tpu.client.source import open_url

        te = (handler.headers.get("Transfer-Encoding") or "").lower()
        if "chunked" in te:
            # registry pushes (docker PATCH/POST blob uploads) send
            # chunked bodies: decode them here — forwarding body=None
            # would corrupt the upload AND leave the unread chunks in
            # rfile to desync the next keep-alive request
            body = _read_chunked_body(handler.rfile)
        else:
            length = int(handler.headers.get("Content-Length") or 0)
            body = handler.rfile.read(length) if length else None
        headers = {
            k: v
            for k, v in handler.headers.items()
            if k.lower() not in _HOP_HEADERS and k.lower() != "transfer-encoding"
        }
        req = urllib.request.Request(
            f"https://{origin}{handler.path}",
            data=body,
            headers=headers,
            method=handler.command,
        )
        try:
            resp = open_url(req, 60.0)
        except urllib.error.HTTPError as e:
            resp = e  # upstream status passes through
        except OSError as e:
            handler.send_error(502, f"upstream {handler.command} failed: {e}")
            return
        with resp:
            data = resp.read()
            handler.send_response(resp.status if hasattr(resp, "status") else resp.code)
            for k, v in resp.headers.items():
                if k.lower() not in _HOP_HEADERS and k.lower() != "content-length":
                    handler.send_header(k, v)
            handler.send_header("Content-Length", str(len(data)))
            handler.end_headers()
            handler.wfile.write(data)

    @staticmethod
    def _relay(a: socket.socket, b: socket.socket) -> None:
        sockets = [a, b]
        while True:
            readable, _, _ = select.select(sockets, [], [], 60)
            if not readable:
                return  # idle timeout
            for s in readable:
                try:
                    data = s.recv(65536)
                except OSError:
                    return
                if not data:
                    return
                (b if s is a else a).sendall(data)
