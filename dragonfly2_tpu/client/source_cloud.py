"""Cloud back-to-source clients: S3 (SigV4), Alibaba OSS (header HMAC),
WebHDFS — stdlib HTTP against each service's REST API, no SDKs
(reference pkg/source/clients/{s3protocol,ossprotocol,hdfsprotocol}).

URL forms (mirroring the reference's source URL conventions):
    s3://bucket/key            credentials via DF_S3_* env or per-request
                               headers (X-Dragonfly-S3-*)
    oss://bucket/key           DF_OSS_* / X-Dragonfly-OSS-*
    hdfs://namenode:port/path  WebHDFS REST (no auth / simple user)

Endpoint override (S3-compatible stores, MinIO, test fakes):
    DF_S3_ENDPOINT / DF_OSS_ENDPOINT — http(s)://host:port; when set,
    requests go to <endpoint>/<bucket>/<key> (path-style).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Iterator

from dragonfly2_tpu.client.source import (
    CHUNK_SIZE,
    ListEntry,
    Metadata,
    SourceClient,
    SourceError,
    open_url,
)

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _env(headers: dict | None, name: str, env: str, default: str = "") -> str:
    if headers:
        v = headers.get(name)
        if v:
            return v
    return os.environ.get(env, default)


class S3SourceClient(SourceClient):
    """AWS S3 / S3-compatible origin over SigV4-signed REST
    (reference pkg/source/clients/s3protocol)."""

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    # -- request construction -------------------------------------------
    def _conf(self, headers: dict | None):
        return {
            "access_key": _env(headers, "X-Dragonfly-S3-Access-Key", "DF_S3_ACCESS_KEY"),
            "secret_key": _env(headers, "X-Dragonfly-S3-Secret-Key", "DF_S3_SECRET_KEY"),
            "region": _env(headers, "X-Dragonfly-S3-Region", "DF_S3_REGION", "us-east-1"),
            "endpoint": _env(headers, "X-Dragonfly-S3-Endpoint", "DF_S3_ENDPOINT"),
        }

    def _target(self, url: str, conf) -> tuple[str, str, str]:
        """s3://bucket/key → (request_url, host, canonical_path)."""
        p = urllib.parse.urlsplit(url)
        bucket, key = p.netloc, p.path.lstrip("/")
        if conf["endpoint"]:
            e = urllib.parse.urlsplit(conf["endpoint"])
            path = f"/{bucket}/{urllib.parse.quote(key)}"
            return f"{e.scheme}://{e.netloc}{path}", e.netloc, path
        host = f"{bucket}.s3.{conf['region']}.amazonaws.com"
        path = "/" + urllib.parse.quote(key)
        return f"https://{host}{path}", host, path

    def _sign(self, method, host, path, query, conf, extra_headers):
        """SigV4 (AWS4-HMAC-SHA256), unsigned payload — shared with the
        s3 object-storage driver (utils/awssig.py)."""
        from dragonfly2_tpu.utils.awssig import sigv4_headers

        return sigv4_headers(
            method,
            host,
            path,
            query,
            conf["region"],
            conf["access_key"],
            conf["secret_key"],
            extra_headers,
        )

    def _request(self, method, url, headers=None, range_header=None, query=""):
        conf = self._conf(headers)
        if not conf["access_key"]:
            raise SourceError(
                "s3 credentials missing: set DF_S3_ACCESS_KEY/DF_S3_SECRET_KEY"
                " or X-Dragonfly-S3-* request headers"
            )
        req_url, host, path = self._target(url, conf)
        if query:
            req_url = f"{req_url}?{query}"
        extra = {"range": range_header} if range_header else {}
        signed = self._sign(method, host, path, query, conf, extra)
        req = urllib.request.Request(req_url, method=method, headers=signed)
        try:
            return urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            raise SourceError(f"s3 {method} {url}: HTTP {e.code} {e.reason}") from e
        except urllib.error.URLError as e:
            raise SourceError(f"s3 {method} {url}: {e.reason}") from e

    # -- SourceClient ----------------------------------------------------
    def metadata(self, url: str, headers: dict | None = None) -> Metadata:
        with self._request("HEAD", url, headers) as resp:
            return Metadata(
                content_length=int(resp.headers.get("Content-Length") or -1),
                content_type=resp.headers.get("Content-Type", ""),
                support_range=True,  # S3 always honors Range
            )

    def download(
        self, url: str, headers: dict | None = None, offset: int = 0, length: int = -1
    ) -> Iterator[bytes]:
        range_header = None
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            range_header = f"bytes={offset}-{end}"
        resp = self._request("GET", url, headers, range_header=range_header)
        with resp:
            while True:
                chunk = resp.read(CHUNK_SIZE)
                if not chunk:
                    return
                yield chunk

    def list(self, url: str, headers: dict | None = None) -> list[ListEntry]:
        """ListObjectsV2 under the key prefix (recursive dfget)."""
        import xml.etree.ElementTree as ET

        p = urllib.parse.urlsplit(url)
        prefix = p.path.lstrip("/")
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        query = "delimiter=%2F&list-type=2&prefix=" + urllib.parse.quote(prefix, safe="")
        bucket_url = f"s3://{p.netloc}/"
        with self._request("GET", bucket_url, headers, query=query) as resp:
            root = ET.fromstring(resp.read())
        ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") else ""
        out: list[ListEntry] = []
        for cp in root.findall(f"{ns}CommonPrefixes"):
            sub = cp.findtext(f"{ns}Prefix") or ""
            name = sub[len(prefix) :].strip("/")
            if name:
                out.append(
                    ListEntry(name=name, url=f"s3://{p.netloc}/{sub}", is_dir=True)
                )
        for obj in root.findall(f"{ns}Contents"):
            key = obj.findtext(f"{ns}Key") or ""
            if key == prefix:
                continue
            name = key[len(prefix) :]
            out.append(ListEntry(name=name, url=f"s3://{p.netloc}/{key}", is_dir=False))
        return out


class OSSSourceClient(SourceClient):
    """Alibaba OSS origin: classic header signature
    (Authorization: OSS <key>:<base64(hmac-sha1(...))>), path-style when
    DF_OSS_ENDPOINT is set (reference pkg/source/clients/ossprotocol)."""

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    def _conf(self, headers):
        return {
            "access_key": _env(headers, "X-Dragonfly-OSS-Access-Key", "DF_OSS_ACCESS_KEY"),
            "secret_key": _env(headers, "X-Dragonfly-OSS-Secret-Key", "DF_OSS_SECRET_KEY"),
            "endpoint": _env(
                headers, "X-Dragonfly-OSS-Endpoint", "DF_OSS_ENDPOINT",
                "https://oss-cn-hangzhou.aliyuncs.com",
            ),
        }

    def _request(self, method, url, headers=None, range_header=None):
        import base64

        conf = self._conf(headers)
        if not conf["access_key"]:
            raise SourceError(
                "oss credentials missing: set DF_OSS_ACCESS_KEY/DF_OSS_SECRET_KEY"
                " or X-Dragonfly-OSS-* request headers"
            )
        p = urllib.parse.urlsplit(url)
        bucket, key = p.netloc, p.path.lstrip("/")
        e = urllib.parse.urlsplit(conf["endpoint"])
        req_url = f"{e.scheme}://{e.netloc}/{bucket}/{urllib.parse.quote(key)}"
        date = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%a, %d %b %Y %H:%M:%S GMT"
        )
        to_sign = f"{method}\n\n\n{date}\n/{bucket}/{key}"
        sig = base64.b64encode(
            hmac.new(conf["secret_key"].encode(), to_sign.encode(), hashlib.sha1).digest()
        ).decode()
        hdrs = {"Date": date, "Authorization": f"OSS {conf['access_key']}:{sig}"}
        if range_header:
            hdrs["Range"] = range_header
        req = urllib.request.Request(req_url, method=method, headers=hdrs)
        try:
            return urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as err:
            raise SourceError(f"oss {method} {url}: HTTP {err.code} {err.reason}") from err
        except urllib.error.URLError as err:
            raise SourceError(f"oss {method} {url}: {err.reason}") from err

    def metadata(self, url: str, headers: dict | None = None) -> Metadata:
        with self._request("HEAD", url, headers) as resp:
            return Metadata(
                content_length=int(resp.headers.get("Content-Length") or -1),
                content_type=resp.headers.get("Content-Type", ""),
                support_range=True,
            )

    def download(
        self, url: str, headers: dict | None = None, offset: int = 0, length: int = -1
    ) -> Iterator[bytes]:
        range_header = None
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            range_header = f"bytes={offset}-{end}"
        resp = self._request("GET", url, headers, range_header=range_header)
        with resp:
            while True:
                chunk = resp.read(CHUNK_SIZE)
                if not chunk:
                    return
                yield chunk

    def list(self, url: str, headers: dict | None = None) -> list[ListEntry]:
        raise SourceError("oss recursive listing is not implemented")


class HDFSSourceClient(SourceClient):
    """HDFS origin over the WebHDFS REST API
    (hdfs://namenode:port/path → http://namenode:port/webhdfs/v1/path,
    reference pkg/source/clients/hdfsprotocol)."""

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    def _rest(self, url: str, op: str, extra: str = "") -> str:
        p = urllib.parse.urlsplit(url)
        user = _env(None, "", "DF_HDFS_USER")
        q = f"op={op}" + (f"&user.name={user}" if user else "") + extra
        return f"http://{p.netloc}/webhdfs/v1{urllib.parse.quote(p.path)}?{q}"

    def _open(self, rest_url: str):
        try:
            return urllib.request.urlopen(rest_url, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            raise SourceError(f"hdfs {rest_url}: HTTP {e.code} {e.reason}") from e
        except urllib.error.URLError as e:
            raise SourceError(f"hdfs {rest_url}: {e.reason}") from e

    def metadata(self, url: str, headers: dict | None = None) -> Metadata:
        with self._open(self._rest(url, "GETFILESTATUS")) as resp:
            st = json.loads(resp.read())["FileStatus"]
        return Metadata(
            content_length=int(st.get("length", -1)),
            content_type="application/octet-stream",
            support_range=True,  # OPEN supports offset/length
            last_modified=float(st.get("modificationTime", 0)) / 1000.0,
        )

    def download(
        self, url: str, headers: dict | None = None, offset: int = 0, length: int = -1
    ) -> Iterator[bytes]:
        extra = ""
        if offset:
            extra += f"&offset={offset}"
        if length >= 0:
            extra += f"&length={length}"
        with self._open(self._rest(url, "OPEN", extra)) as resp:
            while True:
                chunk = resp.read(CHUNK_SIZE)
                if not chunk:
                    return
                yield chunk

    def list(self, url: str, headers: dict | None = None) -> list[ListEntry]:
        with self._open(self._rest(url, "LISTSTATUS")) as resp:
            statuses = json.loads(resp.read())["FileStatuses"]["FileStatus"]
        base = url.rstrip("/")
        out = []
        for st in statuses:
            name = st["pathSuffix"]
            out.append(
                ListEntry(
                    name=name,
                    url=f"{base}/{name}",
                    is_dir=st.get("type") == "DIRECTORY",
                )
            )
        return out


from dragonfly2_tpu.utils.oci import MANIFEST_ACCEPT as _OCI_MANIFEST_ACCEPT


class ORASSourceClient(SourceClient):
    """OCI-registry artifact origin (reference
    pkg/source/clients/orasprotocol/oras_source_client.go).

    URL form: ``oras://registry.host/repo/name:tag`` — the artifact is
    the manifest's first layer blob. Flow: bearer-token handshake →
    manifest fetch (digest of layer 0) → blob download. Fast path
    matching the reference's digest/token shortcut: when the request
    carries ``?digest=sha256:…`` AND an ``X-Dragonfly-Oras-Token``
    header, the manifest round-trip is skipped entirely.

    Registry base defaults to ``https://host``; ``DF_ORAS_ENDPOINT``
    overrides it (test fakes, plain-HTTP internal registries), same
    convention as DF_S3_ENDPOINT.
    """

    TOKEN_HEADER = "X-Dragonfly-Oras-Token"

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    # -- URL handling ----------------------------------------------------
    @staticmethod
    def _parse(url: str) -> tuple[str, str, str, str]:
        """→ (base, repo, tag, digest_query). repo/tag from the path
        ``/repo/name:tag``; base honors DF_ORAS_ENDPOINT."""
        u = urllib.parse.urlparse(url)
        path = u.path.lstrip("/")
        if ":" not in path:
            raise SourceError(f"oras url needs a ':tag' suffix: {url}")
        repo, _, tag = path.rpartition(":")
        if not repo or not tag:
            raise SourceError(f"malformed oras url: {url}")
        base = os.environ.get("DF_ORAS_ENDPOINT", "") or f"https://{u.netloc}"
        digest = urllib.parse.parse_qs(u.query).get("digest", [""])[0]
        return base.rstrip("/"), repo, tag, digest

    # -- auth ------------------------------------------------------------
    def _fetch_token(self, base: str, repo: str, headers: dict) -> str:
        """Bearer token for ``repository:<repo>:pull``. A caller-supplied
        token header short-circuits; an Authorization header (basic auth)
        is forwarded to the token service, mirroring the reference's
        fetchTokenWithHeader."""
        if headers.get(self.TOKEN_HEADER):
            return headers[self.TOKEN_HEADER]
        tok_url = (
            f"{base}/service/token?"
            + urllib.parse.urlencode({"scope": f"repository:{repo}:pull"})
        )
        hdrs = {}
        if headers.get("Authorization"):
            hdrs["Authorization"] = headers["Authorization"]
            hdrs["Accept"] = "application/json"
        req = urllib.request.Request(tok_url, headers=hdrs)
        try:
            with open_url(req, self.timeout) as resp:
                return str(json.loads(resp.read()).get("token", ""))
        except urllib.error.HTTPError as e:
            if e.code == 404:  # registry without a token service: anonymous
                return ""
            raise SourceError(f"oras token fetch: {e.code}") from e
        except urllib.error.URLError as e:
            raise SourceError(f"oras token fetch: {e.reason}") from e

    def _get(self, url: str, token: str, accept: str = "", rng: str = ""):
        hdrs = {}
        if token:
            hdrs["Authorization"] = f"Bearer {token}"
        if accept:
            hdrs["Accept"] = accept
        if rng:
            hdrs["Range"] = rng
        req = urllib.request.Request(url, headers=hdrs)
        try:
            return open_url(req, self.timeout)
        except urllib.error.HTTPError as e:
            raise SourceError(f"GET {url}: {e.code}") from e
        except urllib.error.URLError as e:
            raise SourceError(f"GET {url}: {e.reason}") from e

    def _first_layer(self, base: str, repo: str, tag: str, token: str) -> tuple[str, int]:
        """Manifest fetch → (digest, size) of layer 0 — the artifact
        payload (reference fetchManifest takes Layers[0].Digest)."""
        with self._get(
            f"{base}/v2/{repo}/manifests/{tag}", token, accept=_OCI_MANIFEST_ACCEPT
        ) as resp:
            manifest = json.loads(resp.read())
        layers = manifest.get("layers") or []
        if not layers or not layers[0].get("digest"):
            raise SourceError(f"oras manifest for {repo}:{tag} has no layer digest")
        return layers[0]["digest"], int(layers[0].get("size", -1))

    # -- SourceClient surface -------------------------------------------
    def metadata(self, url: str, headers: dict | None = None) -> Metadata:
        headers = dict(headers or {})
        base, repo, tag, digest = self._parse(url)
        token = self._fetch_token(base, repo, headers)
        size = -1
        if not digest:
            digest, size = self._first_layer(base, repo, tag, token)
        if size < 0:
            hdrs = {"Authorization": f"Bearer {token}"} if token else {}
            req = urllib.request.Request(
                f"{base}/v2/{repo}/blobs/{digest}", method="HEAD", headers=hdrs
            )
            try:
                with open_url(req, self.timeout) as resp:
                    size = int(resp.headers.get("Content-Length", -1))
            except urllib.error.HTTPError as e:
                raise SourceError(f"HEAD blob {digest}: {e.code}") from e
            except urllib.error.URLError as e:
                raise SourceError(f"HEAD blob {digest}: {e.reason}") from e
        return Metadata(
            content_length=size,
            support_range=True,
            etag=digest,
            content_type="application/octet-stream",
        )

    def download(
        self,
        url: str,
        headers: dict | None = None,
        offset: int = 0,
        length: int = -1,
    ) -> Iterator[bytes]:
        headers = dict(headers or {})
        base, repo, tag, digest = self._parse(url)
        # reference fast path: digest in query + token in header → blob
        # fetch directly, no token service / manifest round-trips
        if not (digest and headers.get(self.TOKEN_HEADER)):
            token = self._fetch_token(base, repo, headers)
            if not digest:
                digest, _ = self._first_layer(base, repo, tag, token)
        else:
            token = headers[self.TOKEN_HEADER]
        rng = ""
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            rng = f"bytes={offset}-{end}"
        with self._get(f"{base}/v2/{repo}/blobs/{digest}", token, rng=rng) as resp:
            while True:
                chunk = resp.read(CHUNK_SIZE)
                if not chunk:
                    break
                yield chunk

    def list(self, url: str, headers: dict | None = None) -> list[ListEntry]:
        raise SourceError("oras origin does not support recursive listing")
