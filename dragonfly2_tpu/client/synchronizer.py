"""Daemon↔daemon piece-metadata synchronizer: live SyncPieceTasks bidi
streams from a downloading child to each candidate parent (reference
client/daemon/peer/peertask_piecetask_synchronizer.go, 494 LoC).

The scheduler's candidate list carries a STATIC finished_pieces snapshot;
an in-progress parent keeps finishing pieces after that snapshot. The
synchronizer keeps each ParentInfo.finished_pieces fresh over the
parent's dfdaemon gRPC port, so the dispatcher prefers parents that
actually hold a piece instead of probing optimistically and eating 404s.

One thread + one bidi stream per parent; failures degrade silently to
the snapshot (the conductor's optimistic-probe fallback still works).
"""

from __future__ import annotations

import threading

from dragonfly2_tpu.rpc import gen  # noqa: F401
import dfdaemon_pb2  # noqa: E402

from dragonfly2_tpu.rpc import glue
from dragonfly2_tpu.utils import dflog

logger = dflog.get("client.sync")


class PieceTaskSynchronizer:
    def __init__(
        self,
        task_id: str,
        peer_id: str,
        interval: float = 0.2,
    ):
        self.task_id = task_id
        self.peer_id = peer_id
        self.interval = interval
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._calls: list = []  # live stream handles, cancelled on stop

    # ------------------------------------------------------------------
    def watch(self, parent, daemon_addr: str) -> None:
        """Open a sync stream to ``daemon_addr`` feeding
        ``parent.finished_pieces`` until stop()."""
        if not daemon_addr or daemon_addr.endswith(":0"):
            return
        t = threading.Thread(
            target=self._run,
            args=(parent, daemon_addr),
            name=f"piece-sync-{parent.peer_id[:8]}",
            daemon=True,
        )
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for call in self._calls:
            try:
                call.cancel()  # unblocks a thread stuck on a hung parent
            except Exception as e:
                logger.debug("piece-sync cancel failed: %s", e)
        for t in self._threads:
            t.join(timeout=2.0)

    # ------------------------------------------------------------------
    def _run(self, parent, daemon_addr: str) -> None:
        try:
            channel = glue.dial(daemon_addr, retries=1)
        except Exception as e:
            logger.debug("sync dial %s failed: %s", daemon_addr, e)
            return
        try:
            # target=daemon_addr: per-parent breaker/budget — one dead
            # parent must not trip the others' circuit
            client = glue.ServiceClient(
                channel, glue.DFDAEMON_SERVICE, target=daemon_addr
            )
            first = [True]

            def watermark() -> int:
                # contiguous-prefix watermark: every piece below it is
                # already known, so the parent only re-sends the tail —
                # without this, big tasks re-transfer the whole inventory
                # every poll
                n = 0
                known = parent.finished_pieces
                while n in known:
                    n += 1
                return n

            def requests():
                # paced request loop: each request asks for the parent's
                # inventory above the watermark; stop() ends the stream
                while not self._stop.wait(0 if first[0] else self.interval):
                    first[0] = False
                    yield dfdaemon_pb2.PieceTaskRequest(
                        task_id=self.task_id,
                        src_peer_id=parent.peer_id,
                        dst_peer_id=self.peer_id,
                        start_num=watermark(),
                        limit=0,
                    )

            call = client.SyncPieceTasks(requests())
            self._calls.append(call)
            for packet in call:
                if self._stop.is_set():
                    break
                if packet.piece_infos:
                    # set assignment is atomic enough for the dispatcher's
                    # membership reads (CPython set under the GIL)
                    parent.finished_pieces |= {
                        p.number for p in packet.piece_infos
                    }
        except Exception as e:
            if not self._stop.is_set():
                logger.debug("piece sync with %s ended: %s", parent.peer_id, e)
        finally:
            channel.close()
