"""Peer task manager: conductor dedup + completed-task reuse.

Role parity: reference client/daemon/peer/peertask_manager.go:47-505 —
StartFileTask/StartStreamTask with one conductor per task (concurrent
requests for the same task share it) and reuse of completed local tasks
(reference peertask_reuse.go).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from dataclasses import dataclass
from typing import Iterator

from dragonfly2_tpu.rpc import gen  # noqa: F401
import common_pb2  # noqa: E402

from dragonfly2_tpu.client.conductor import ConductorOptions, PeerTaskConductor, Progress
from dragonfly2_tpu.client.piece_manager import PieceManager
from dragonfly2_tpu.client.storage import StorageManager
from dragonfly2_tpu.utils import dflog
from dragonfly2_tpu.utils.idgen import URLMeta, peer_id_v2, task_id_v1

logger = dflog.get("client.peertask")


@dataclass
class FileTaskRequest:
    url: str
    output: str = ""  # empty = leave in the piece store (stream use)
    url_meta: common_pb2.UrlMeta | None = None
    disable_back_source: bool = False
    # origin-first: tell the scheduler to send this peer straight to the
    # source (seed-trigger path, reference seed_peer.go ObtainSeeds)
    need_back_to_source: bool = False
    task_type: int = 0
    headers: dict | None = None


class TaskManager:
    def __init__(
        self,
        host_id: str,
        storage: StorageManager,
        scheduler_client,
        piece_manager: PieceManager | None = None,
        options: ConductorOptions | None = None,
        host_info_fn=None,  # () -> common_pb2.HostInfo, for AnnounceTask
    ):
        self.host_id = host_id
        self.storage = storage
        self.scheduler = scheduler_client
        self.pm = piece_manager or PieceManager()
        self.options = options or ConductorOptions()
        self.host_info_fn = host_info_fn
        self.conductors: dict[str, PeerTaskConductor] = {}
        self.lock = threading.Lock()

    def _scheduler_for(self, task_id: str):
        """Consistent-hash task affinity when a multi-scheduler selector
        is wired (reference pkg/balancer); a plain client passes through."""
        if hasattr(self.scheduler, "for_task"):
            return self.scheduler.for_task(task_id)
        return self.scheduler

    # ------------------------------------------------------------------
    def task_id_for(self, url: str, url_meta: common_pb2.UrlMeta | None) -> str:
        from dragonfly2_tpu.client.pieces import normalize_byte_range

        meta = None
        if url_meta is not None:
            if url_meta.digest:
                # reject malformed pins at registration — discovering a
                # bad 'sha1:…' AFTER downloading gigabytes wastes the
                # whole transfer
                from dragonfly2_tpu.utils.digest import parse_digest

                parse_digest(url_meta.digest)
            meta = URLMeta(
                digest=url_meta.digest,
                tag=url_meta.tag,
                # canonicalized: equivalent range spellings share one
                # task (and malformed specs fail at registration)
                range=normalize_byte_range(url_meta.range),
                filter=url_meta.filter,
                application=url_meta.application,
            )
        return task_id_v1(url, meta)

    def start_file_task(self, req: FileTaskRequest) -> tuple[str, str, PeerTaskConductor | None]:
        """Returns (task_id, peer_id, conductor|None). None conductor =
        served from completed local storage (reuse path)."""
        url_meta = req.url_meta or common_pb2.UrlMeta()
        task_id = self.task_id_for(req.url, url_meta)

        done = self.storage.find_completed_task(task_id)
        if done is not None:
            logger.info("task %s reused from local storage", task_id[:16])
            if req.output:
                done.store(req.output)
            return task_id, done.meta.peer_id, None

        with self.lock:
            conductor = self.conductors.get(task_id)
            if conductor is not None and not conductor.progress().error:
                return task_id, conductor.peer_id, conductor
            peer_id = peer_id_v2()
            opts = dataclasses.replace(
                self.options,
                disable_back_source=req.disable_back_source or self.options.disable_back_source,
            )
            conductor = PeerTaskConductor(
                task_id=task_id,
                peer_id=peer_id,
                host_id=self.host_id,
                url=req.url,
                url_meta=url_meta,
                storage=self.storage,
                # the selector itself, not a resolved client: the
                # conductor re-resolves the task's ring owner per stream
                # connect, so fleet membership moves (WRONG_SHARD
                # re-pick, successor failover) land mid-task
                scheduler_client=self.scheduler,
                piece_manager=self.pm,
                options=opts,
                task_type=req.task_type,
                # origin headers: explicit request field, else
                # UrlMeta.header — EVERY frontend (Download, ExportTask,
                # proxy, gateway) gets auth to the back-to-source fetch
                # without per-entry-point special-casing
                headers=req.headers or dict(url_meta.header),
                need_back_to_source=req.need_back_to_source,
                on_done=self._forget,
            )
            self.conductors[task_id] = conductor
            conductor.start()
        return task_id, peer_id, conductor

    # ------------------------------------------------------------------
    # stream frontend (reference peertask_stream.go): bytes flow to the
    # caller as pieces land, instead of waiting for the whole task —
    # the proxy/transport/object-gateway path for large blobs
    # ------------------------------------------------------------------
    def start_stream_task(
        self, req: FileTaskRequest, timeout: float | None = None
    ) -> tuple[str, str, int, dict, "Iterator[bytes]"]:
        """Returns (task_id, peer_id, content_length, origin_headers,
        piece iterator). Blocks only until the task geometry and first
        piece are known (time-to-first-byte), then hands back a generator
        yielding pieces in order as they complete. The generator raises
        ``IOError`` if the underlying task fails mid-stream."""
        task_id, peer_id, conductor = self.start_file_task(
            dataclasses.replace(req, output="")
        )
        if conductor is None:  # completed local task: stream from disk
            ts = self.storage.load(task_id)
            return (
                task_id,
                peer_id,
                ts.meta.content_length,
                dict(ts.meta.headers),
                self._stored_pieces(ts),
            )

        # subscribe BEFORE inspecting state so no completion wakeup is lost
        sub = conductor.subscribe()
        deadline = None if timeout is None else time.monotonic() + timeout

        def wait_tick(ctx: str) -> None:
            p = conductor.progress()
            if p.error:
                raise IOError(f"stream task {task_id[:16]} failed {ctx}: {p.error}")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"stream task {task_id[:16]} timed out {ctx}")
            try:
                sub.get(timeout=0.2)
            except queue.Empty:
                pass

        # time-to-first-byte: geometry + piece 0 (reference
        # peertask_stream.go waits for the first piece the same way)
        while True:
            ts = self.storage.load(task_id)
            if ts is not None and ts.meta.content_length >= 0 and (
                0 in ts.meta.pieces or conductor.progress().done
            ):
                break
            wait_tick("before first byte")

        def pieces() -> "Iterator[bytes]":
            n = 0
            sent = 0
            while True:
                if n in ts.meta.pieces:
                    data = ts.read_piece(n)
                    sent += len(data)
                    yield data
                    n += 1
                    # the byte count, not the done flag, ends the stream —
                    # the conductor's finish handshake with the scheduler
                    # lags the last piece and must not hold the response
                    if ts.meta.content_length >= 0 and sent >= ts.meta.content_length:
                        return
                    continue
                p = conductor.progress()
                if p.done:
                    # pieces are written before done is published, so a
                    # finished task has the full contiguous set
                    if n >= len(ts.meta.pieces):
                        return
                    if n not in ts.meta.pieces:  # pragma: no cover - defensive
                        raise IOError(f"stream task {task_id[:16]}: gap at piece {n}")
                wait_tick(f"at piece {n}")

        return task_id, peer_id, ts.meta.content_length, dict(ts.meta.headers), pieces()

    @staticmethod
    def _stored_pieces(ts) -> "Iterator[bytes]":
        for number in sorted(ts.meta.pieces):
            yield ts.read_piece(number)

    # ------------------------------------------------------------------
    # seed frontend (reference peertask_seed.go / seeder ObtainSeeds):
    # origin-first download that makes THIS daemon the swarm's feed
    # ------------------------------------------------------------------
    def start_seed_task(
        self,
        url: str,
        url_meta: common_pb2.UrlMeta | None = None,
        headers: dict | None = None,
        task_type: int = 0,
    ) -> tuple[str, str, PeerTaskConductor | None]:
        """Registers with need_back_to_source so the scheduler sends this
        peer straight to the origin; children are then fed from here
        (reference seed_peer.go:92-213 trigger → seeder.go ObtainSeeds)."""
        return self.start_file_task(
            FileTaskRequest(
                url=url,
                url_meta=url_meta,
                need_back_to_source=True,
                headers=headers,
                task_type=task_type,
            )
        )

    def _forget(self, conductor: PeerTaskConductor) -> None:
        """Completion callback: drop the finished conductor so the dict
        doesn't grow unboundedly and a failed task can be retried. A
        timed-out waiter must NOT pop — the conductor is still running
        and concurrent requests should keep sharing it."""
        with self.lock:
            if self.conductors.get(conductor.task_id) is conductor:
                self.conductors.pop(conductor.task_id)

    def import_completed_task(
        self,
        task_id: str,
        url: str,
        read_chunk,
        size: int,
        piece_length: int = 0,
        task_type: int = 0,
    ) -> None:
        """Seed local bytes as a completed task and announce it: shared by
        dfcache ImportTask and the gateway's seed-on-write path (reference
        rpcserver.go ImportTask → announcePeerTask). ``read_chunk(n)``
        yields up to n bytes per call (file handle or BytesIO reader).
        The announce is best-effort — a scheduler outage must not fail a
        local import."""
        from dragonfly2_tpu.client.pieces import compute_piece_length

        pl = piece_length or compute_piece_length(size)
        ts = self.storage.register_task(
            task_id, peer_id_v2(), url=url, piece_length=pl, content_length=size
        )
        number = 0
        while True:
            chunk = read_chunk(pl)
            if not chunk and number > 0:
                break
            ts.write_piece(number, number * pl, chunk, traffic_type="local_peer")
            number += 1
            if len(chunk) < pl:
                break
        ts.mark_done(size)
        try:
            self.announce_completed_task(ts, task_type=task_type)
        except Exception as e:
            logger.warning("announce imported task %s failed: %s", task_id[:16], e)

    def announce_completed_task(self, ts, task_type: int = 0) -> None:
        """Tell the scheduler this daemon holds the complete task (dfcache
        import / gateway seed-on-write) so it becomes the first candidate
        parent instead of every other peer back-sourcing (reference
        client/daemon/rpcserver announcePeerTask → scheduler AnnounceTask)."""
        import scheduler_pb2  # noqa: E402 — flat proto import

        self._scheduler_for(ts.meta.task_id).AnnounceTask(
            scheduler_pb2.AnnounceTaskRequest(
                host_id=self.host_id,
                host=self.host_info_fn() if self.host_info_fn else None,
                task_id=ts.meta.task_id,
                peer_id=ts.meta.peer_id,
                url=ts.meta.url,
                url_meta=common_pb2.UrlMeta(tag=ts.meta.tag, application=ts.meta.application),
                task_type=task_type,
                content_length=ts.meta.content_length,
                piece_length=ts.meta.piece_length,
                pieces=[
                    common_pb2.PieceInfo(
                        number=p.number,
                        offset=p.offset,
                        length=p.length,
                        digest=p.digest,
                        traffic_type=p.traffic_type,
                        cost_ns=p.cost_ns,
                    )
                    for _, p in sorted(ts.meta.pieces.items())
                ],
            )
        )

    def wait_file_task(self, req: FileTaskRequest, timeout: float | None = None) -> tuple[str, str, Progress]:
        task_id, peer_id, conductor = self.start_file_task(req)
        if conductor is None:
            ts = self.storage.load(task_id)
            return task_id, peer_id, Progress(
                completed_length=ts.meta.content_length,
                content_length=ts.meta.content_length,
                done=True,
            )
        progress = conductor.wait(timeout)
        if progress.done and req.output:
            self.storage.load(task_id).store(req.output)
        return task_id, peer_id, progress
