"""Peer task manager: conductor dedup + completed-task reuse.

Role parity: reference client/daemon/peer/peertask_manager.go:47-505 —
StartFileTask/StartStreamTask with one conductor per task (concurrent
requests for the same task share it) and reuse of completed local tasks
(reference peertask_reuse.go).
"""

from __future__ import annotations

import dataclasses
import threading
from dataclasses import dataclass

from dragonfly2_tpu.rpc import gen  # noqa: F401
import common_pb2  # noqa: E402

from dragonfly2_tpu.client.conductor import ConductorOptions, PeerTaskConductor, Progress
from dragonfly2_tpu.client.piece_manager import PieceManager
from dragonfly2_tpu.client.storage import StorageManager
from dragonfly2_tpu.utils import dflog
from dragonfly2_tpu.utils.idgen import URLMeta, peer_id_v2, task_id_v1

logger = dflog.get("client.peertask")


@dataclass
class FileTaskRequest:
    url: str
    output: str = ""  # empty = leave in the piece store (stream use)
    url_meta: common_pb2.UrlMeta | None = None
    disable_back_source: bool = False
    # origin-first: tell the scheduler to send this peer straight to the
    # source (seed-trigger path, reference seed_peer.go ObtainSeeds)
    need_back_to_source: bool = False
    task_type: int = 0
    headers: dict | None = None


class TaskManager:
    def __init__(
        self,
        host_id: str,
        storage: StorageManager,
        scheduler_client,
        piece_manager: PieceManager | None = None,
        options: ConductorOptions | None = None,
        host_info_fn=None,  # () -> common_pb2.HostInfo, for AnnounceTask
    ):
        self.host_id = host_id
        self.storage = storage
        self.scheduler = scheduler_client
        self.pm = piece_manager or PieceManager()
        self.options = options or ConductorOptions()
        self.host_info_fn = host_info_fn
        self.conductors: dict[str, PeerTaskConductor] = {}
        self.lock = threading.Lock()

    def _scheduler_for(self, task_id: str):
        """Consistent-hash task affinity when a multi-scheduler selector
        is wired (reference pkg/balancer); a plain client passes through."""
        if hasattr(self.scheduler, "for_task"):
            return self.scheduler.for_task(task_id)
        return self.scheduler

    # ------------------------------------------------------------------
    def task_id_for(self, url: str, url_meta: common_pb2.UrlMeta | None) -> str:
        meta = None
        if url_meta is not None:
            meta = URLMeta(
                digest=url_meta.digest,
                tag=url_meta.tag,
                range=url_meta.range,
                filter=url_meta.filter,
                application=url_meta.application,
            )
        return task_id_v1(url, meta)

    def start_file_task(self, req: FileTaskRequest) -> tuple[str, str, PeerTaskConductor | None]:
        """Returns (task_id, peer_id, conductor|None). None conductor =
        served from completed local storage (reuse path)."""
        url_meta = req.url_meta or common_pb2.UrlMeta()
        task_id = self.task_id_for(req.url, url_meta)

        done = self.storage.find_completed_task(task_id)
        if done is not None:
            logger.info("task %s reused from local storage", task_id[:16])
            if req.output:
                done.store(req.output)
            return task_id, done.meta.peer_id, None

        with self.lock:
            conductor = self.conductors.get(task_id)
            if conductor is not None and not conductor.progress().error:
                return task_id, conductor.peer_id, conductor
            peer_id = peer_id_v2()
            opts = dataclasses.replace(
                self.options,
                disable_back_source=req.disable_back_source or self.options.disable_back_source,
            )
            conductor = PeerTaskConductor(
                task_id=task_id,
                peer_id=peer_id,
                host_id=self.host_id,
                url=req.url,
                url_meta=url_meta,
                storage=self.storage,
                scheduler_client=self._scheduler_for(task_id),
                piece_manager=self.pm,
                options=opts,
                task_type=req.task_type,
                headers=req.headers,
                need_back_to_source=req.need_back_to_source,
                on_done=self._forget,
            )
            self.conductors[task_id] = conductor
            conductor.start()
        return task_id, peer_id, conductor

    def _forget(self, conductor: PeerTaskConductor) -> None:
        """Completion callback: drop the finished conductor so the dict
        doesn't grow unboundedly and a failed task can be retried. A
        timed-out waiter must NOT pop — the conductor is still running
        and concurrent requests should keep sharing it."""
        with self.lock:
            if self.conductors.get(conductor.task_id) is conductor:
                self.conductors.pop(conductor.task_id)

    def import_completed_task(
        self,
        task_id: str,
        url: str,
        read_chunk,
        size: int,
        piece_length: int = 0,
        task_type: int = 0,
    ) -> None:
        """Seed local bytes as a completed task and announce it: shared by
        dfcache ImportTask and the gateway's seed-on-write path (reference
        rpcserver.go ImportTask → announcePeerTask). ``read_chunk(n)``
        yields up to n bytes per call (file handle or BytesIO reader).
        The announce is best-effort — a scheduler outage must not fail a
        local import."""
        from dragonfly2_tpu.client.pieces import compute_piece_length

        pl = piece_length or compute_piece_length(size)
        ts = self.storage.register_task(
            task_id, peer_id_v2(), url=url, piece_length=pl, content_length=size
        )
        number = 0
        while True:
            chunk = read_chunk(pl)
            if not chunk and number > 0:
                break
            ts.write_piece(number, number * pl, chunk, traffic_type="local_peer")
            number += 1
            if len(chunk) < pl:
                break
        ts.mark_done(size)
        try:
            self.announce_completed_task(ts, task_type=task_type)
        except Exception as e:
            logger.warning("announce imported task %s failed: %s", task_id[:16], e)

    def announce_completed_task(self, ts, task_type: int = 0) -> None:
        """Tell the scheduler this daemon holds the complete task (dfcache
        import / gateway seed-on-write) so it becomes the first candidate
        parent instead of every other peer back-sourcing (reference
        client/daemon/rpcserver announcePeerTask → scheduler AnnounceTask)."""
        import scheduler_pb2  # noqa: E402 — flat proto import

        self._scheduler_for(ts.meta.task_id).AnnounceTask(
            scheduler_pb2.AnnounceTaskRequest(
                host_id=self.host_id,
                host=self.host_info_fn() if self.host_info_fn else None,
                task_id=ts.meta.task_id,
                peer_id=ts.meta.peer_id,
                url=ts.meta.url,
                url_meta=common_pb2.UrlMeta(tag=ts.meta.tag, application=ts.meta.application),
                task_type=task_type,
                content_length=ts.meta.content_length,
                piece_length=ts.meta.piece_length,
                pieces=[
                    common_pb2.PieceInfo(
                        number=p.number,
                        offset=p.offset,
                        length=p.length,
                        digest=p.digest,
                        traffic_type=p.traffic_type,
                        cost_ns=p.cost_ns,
                    )
                    for _, p in sorted(ts.meta.pieces.items())
                ],
            )
        )

    def wait_file_task(self, req: FileTaskRequest, timeout: float | None = None) -> tuple[str, str, Progress]:
        task_id, peer_id, conductor = self.start_file_task(req)
        if conductor is None:
            ts = self.storage.load(task_id)
            return task_id, peer_id, Progress(
                completed_length=ts.meta.content_length,
                content_length=ts.meta.content_length,
                done=True,
            )
        progress = conductor.wait(timeout)
        if progress.done and req.output:
            self.storage.load(task_id).store(req.output)
        return task_id, peer_id, progress
