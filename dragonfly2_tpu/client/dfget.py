"""dfget — file download CLI, a thin gRPC client of the local daemon.

Role parity: reference client/dfget/dfget.go:47-386 +
cmd/dfget/cmd/root.go:246-300 — Download stream with progress, recursive
directory mode via source listing (dfget.go:317-386).
"""

from __future__ import annotations

import argparse
import os
import sys

from dragonfly2_tpu.rpc import gen  # noqa: F401
import common_pb2  # noqa: E402
import dfdaemon_pb2  # noqa: E402

from dragonfly2_tpu.client import source
from dragonfly2_tpu.rpc import glue

from dragonfly2_tpu.rpc.glue import DFDAEMON_SERVICE


def daemon_alive(daemon_address: str, timeout: float = 2.0) -> bool:
    """Liveness probe: can a channel to the daemon become ready within
    ``timeout``?"""
    try:
        channel = glue.dial(daemon_address, retries=1, ready_timeout=timeout)
        channel.close()
        return True
    except Exception:
        return False


def ensure_daemon(
    daemon_address: str,
    scheduler_address: str,
    data_dir: str,
    wait: float = 15.0,
) -> bool:
    """Spawn-or-reuse the local daemon (reference cmd/dfget/cmd/root.go:279
    checkAndSpawnDaemon): probe ``daemon_address`` (normally a
    ``unix:/path`` socket); when dead, fork a detached
    ``python -m dragonfly2_tpu.client.daemon`` serving that address and
    wait for it to come up. Returns True when the daemon got spawned."""
    import subprocess
    import time

    if daemon_alive(daemon_address):
        return False
    overrides = [
        "--set", f"scheduler_address={scheduler_address}",
        "--set", f"data_dir={data_dir}",
    ]
    if daemon_address.startswith("unix:"):
        overrides += ["--set", f"unix_socket={daemon_address[5:]}"]
    else:
        overrides += ["--set", f"listen={daemon_address}"]
    proc = subprocess.Popen(
        [sys.executable, "-m", "dragonfly2_tpu.client.daemon", *overrides],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,  # outlive this dfget invocation
    )
    deadline = time.monotonic() + wait
    while time.monotonic() < deadline:
        if daemon_alive(daemon_address, timeout=0.5):
            return True
        if proc.poll() is not None:
            # OUR spawn exiting is not fatal by itself: in a concurrent
            # spawn race the loser exits ("another daemon is serving")
            # while the winner is still starting — keep probing until
            # the deadline and only then conclude nothing is serving
            time.sleep(0.2)
            continue
        time.sleep(0.2)
    if daemon_alive(daemon_address, timeout=1.0):
        return True
    if proc.poll() is not None:
        raise RuntimeError(
            f"spawned daemon exited with rc={proc.returncode} and nothing"
            f" is serving {daemon_address}"
        )
    raise TimeoutError(f"spawned daemon not ready on {daemon_address} within {wait}s")


def add_spawn_daemon_args(parser) -> None:
    """The spawn-or-reuse CLI trio shared by dfget/dfcache (reference:
    both CLIs spawn the daemon over the unix socket when none answers)."""
    parser.add_argument("--spawn-daemon", action="store_true")
    parser.add_argument(
        "--scheduler",
        default=os.environ.get("DF_SCHEDULER_ADDR", "127.0.0.1:8002"),
        help="scheduler address(es) a spawned daemon announces to",
    )
    parser.add_argument(
        "--daemon-data-dir",
        default=os.path.expanduser("~/.dragonfly2/daemon"),
        help="data dir a spawned daemon uses",
    )


def download(
    daemon_address: str,
    url: str,
    output: str,
    tag: str = "",
    application: str = "",
    digest: str = "",
    byte_range: str = "",
    headers: dict | None = None,
    disable_back_source: bool = False,
    recursive: bool = False,
    on_progress=None,
) -> list[str]:
    """Download ``url`` to ``output`` through the daemon; returns the
    list of written paths (1 for a file, N for recursive)."""
    if recursive:
        if byte_range:
            # a byte range of a directory is meaningless; dropping it
            # silently would hand back full files the caller didn't ask for
            raise ValueError("--range cannot be combined with --recursive")
        if digest:
            # one digest cannot pin N different files — silently skipping
            # verification would betray exactly the caller who asked for it
            raise ValueError("--digest cannot be combined with --recursive")
        return _download_recursive(
            daemon_address, url, output, tag=tag, application=application,
            headers=headers, on_progress=on_progress,
        )
    client = glue.ServiceClient(glue.dial(daemon_address), DFDAEMON_SERVICE)
    req = dfdaemon_pb2.DownloadRequest(
        url=url,
        output=os.path.abspath(output),
        url_meta=common_pb2.UrlMeta(
            tag=tag,
            application=application,
            digest=digest,
            range=byte_range,
            header=headers or {},
        ),
        disable_back_source=disable_back_source,
    )
    for result in client.Download(req):
        if on_progress:
            on_progress(result)
        if result.done:
            return [output]
    raise RuntimeError("download stream ended without completion")


def _download_recursive(
    daemon_address: str, url: str, output: str, tag: str = "",
    application: str = "", headers: dict | None = None, on_progress=None,
) -> list[str]:
    """Directory mode: list the origin, download each file through the
    daemon (reference dfget.go:317-386). ``headers`` authenticate both
    the listing and every per-file back-to-source fetch."""
    entries = source.client_for(url).list(url, headers)
    written: list[str] = []
    for e in entries:
        dest = os.path.join(output, e.name)
        if e.is_dir:
            written += _download_recursive(
                daemon_address, e.url, dest, tag=tag,
                application=application, headers=headers, on_progress=on_progress,
            )
        else:
            os.makedirs(output, exist_ok=True)
            written += download(
                daemon_address, e.url, dest, tag=tag,
                application=application, headers=headers, on_progress=on_progress,
            )
    return written


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="dfget", description="P2P file download")
    p.add_argument("url")
    p.add_argument("-O", "--output", required=True)
    p.add_argument("--daemon", default=os.environ.get("DFDAEMON_ADDR", "127.0.0.1:65000"))
    p.add_argument("--tag", default="")
    p.add_argument("--application", default="")
    p.add_argument(
        "--digest",
        default="",
        help='pin the downloaded content: "sha256:<hex>" or "md5:<hex>";'
        " verified before success is reported (with --range, the pin"
        " covers the slice — the task's content)",
    )
    p.add_argument(
        "-H",
        "--header",
        action="append",
        default=[],
        dest="origin_headers",
        metavar="'K: V'",
        help="origin request header (repeatable) — auth for private"
        " registries / signed URLs on the back-to-source fetch",
    )
    p.add_argument(
        "--range",
        default="",
        dest="byte_range",
        help='byte range of the origin object, e.g. "0-1023" or "bytes=4096-" '
        "(inclusive HTTP semantics; the range is part of the task identity)",
    )
    p.add_argument("--disable-back-source", action="store_true")
    p.add_argument("--recursive", action="store_true")
    # spawn-or-reuse: start a local daemon on --daemon when none answers
    # (reference dfget root.go:279 checkAndSpawnDaemon)
    add_spawn_daemon_args(p)
    args = p.parse_args(argv)

    if args.byte_range:
        # fail fast with the real message — daemon-side validation would
        # surface as an opaque gRPC error
        from dragonfly2_tpu.client.pieces import normalize_byte_range

        try:
            args.byte_range = normalize_byte_range(args.byte_range)
        except ValueError as e:
            p.error(str(e))

    if args.spawn_daemon:
        ensure_daemon(args.daemon, args.scheduler, args.daemon_data_dir)

    def progress(r):
        if r.content_length > 0:
            pct = 100.0 * r.completed_length / r.content_length
            print(f"\r{pct:6.2f}% {r.completed_length}/{r.content_length}", end="", file=sys.stderr)

    origin_headers = {}
    for spec in args.origin_headers:
        k, sep, v = spec.partition(":")
        if not sep or not k.strip():
            p.error(f"malformed --header {spec!r} (need 'Name: value')")
        k = k.strip()
        if k in origin_headers:
            # repeated names combine per RFC 9110 — silent last-wins
            # would drop a Cookie/Forwarded entry the origin requires
            origin_headers[k] = f"{origin_headers[k]}, {v.strip()}"
        else:
            origin_headers[k] = v.strip()

    paths = download(
        args.daemon, args.url, args.output,
        tag=args.tag, application=args.application, digest=args.digest,
        byte_range=args.byte_range, headers=origin_headers,
        disable_back_source=args.disable_back_source,
        recursive=args.recursive, on_progress=progress,
    )
    print(file=sys.stderr)
    for path in paths:
        print(path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
