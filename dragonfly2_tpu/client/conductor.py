"""Peer task conductor — one per (task, peer): the client hot path.

Role parity: reference client/daemon/peer/peertask_conductor.go:68-1584 —
register with the scheduler (:249), ingest parent assignments from the
announce stream (:659-774), fan piece downloads across workers
(:976-1108), fall back to the origin when told to (:485-523), and report
every piece + the final result back up the stream (which is what produces
the scheduler's Download training records).

The v2 AnnouncePeer bidi stream replaces the reference's v1
RegisterPeerTask/ReportPieceResult pair; piece *bytes* still ride HTTP
from the parent's upload server.
"""

# dfanalyze: hot — per-piece accounting and the per-peer run loop

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from dragonfly2_tpu.rpc import gen  # noqa: F401
import common_pb2  # noqa: E402
import dfdaemon_pb2  # noqa: E402
import scheduler_pb2  # noqa: E402

from dragonfly2_tpu.rpc import glue, resilience
from dragonfly2_tpu.scheduler import fleet
from dragonfly2_tpu.utils import tracing

from dragonfly2_tpu.client import downloader
from dragonfly2_tpu.client.downloader import PieceDownloadError
from dragonfly2_tpu.client.synchronizer import PieceTaskSynchronizer
from dragonfly2_tpu.client.piece_manager import (
    ParentInfo,
    PieceDispatcher,
    PieceManager,
    PieceResult,
    TRAFFIC_REMOTE_PEER,
)
from dragonfly2_tpu.client.pieces import PieceRange, parse_byte_range, piece_ranges
from dragonfly2_tpu.client.storage import StorageManager
from dragonfly2_tpu.client import metrics as M
from dragonfly2_tpu.utils import dflog, faults, flight, profiling

logger = dflog.get("client.conductor")

# dfprof phase: time spent waiting for an in-progress parent to produce
# a piece it hasn't written yet — the piece path's third wall leg next
# to daemon.piece_read / daemon.piece_write (piece_manager)
PH_PARENT_WAIT = profiling.phase_type("daemon.parent_wait")

# fault point: the announce-stream open — chaos schedules kill the
# scheduler link here to drill the reconnect-with-resume path
FP_ANNOUNCE_STREAM = faults.point("daemon.announce_stream")

# flight-recorder emitters: the peer/piece lifecycle as the daemon saw
# it — the always-on black box a wedged peer postmortem replays
EV_PEER_START = flight.event_type("daemon.peer_start")
EV_PEER_DECISION = flight.event_type("daemon.peer_decision")
EV_PEER_FINISHED = flight.event_type("daemon.peer_finished")
EV_PEER_FAILED = flight.event_type("daemon.peer_failed")
EV_PEER_BACK_TO_SOURCE = flight.event_type("daemon.peer_back_to_source")
EV_PIECE_DONE = flight.event_type("daemon.piece_done")
EV_PIECE_FAILED = flight.event_type("daemon.piece_failed")
EV_PARENT_BLOCKED = flight.event_type("daemon.parent_blocked")
EV_RESCHEDULE = flight.event_type("daemon.reschedule")
EV_ANNOUNCE_RECONNECT = flight.event_type("daemon.announce_reconnect")
EV_WRONG_SHARD_REPICK = flight.event_type("daemon.wrong_shard_repick")


@dataclass
class Progress:
    completed_length: int = 0
    content_length: int = -1
    done: bool = False
    error: str = ""


@dataclass
class ConductorOptions:
    piece_workers: int = 4
    schedule_timeout: float = 10.0
    piece_retry: int = 3
    # consecutive hard failures before a parent is blocked for the task —
    # one transient timeout must not escalate to back-to-source
    parent_fail_limit: int = 3
    # wait between retries when a parent 404s a piece it may write soon
    not_found_backoff: float = 0.05
    # total time budget to wait for an in-progress parent to produce an
    # unadvertised piece — separate from piece_retry, so a slightly-slow
    # swarm doesn't force a full reschedule round-trip every ~150ms
    wait_piece_timeout: float = 5.0
    disable_back_source: bool = False
    piece_length: int = 0  # 0 = derive from content length
    # announce-stream resume: a broken scheduler stream (restart, network
    # blip) re-opens and re-registers this many times before the old
    # fail/back-to-source behavior kicks in — the peer task survives the
    # scheduler's incident instead of paying an origin round trip for it
    stream_reconnect_attempts: int = 3
    stream_reconnect_backoff: float = 0.2
    # WRONG_SHARD retry budget (docs/fleet.md): a refused announce
    # re-picks from the refreshed ring for this long before the regular
    # reconnect/back-to-source ladder takes over. Sized to cover one
    # lease TTL + one membership poll — the window in which a SIGKILL'd
    # owner is still leased and every member keeps pointing at it
    wrong_shard_retry_window: float = 15.0
    wrong_shard_backoff: float = 0.1


class PeerTaskConductor:
    """Drives one peer's download of one task end to end."""

    def __init__(
        self,
        task_id: str,
        peer_id: str,
        host_id: str,
        url: str,
        url_meta: common_pb2.UrlMeta,
        storage: StorageManager,
        scheduler_client,
        piece_manager: PieceManager | None = None,
        options: ConductorOptions | None = None,
        task_type: int = 0,
        headers: dict | None = None,
        need_back_to_source: bool = False,
        on_done=None,
    ):
        self.task_id = task_id
        self.peer_id = peer_id
        self.host_id = host_id
        self.url = url
        self.url_meta = url_meta
        self.storage = storage
        self.scheduler = scheduler_client
        self.pm = piece_manager or PieceManager()
        self.opts = options or ConductorOptions()
        self.task_type = task_type
        self.headers = headers or {}
        self.need_back_to_source = need_back_to_source
        self.on_done = on_done

        self.ts = storage.register_task(
            task_id,
            peer_id,
            url=url,
            piece_length=self.opts.piece_length,
            tag=url_meta.tag,
            application=url_meta.application,
        )
        self.ts.busy = True  # owned by this conductor until finish/fail
        self._requests: "queue.Queue[scheduler_pb2.AnnouncePeerRequest | None]" = queue.Queue()
        self._decisions: "queue.Queue[object]" = queue.Queue()
        self._progress_subs: list["queue.Queue[Progress]"] = []
        self._lock = threading.Lock()
        self._completed = 0
        self._blocked_parents: set[str] = set()
        self._parent_failures: dict[str, int] = {}
        self._done = threading.Event()
        self._error: str | None = None
        self._started_at = 0.0
        self._stream_thread: threading.Thread | None = None
        self._run_thread: threading.Thread | None = None
        self._stream_reconnects = 0
        self._wrong_shard_deadline = 0.0
        self._wrong_shard_retries = 0
        self._owner_hint = ""  # WRONG_SHARD told us who owns the shard
        self._outage_started = 0.0  # announce-plane blackout clock
        # members this conductor's streams just failed against: a cached
        # channel to a dead scheduler fails at CALL time, not dial time,
        # so the selector needs this feedback to walk past it
        self._avoid_addrs: set[str] = set()
        self._last_sched_addr = ""

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        M.TASK_TOTAL.labels("file").inc()
        # span per peer task (reference peertask_conductor.go:123-124)
        self._span = tracing.get("dfdaemon").start_span(
            "peer_task", task_id=self.task_id, peer_id=self.peer_id, url=self.url
        )
        with tracing.use_span(self._span):
            EV_PEER_START(task_id=self.task_id, peer_id=self.peer_id, url=self.url)
        self._started_at = time.monotonic()
        self._stream_thread = threading.Thread(
            target=self._stream_loop,
            name=f"daemon.announce-{self.peer_id[:8]}",
            daemon=True,
        )
        self._stream_thread.start()
        self._run_thread = threading.Thread(
            target=self._run, name=f"daemon.conductor-{self.peer_id[:8]}", daemon=True
        )
        self._run_thread.start()

    def wait(self, timeout: float | None = None) -> Progress:
        self._done.wait(timeout)
        return self.progress()

    def progress(self) -> Progress:
        with self._lock:
            return Progress(
                completed_length=self._completed,
                content_length=self.ts.meta.content_length,
                done=self._done.is_set() and self._error is None,
                error=self._error or "",
            )

    def subscribe(self) -> "queue.Queue[Progress]":
        q: "queue.Queue[Progress]" = queue.Queue()
        with self._lock:
            self._progress_subs.append(q)
        if self._done.is_set():  # already finished — deliver terminal state
            q.put(self.progress())
        return q

    def _publish(self) -> None:
        p = self.progress()
        with self._lock:
            subs = list(self._progress_subs)
        for q in subs:
            q.put(p)

    # ------------------------------------------------------------------
    # announce stream plumbing
    # ------------------------------------------------------------------
    def _req_iter(self, requests):
        # the queue is a parameter, not read off self per iteration: a
        # reconnect swaps self._requests, and the dead stream's feeder
        # must keep draining ITS queue (where its None sentinel went),
        # never steal the replacement stream's re-register
        while True:
            r = requests.get()
            if r is None:
                return
            yield r

    def _send(self, **kwargs) -> None:
        self._requests.put(
            scheduler_pb2.AnnouncePeerRequest(
                host_id=self.host_id, task_id=self.task_id, peer_id=self.peer_id, **kwargs
            )
        )

    def _resolve_scheduler(self):
        """The client for THIS stream attempt. A multi-scheduler selector
        re-resolves per connect — the fleet ring moves at runtime, and a
        reconnect after an owner move must land on the new owner, not the
        member the conductor was born under. A WRONG_SHARD owner hint
        (when fresher than our ring) wins outright."""
        sched = self.scheduler
        if not hasattr(sched, "for_task"):
            return sched  # plain single-scheduler client
        if self._owner_hint and hasattr(sched, "client_for"):
            hint, self._owner_hint = self._owner_hint, ""
            # never chase a hint into a member we just failed against:
            # during a failover the whole fleet keeps naming the dead
            # owner until its lease expires
            if hint not in self._avoid_addrs:
                try:
                    client = sched.client_for(hint)
                    self._last_sched_addr = hint
                    return client
                except Exception as e:
                    logger.warning(
                        "wrong-shard owner hint %s undialable: %s", hint, e
                    )
        if hasattr(sched, "resolve_for_task"):
            addr, client = sched.resolve_for_task(
                self.task_id, avoid=self._avoid_addrs
            )
            self._last_sched_addr = addr
            return client
        return sched.for_task(self.task_id)

    def _stream_loop(self) -> None:
        """Own thread: consumes scheduler responses, queues decisions for
        the run loop (reference receivePeerPacket :659)."""
        requests = self._requests  # bound once, before any later swap
        try:
            FP_ANNOUNCE_STREAM()
            client = self._resolve_scheduler()
            # the peer_task span is this thread's context for the
            # AnnouncePeer call, so the scheduler's rpc.AnnouncePeer span
            # (and its scheduling children) join the download's trace
            with tracing.use_span(getattr(self, "_span", None)):
                responses = client.AnnouncePeer(self._req_iter(requests))
            for resp in responses:
                which = resp.WhichOneof("response")
                self._decisions.put((which, getattr(resp, which)))
        except Exception as e:  # stream teardown or scheduler gone
            if not self._done.is_set():
                logger.warning("announce stream for %s ended: %s", self.peer_id, e)
                self._decisions.put(("stream_error", str(e)))

    # ------------------------------------------------------------------
    # main run loop
    # ------------------------------------------------------------------
    def _run(self) -> None:
        with tracing.use_span(getattr(self, "_span", None)):
            self._run_traced()

    def _register_request(self) -> "scheduler_pb2.RegisterPeerRequest":
        """The registration message — shared by first registration and
        the announce-stream reconnect re-register, so the two can never
        drift apart field by field."""
        return scheduler_pb2.RegisterPeerRequest(
            task_id=self.task_id,
            peer_id=self.peer_id,
            url=self.url,
            url_meta=self.url_meta,
            task_type=self.task_type,
            need_back_to_source=self.need_back_to_source,
        )

    def _run_traced(self) -> None:
        try:
            self._send(register_peer=self._register_request())
            self._drive()
        except Exception as e:
            logger.exception("conductor %s failed", self.peer_id)
            self._fail(str(e))
        finally:
            self._requests.put(None)

    def _drive(self) -> None:
        while not self._done.is_set():
            try:
                which, body = self._decisions.get(timeout=self.opts.schedule_timeout)
                EV_PEER_DECISION(peer_id=self.peer_id, decision=which)
                if which != "stream_error":
                    self._avoid_addrs.clear()  # the member we're on works
                    # a later failover gets its own retry window AND its
                    # own backoff ramp — the budget bounds one outage,
                    # not the task's lifetime
                    self._wrong_shard_deadline = 0.0
                    self._wrong_shard_retries = 0
                    if self._outage_started:
                        # announce plane recovered: the blackout is the
                        # gap from first stream error to this decision,
                        # and the decision's KIND says whether the
                        # failover was lossless — a parent assignment
                        # means the successor recognized this peer, a
                        # back-to-source means its swarm state was lost
                        fleet.BLACKOUT_MS.observe(
                            (time.monotonic() - self._outage_started) * 1e3
                        )
                        self._outage_started = 0.0
                        kind = (
                            "recognized"
                            if which in ("normal_task", "small_task")
                            else "fallback"
                            if which == "need_back_to_source"
                            else "other"
                        )
                        fleet.FAILOVER_RESUME_TOTAL.labels(kind).inc()
                elif not self._outage_started:
                    self._outage_started = time.monotonic()
            except queue.Empty:
                EV_PEER_DECISION(peer_id=self.peer_id, decision="schedule_timeout")
                # No decision in time: back-source if allowed, else fail
                # (reference needBackSource fallback :485-523).
                if self.opts.disable_back_source:
                    self._fail("schedule timeout and back-to-source disabled")
                else:
                    self._back_to_source()
                return

            if which == "empty_task":
                self.ts.meta.piece_length = self.ts.meta.piece_length or 1
                if self._complete(0):
                    self._finish(piece_count=0)
                return
            if which == "tiny_task":
                content = body.content
                self.ts.meta.piece_length = max(len(content), 1)
                t0 = time.monotonic()
                pm = self.ts.write_piece(
                    0, 0, content, traffic_type=TRAFFIC_REMOTE_PEER,
                    cost_ns=int((time.monotonic() - t0) * 1e9),
                )
                self._piece_done(PieceResult(pm.number, pm.offset, pm.length, pm.digest, pm.traffic_type, pm.cost_ns, ""))
                if self._complete(len(content)):
                    self._finish(piece_count=1)
                return
            if which == "need_back_to_source":
                if self.opts.disable_back_source:
                    self._fail(f"need back-to-source but disabled: {body.description}")
                    return
                self._back_to_source()
                return
            if which in ("normal_task", "small_task"):
                parents = (
                    list(body.candidate_parents)
                    if which == "normal_task"
                    else [body.candidate_parent]
                )
                if self._download_from_parents(parents):
                    return
                continue  # rescheduled — wait for next decision
            if which == "stream_error":
                # WRONG_SHARD refusal (fleet sharding, docs/fleet.md):
                # this member isn't the task's ring owner — refresh
                # membership, re-pick, and resume with the same peer_id.
                # Its retry budget is time-based and separate from the
                # reconnect attempts: during a failover the whole fleet
                # may point at a still-leased dead owner until the lease
                # expires, and those refusals must not burn the budget
                # that guards against a genuinely broken scheduler.
                ws = fleet.parse_wrong_shard(str(body))
                if ws is not None and self._wrong_shard_repick(*ws):
                    continue
                if ws is None and self._last_sched_addr:
                    # a wire-dead member, not a refusal: route the next
                    # resolve past it (its cached channel can't raise at
                    # resolve time, only here)
                    self._avoid_addrs.add(self._last_sched_addr)
                # resilience: re-open the stream and re-register before
                # giving up — pieces already on disk are resumed by
                # _download_from_parents, and the scheduler re-dispatches
                # a known peer_id by its current state, so a scheduler
                # restart costs a reconnect, not the whole peer task
                if self._reconnect_stream(str(body)):
                    continue
                if self.opts.disable_back_source:
                    self._fail(f"announce stream error: {body}")
                else:
                    self._back_to_source()
                return

    # ------------------------------------------------------------------
    def _restart_stream(self, tag: str) -> None:
        """Swap in a fresh request queue + stream thread and re-register
        with the SAME peer_id (shared by reconnect and wrong-shard
        re-pick so the two resume paths can never drift). The old
        stream's feeder is released first — gRPC's sender thread may
        still be blocked on the old queue."""
        self._requests.put(None)
        self._requests = queue.Queue()
        self._stream_thread = threading.Thread(
            target=self._stream_loop,
            name=f"daemon.announce-{self.peer_id[:8]}-{tag}",
            daemon=True,
        )
        self._stream_thread.start()
        self._send(register_peer=self._register_request())

    def _reconnect_stream(self, cause: str) -> bool:
        """Announce-stream resume: jittered wait, fresh request queue, a
        new stream thread, and a re-register carrying the same peer_id.
        False once the attempt budget is spent (callers then run the old
        fail/back-to-source path)."""
        if self._stream_reconnects >= self.opts.stream_reconnect_attempts:
            return False
        self._stream_reconnects += 1
        attempt = self._stream_reconnects
        EV_ANNOUNCE_RECONNECT(
            peer_id=self.peer_id, attempt=attempt, cause=cause[:200]
        )
        logger.warning(
            "announce stream for %s reconnecting (attempt %d/%d): %s",
            self.peer_id, attempt, self.opts.stream_reconnect_attempts, cause,
        )
        time.sleep(
            resilience.full_jitter_backoff(
                attempt - 1, base_s=self.opts.stream_reconnect_backoff, cap_s=2.0
            )
        )
        self._restart_stream(f"r{attempt}")
        return True

    def _wrong_shard_repick(self, owner: str, ring_version: int) -> bool:
        """WRONG_SHARD retry: refresh membership, detect staleness via
        the ring version, adopt the refuser's owner hint when our ring
        did NOT move (the refusal came from a fresher view than ours),
        and resume the stream on the re-picked member. Time-bounded, not
        attempt-bounded — see the _drive caller."""
        now = time.monotonic()
        if self._wrong_shard_deadline == 0.0:
            self._wrong_shard_deadline = now + self.opts.wrong_shard_retry_window
        if now >= self._wrong_shard_deadline:
            logger.warning(
                "wrong-shard retries for %s exhausted after %.1fs",
                self.peer_id, self.opts.wrong_shard_retry_window,
            )
            return False
        self._wrong_shard_retries += 1
        fleet.WRONG_SHARD_TOTAL.labels("daemon").inc()
        sched = self.scheduler
        refreshed = False
        if hasattr(sched, "refresh_membership"):
            refreshed = sched.refresh_membership()
        if not refreshed and owner and owner not in self._avoid_addrs:
            # our ring didn't move: the refuser knows something our
            # membership feed hasn't delivered yet — believe its hint
            # (unless it names a member we've already failed against:
            # then the hint is the still-leased corpse, and the right
            # move is to keep riding the retry window until it expires)
            self._owner_hint = owner
        EV_WRONG_SHARD_REPICK(
            peer_id=self.peer_id,
            owner=owner,
            ring_version=ring_version,
            attempt=self._wrong_shard_retries,
            ring_refreshed=refreshed,
        )
        time.sleep(
            resilience.full_jitter_backoff(
                min(self._wrong_shard_retries - 1, 4),
                base_s=self.opts.wrong_shard_backoff,
                cap_s=1.0,
            )
        )
        self._restart_stream(f"ws{self._wrong_shard_retries}")
        return True

    # ------------------------------------------------------------------
    def _back_to_source(self) -> None:
        M.BACK_TO_SOURCE_TOTAL.inc()
        EV_PEER_BACK_TO_SOURCE(peer_id=self.peer_id, task_id=self.task_id)
        if getattr(self, "_span", None) is not None:
            self._span.event("back_to_source")
        self._send(
            download_peer_back_to_source_started=scheduler_pb2.DownloadPeerBackToSourceStartedRequest(
                description="falling back to origin"
            )
        )
        try:
            # UrlMeta.range (dfget --range): the task IS that slice of
            # the origin object (the range is baked into the task id, so
            # P2P parents already hold sliced content; only the origin
            # fetch needs the offset applied)
            r_off, r_len = parse_byte_range(self.url_meta.range)
            n = self.pm.download_source(
                self.ts,
                self.url,
                headers=self.headers,
                on_piece=self._piece_done,
                offset=r_off,
                length=r_len,
                expected_digest=self.url_meta.digest,
            )
        except Exception as e:
            self._fail(f"back-to-source failed: {e}")
            return
        self._finish(piece_count=len(self.ts.meta.pieces), content_length=n)

    # ------------------------------------------------------------------
    def _download_from_parents(self, candidates) -> bool:
        """Pull all pieces from candidate parents; True when the task
        finished (success or failure), False to wait for a reschedule."""
        # adopt task geometry from the first parent that knows it — the
        # task's piece grid was fixed by whoever wrote the first piece, so
        # an advertised piece_length overrides the local config default
        # (which only governs this peer's own back-to-source writes)
        content_length = self.ts.meta.content_length
        piece_length = self.ts.meta.piece_length
        for c in candidates:
            if c.task_content_length > 0 and content_length < 0:
                content_length = c.task_content_length
            if c.task_piece_length > 0 and not self.ts.meta.pieces:
                piece_length = c.task_piece_length
        if content_length < 0 or not piece_length:
            # ask a parent daemon directly for the piece inventory
            # (reference piece-metadata sync between daemons,
            # peertask_piecetask_synchronizer.go)
            content_length, piece_length = self._fetch_task_geometry(
                candidates, content_length, piece_length
            )
        if content_length < 0 or not piece_length:
            self._reschedule([c.peer_id for c in candidates], "parents lack task metadata")
            return False
        self.ts.meta.content_length = content_length
        self.ts.meta.piece_length = piece_length

        parents = [
            ParentInfo(
                peer_id=c.peer_id,
                upload_addr=f"{c.host.ip}:{c.host.download_port}",
                finished_pieces=set(c.finished_pieces),
            )
            for c in candidates
            if c.peer_id not in self._blocked_parents
        ]
        if not parents:
            self._reschedule([], "all candidate parents blocked")
            return False

        # live piece-metadata sync with each parent daemon (reference
        # peertask_piecetask_synchronizer.go): keeps finished_pieces
        # fresh while in-progress parents keep downloading, so the
        # dispatcher stops guessing
        daemon_addrs = {
            c.peer_id: f"{c.host.ip}:{c.host.port}"
            for c in candidates
            if c.host.port
        }
        total_pieces = len(piece_ranges(content_length, piece_length))
        synchronizer = PieceTaskSynchronizer(self.task_id, self.peer_id)
        for p in parents:
            if len(p.finished_pieces) >= total_pieces:
                continue  # completed parent: the snapshot is already final
            addr = daemon_addrs.get(p.peer_id)
            if addr:
                synchronizer.watch(p, addr)

        self._send(download_peer_started=scheduler_pb2.DownloadPeerStartedRequest())
        dispatcher = PieceDispatcher()
        todo = [
            pr for pr in piece_ranges(content_length, piece_length)
            if pr.number not in self.ts.meta.pieces
        ]
        # account pieces already on disk (resume)
        with self._lock:
            self._completed = sum(p.length for p in self.ts.meta.pieces.values())

        failed: list[PieceRange] = []
        lock = threading.Lock()

        def work(pr: PieceRange) -> None:
            last_err: Exception | None = None
            failed_here: set[str] = set()
            hard_failures = 0
            # one wait budget per parent — a stalled parent exhausting its
            # deadline must not instantly hard-fail the other parents'
            # optimistic probes
            wait_deadlines: dict[str, float] = {}
            while hard_failures < self.opts.piece_retry:
                with lock:
                    live = [p for p in parents if p.peer_id not in self._blocked_parents]
                parent = dispatcher.pick(live, pr.number, exclude=failed_here)
                if parent is None:
                    break
                try:
                    result = self.pm.download_piece_from_parent(
                        self.ts, parent, pr, self.peer_id
                    )
                    with lock:
                        self._parent_failures[parent.peer_id] = 0
                    self._piece_done(result)
                    return
                except PieceDownloadError as e:
                    last_err = e
                    if e.not_found and pr.number not in parent.finished_pieces:
                        # optimistic probe of an in-progress parent that
                        # never claimed the piece — wait for it to appear
                        # on its own deadline, don't penalize the parent
                        # or burn the hard-failure retry budget
                        now = time.monotonic()
                        deadline = wait_deadlines.setdefault(
                            parent.peer_id, now + self.opts.wait_piece_timeout
                        )
                        if now < deadline:
                            with PH_PARENT_WAIT:
                                time.sleep(self.opts.not_found_backoff)
                            continue
                        # waited out the piece — fall through as a hard
                        # failure so the task reschedules instead of
                        # spinning forever on a stalled parent
                    # hard failure — including a 404 on a piece the parent
                    # *advertised*: its inventory lies (evicted piece), so
                    # deprioritize it or it wins every retry on EWMA weight
                    hard_failures += 1
                    failed_here.add(parent.peer_id)
                    EV_PIECE_FAILED(
                        peer_id=self.peer_id,
                        piece=pr.number,
                        parent_id=parent.peer_id,
                        error=str(e),
                    )
                    self._send(
                        download_piece_failed=scheduler_pb2.DownloadPieceFailedRequest(
                            piece_number=pr.number, parent_id=parent.peer_id, temporary=True
                        )
                    )
                    # block only after repeated hard failures — one transient
                    # timeout must not knock the parent out of the swarm
                    with lock:
                        n = self._parent_failures.get(parent.peer_id, 0) + 1
                        self._parent_failures[parent.peer_id] = n
                        if n >= self.opts.parent_fail_limit:
                            self._blocked_parents.add(parent.peer_id)
                            EV_PARENT_BLOCKED(
                                peer_id=self.peer_id,
                                parent_id=parent.peer_id,
                                failures=n,
                            )
            logger.warning("piece %d failed from all parents: %s", pr.number, last_err)
            with lock:
                failed.append(pr)

        try:
            with ThreadPoolExecutor(max_workers=self.opts.piece_workers) as pool:
                list(pool.map(work, todo))
        finally:
            synchronizer.stop()
            # the piece fetches rode the shared transfer pool's
            # keep-alive connections; this task is done with these
            # parents, so let the pool retire the idle sockets (a
            # 10k-parent swarm must not pin one fd per parent forever)
            downloader.release_parents(p.upload_addr for p in parents)

        if not failed:
            # _complete failure is terminal (pinned-content mismatch),
            # not reschedulable — fresh parents would feed the same task
            if self._complete(content_length):
                self._finish(piece_count=len(self.ts.meta.pieces), content_length=content_length)
            return True

        # some pieces failed everywhere → reschedule with blocklist;
        # scheduler may answer with fresh parents or back-to-source
        self._reschedule(sorted(self._blocked_parents), f"{len(failed)} pieces failed")
        return False

    def _fetch_task_geometry(
        self, candidates, content_length: int, piece_length: int
    ) -> tuple[int, int]:
        """GetPieceTasks against candidate parents' daemon gRPC ports to
        learn (content_length, piece_length)."""
        for c in candidates:
            if not c.host.port:
                continue
            try:
                addr = f"{c.host.ip}:{c.host.port}"
                channel = glue.dial(addr, retries=1)
                try:
                    # target=addr: each parent gets its own breaker —
                    # one dead parent must not fail-fast the healthy ones
                    parent = glue.ServiceClient(
                        channel, glue.DFDAEMON_SERVICE, target=addr
                    )
                    packet = parent.GetPieceTasks(
                        dfdaemon_pb2.PieceTaskRequest(
                            task_id=self.task_id,
                            src_peer_id=self.peer_id,
                            dst_peer_id=c.peer_id,
                            limit=1,
                        )
                    )
                finally:
                    channel.close()
            except Exception as e:
                logger.debug("GetPieceTasks from %s failed: %s", c.peer_id, e)
                continue
            if packet.content_length >= 0 and packet.piece_infos:
                if content_length < 0:
                    content_length = packet.content_length
                if not piece_length:
                    piece_length = packet.piece_infos[0].length
                return content_length, piece_length
        return content_length, piece_length

    def _reschedule(self, blocked: list[str], description: str) -> None:
        EV_RESCHEDULE(
            peer_id=self.peer_id, blocked=list(blocked), reason=description
        )
        self._send(
            reschedule=scheduler_pb2.RescheduleRequest(
                blocked_parent_ids=blocked, description=description
            )
        )

    # ------------------------------------------------------------------
    def _piece_done(self, r: PieceResult) -> None:
        EV_PIECE_DONE(
            peer_id=self.peer_id,
            piece=r.number,
            parent_id=r.parent_id,
            length=r.length,
            traffic=r.traffic_type,
            cost_ms=round(r.cost_ns / 1e6, 3),
        )
        with self._lock:
            self._completed += r.length
        self._send(
            download_piece_finished=scheduler_pb2.DownloadPieceFinishedRequest(
                piece=common_pb2.PieceInfo(
                    number=r.number,
                    parent_id=r.parent_id,
                    offset=r.offset,
                    length=r.length,
                    digest=r.digest,
                    traffic_type=r.traffic_type,
                    cost_ns=r.cost_ns,
                    created_at_ns=time.time_ns(),
                )
            )
        )
        self._publish()

    def _complete(self, content_length: int) -> bool:
        """mark_done with the digest pin applied; False = verification
        failed and the task was failed (the one mismatch-handling site
        for every completion path)."""
        try:
            self.ts.mark_done(content_length, expected_digest=self.url_meta.digest)
        except Exception as e:
            self._fail(str(e))
            return False
        return True

    def _finish(self, piece_count: int, content_length: int | None = None) -> None:
        self.ts.busy = False
        # Whole-task integrity (UrlMeta.digest) is enforced INSIDE
        # TaskStorage.mark_done before `done` ever flips, so every
        # completion path races nothing: a reuse lookup can only see a
        # verified task. The stream frontend hands out pieces as they
        # arrive by design; its guarantee is that no COMPLETED task
        # (reuse index, parents serving children, dfget success) ever
        # carries mismatching content.
        if getattr(self, "_span", None) is not None:
            self._span.set(piece_count=piece_count).end("ok")
        self._release_shaper()
        cost_ns = int((time.monotonic() - self._started_at) * 1e9)
        EV_PEER_FINISHED(
            peer_id=self.peer_id,
            task_id=self.task_id,
            pieces=piece_count,
            cost_ms=round(cost_ns / 1e6, 3),
        )
        self._send(
            download_peer_finished=scheduler_pb2.DownloadPeerFinishedRequest(
                content_length=(
                    content_length
                    if content_length is not None
                    else max(self.ts.meta.content_length, 0)
                ),
                piece_count=piece_count,
                cost_ns=cost_ns,
            )
        )
        self._drain_stream()
        self._done.set()
        self._publish()
        if self.on_done:
            self.on_done(self)

    def _release_shaper(self) -> None:
        shaper = getattr(self.pm, "shaper", None)
        if shaper is not None:
            shaper.release(self.task_id)

    def _fail(self, description: str) -> None:
        self.ts.busy = False
        if getattr(self, "_span", None) is not None:
            self._span.set(error=description).end("error")
        self._release_shaper()
        M.TASK_FAILURE_TOTAL.inc()
        EV_PEER_FAILED(
            peer_id=self.peer_id, task_id=self.task_id, error=description
        )
        self._error = description
        self._send(
            download_peer_failed=scheduler_pb2.DownloadPeerFailedRequest(
                description=description
            )
        )
        self._drain_stream()
        self._done.set()
        self._publish()
        if self.on_done:
            self.on_done(self)

    def _drain_stream(self) -> None:
        """Close the request side and wait for the server to close the
        response side — the server handles requests in order, so when the
        stream ends the final peer event (and its Download record) has
        been processed."""
        self._requests.put(None)
        if self._stream_thread is not None and self._stream_thread is not threading.current_thread():
            self._stream_thread.join(timeout=5.0)
