"""Peer daemon assembly: wires storage, piece pipeline, upload server,
gRPC surface, announcer, prober, and GC into one process.

Role parity: reference client/daemon/daemon.go:86-899 (assembly),
client/daemon/announcer/announcer.go:45-337 (host announce),
client/daemon/networktopology/network_topology.go:39-203 (prober),
client/daemon/gc/gc.go (storage GC runner).
"""

from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from dragonfly2_tpu.rpc import gen  # noqa: F401
import common_pb2  # noqa: E402
import scheduler_pb2  # noqa: E402

from dragonfly2_tpu.rpc import glue
from dragonfly2_tpu.client import hostinfo
from dragonfly2_tpu.client.conductor import ConductorOptions
from dragonfly2_tpu.client.peertask import TaskManager
from dragonfly2_tpu.client.piece_manager import PieceManager
from dragonfly2_tpu.client.rpcserver import SERVICE_NAME as DFDAEMON_SERVICE, DfdaemonService
from dragonfly2_tpu.client.storage import StorageManager
from dragonfly2_tpu.client.uploader import UploadServer
from dragonfly2_tpu.utils import dflog
from dragonfly2_tpu.utils.gc import GC, GCTask
from dragonfly2_tpu.utils.idgen import host_id_v2

logger = dflog.get("client.daemon")

from dragonfly2_tpu.rpc.glue import SCHEDULER_SERVICE


@dataclass
class DaemonConfig:
    data_dir: str
    scheduler_address: str
    hostname: str = field(default_factory=socket.gethostname)
    ip: str = "127.0.0.1"
    listen: str = "127.0.0.1:0"  # daemon gRPC
    # also serve the dfdaemon gRPC on this unix socket (local CLI path,
    # reference pkg/rpc/mux.go); empty = TCP only
    unix_socket: str = ""
    # manager to fetch the scheduler list from (dynconfig-fed, searcher-
    # scoped); empty = static scheduler_address only
    manager_address: str = ""
    dynconfig_interval: float = 300.0
    # shared KV for scheduler-fleet membership (scheduler/fleet.py,
    # docs/fleet.md): when set, the daemon follows the fleet's leased
    # member set directly — the ring reconciles within one poll of a
    # join/leave/death instead of waiting out a dynconfig interval
    kv_address: str = ""
    kv_secret: str = ""
    fleet_poll_interval: float = 1.0
    # client-side roots (and optional mTLS pair) for the manager dial —
    # same shape as the scheduler/trainer manager clients
    manager_tls_ca_file: str = ""
    manager_tls_server_name: str = ""
    manager_tls_client_cert_file: str = ""
    manager_tls_client_key_file: str = ""
    upload_host: str = "127.0.0.1"
    upload_port: int = 0
    host_type: str = "normal"  # "normal" | "super" (seed peer)
    location: str = ""
    idc: str = ""
    storage_max_bytes: int = 0
    gc_interval: float = 60.0
    announce_interval: float = 30.0
    probe_interval: float = 0.0  # 0 = prober disabled
    piece_workers: int = 4
    piece_length: int = 0  # 0 = derive from content length
    schedule_timeout: float = 10.0
    concurrent_upload_limit: int = 50
    scheduler_cluster_id: int = 1
    # HTTP proxy (registry acceleration): -1 = disabled, 0 = ephemeral
    # port; rules are transport.ProxyRule instances or kwargs dicts
    # ({"regex": ..., "direct": ..., "use_https": ..., "redirect": ...})
    proxy_port: int = -1
    proxy_host: str = "127.0.0.1"  # bind address (0.0.0.0 in containers)
    proxy_rules: list = field(default_factory=list)
    registry_mirror: str = ""
    # HTTPS interception: spoof per-host certs signed by a local CA
    # persisted under data_dir/ca (clients trust ca.crt once); hosts
    # matching proxy_mitm_hosts regexes are intercepted (empty = all)
    proxy_mitm: bool = False
    proxy_mitm_hosts: list = field(default_factory=list)
    object_storage_host: str = "127.0.0.1"  # bind address (0.0.0.0 in containers)
    # object-storage gateway: -1 = disabled, 0 = ephemeral port; the
    # backend dir is the bucket store (shared across daemons — NFS/S3
    # mount in production, a shared tmp dir in tests)
    object_storage_port: int = -1
    object_storage_dir: str = ""
    # host stat collection (reference announcer.go:158-303). Overrides
    # replace sampled values — the A/B harness and tests use them to model
    # synthetic hosts; keys are dotted stat paths ("cpu.percent": 90.0)
    collect_host_stats: bool = True
    host_stats_override: dict = field(default_factory=dict)
    # synthetic per-piece upload latency (A/B harness models slow hosts)
    upload_delay_s: float = 0.0
    # extra serving latency on piece 0 only (benign cold-piece pattern:
    # TCP slow start / cold cache — the GRU bad-node A/B scenario)
    upload_cold_piece_delay_s: float = 0.0
    # synthetic receive-side per-piece latency, inside the measured cost
    # window (fault injection: a loaded host's own downloads slow down)
    download_delay_s: float = 0.0
    # global upload bandwidth budget in bytes/s shared by all child peers
    # (reference upload totalRateLimit); 0 = unlimited
    upload_rate_limit: float = 0.0
    # zero-copy data plane (docs/data-plane.md): serve piece bodies via
    # os.sendfile from the piece store (False = buffered fallback, same
    # event loop — the bench's comparison arm)
    upload_sendfile: bool = True
    # content-addressed cross-task piece dedup in the store (same
    # digest → one physical copy, refcounted); DF_PIECE_DEDUP=0 is the
    # process-wide kill switch
    piece_dedup: bool = True
    # bound on concurrent P2P stream tasks through the proxy/gateway
    # transport; past it requests shed to direct fetches. 0 = unbounded
    p2p_max_inflight: int = 512
    # Prometheus /metrics endpoint: -1 = disabled
    metrics_port: int = -1
    metrics_host: str = "127.0.0.1"
    # cluster telemetry push cadence over the manager channel
    # (utils/telemetry.py, docs/telemetry.md); <= 0 disables
    telemetry_interval: float = 15.0
    # global download budget in bytes/s shared across tasks (cross-task
    # sampling traffic shaper, reference traffic_shaper.go); 0 = off
    total_download_rate: float = 0.0
    # client-side root (and optional mTLS client pair) for schedulers
    scheduler_tls_ca_file: str = ""
    scheduler_tls_server_name: str = ""
    scheduler_tls_client_cert_file: str = ""
    scheduler_tls_client_key_file: str = ""


def _apply_stat_overrides(stats: "hostinfo.HostStats", overrides: dict) -> None:
    """Apply dotted-path overrides onto a HostStats, raising on unknown
    paths — a typo silently keeping the sampled value would poison every
    announced record (round-2 ADVICE c). Shared by the constructor's
    fail-fast validation and the per-announce application."""
    for path, value in overrides.items():
        group, _, attr = path.partition(".")
        target = getattr(stats, group, None)
        if target is None or not attr or not hasattr(target, attr):
            raise ValueError(
                f"host_stats_override: unknown stat path {path!r}"
                f" (expected '<group>.<field>' on HostStats)"
            )
        setattr(target, attr, value)


class Daemon:
    """One peer host: piece store + upload server + dfdaemon gRPC +
    scheduler announce/probe loops."""

    def __init__(self, config: DaemonConfig):
        self.cfg = config
        # fail fast on typo'd stat paths — don't wait for the first
        # announce to discover a bad config
        _apply_stat_overrides(hostinfo.HostStats(), config.host_stats_override)
        self.host_id = host_id_v2(config.ip, config.hostname)
        self.storage = StorageManager(
            config.data_dir,
            max_bytes=config.storage_max_bytes,
            dedup=config.piece_dedup,
        )
        self.upload = UploadServer(
            self.storage,
            host=config.upload_host,
            port=config.upload_port,
            delay_s=config.upload_delay_s,
            cold_piece_delay_s=config.upload_cold_piece_delay_s,
            rate_limit_bps=config.upload_rate_limit,
            use_sendfile=config.upload_sendfile,
        )
        self._selector = None
        self._server = None
        self.port = 0
        self._stop = threading.Event()
        self._dynconfig = None
        self._manager_channel = None
        self._fleet_kv = None
        self._fleet_watcher = None
        self._telemetry_reporter = None
        self._threads: list[threading.Thread] = []
        self.gc = GC()
        self.task_manager: TaskManager | None = None
        self.proxy = None
        self.object_gateway = None
        # constructed here, not in start(): probe_once() is a public
        # single-round entry point and must work without a running
        # probe loop (per-host echo budget tied to the probe cadence —
        # concurrent probes of one host within a round reuse the cached
        # RTT instead of multiplying echoes)
        from dragonfly2_tpu.utils.ping import Pinger

        self._pinger = Pinger(
            min_interval=min(1.0, config.probe_interval / 2)
            if config.probe_interval > 0
            else 1.0
        )

    # ------------------------------------------------------------------
    def _make_scheduler_dynconfig(self):
        """Searcher-scoped DaemonDynconfig over the manager, with a disk
        cache fallback under data_dir (utils/dynconfig.DaemonDynconfig;
        reference client/config/dynconfig_manager.go)."""
        from dragonfly2_tpu.manager.service import SERVICE_NAME as MANAGER_SERVICE
        from dragonfly2_tpu.utils.dynconfig import DaemonDynconfig

        self._manager_channel = glue.dial(
            self.cfg.manager_address,
            **glue.dial_tls_args(
                self.cfg.manager_tls_ca_file,
                self.cfg.manager_tls_server_name,
                self.cfg.manager_tls_client_cert_file,
                self.cfg.manager_tls_client_key_file,
            ),
        )
        return DaemonDynconfig(
            glue.ServiceClient(self._manager_channel, MANAGER_SERVICE),
            cache_path=Path(self.cfg.data_dir) / "dynconfig.json",
            refresh_interval=self.cfg.dynconfig_interval,
            hostname=self.cfg.hostname,
            ip=self.cfg.ip,
            idc=self.cfg.idc,
            location=self.cfg.location,
        )

    def start(self) -> None:
        self.upload.start()
        addresses = [a for a in self.cfg.scheduler_address.split(",") if a.strip()]
        if self.cfg.manager_address:
            # dynconfig-fed scheduler list: the manager's view of the
            # cluster (searcher-scoped to this daemon's location) is the
            # source of truth, refreshed on an interval; the static list
            # is the bootstrap/fallback (reference client dynconfig)
            self._dynconfig = self._make_scheduler_dynconfig()
            fetched = self._dynconfig.scheduler_addresses()
            if fetched:
                addresses = fetched
            elif not addresses:
                # surface the real cause: get() swallows fetch failures
                # into {}, which reads as "manager has no schedulers" —
                # an unreachable/TLS-mismatched manager is a different bug
                try:
                    self._dynconfig.fetch_once()
                except Exception as e:
                    raise RuntimeError(
                        f"manager dynconfig fetch failed ({e}) and no static"
                        " scheduler_address fallback is configured"
                    ) from e
                raise RuntimeError(
                    "manager returned no schedulers and no static"
                    " scheduler_address fallback is configured"
                )
        self._selector = glue.SchedulerSelector(
            addresses,
            dial_kwargs=glue.dial_tls_args(
                self.cfg.scheduler_tls_ca_file,
                self.cfg.scheduler_tls_server_name,
                self.cfg.scheduler_tls_client_cert_file,
                self.cfg.scheduler_tls_client_key_file,
            ),
        )
        if self._dynconfig is not None:
            self._dynconfig.register(
                lambda data: self._selector.update_addresses(
                    self._dynconfig.addresses_of(data)
                )
            )
            self._dynconfig.start()
        if self.cfg.kv_address:
            # live fleet membership (docs/fleet.md): the leased member
            # set in the shared KV feeds the selector's ring, and the
            # watcher doubles as the WRONG_SHARD retry's pull-now source
            from dragonfly2_tpu.scheduler.fleet import FleetWatcher
            from dragonfly2_tpu.utils import kvstore

            self._fleet_kv = kvstore.RemoteKVStore(
                self.cfg.kv_address, secret=self.cfg.kv_secret
            )
            self._fleet_watcher = FleetWatcher(
                self._fleet_kv,
                self._selector.update_addresses,
                poll_interval=self.cfg.fleet_poll_interval,
            )
            self._selector.set_membership_source(self._fleet_watcher.read_members)
            # adopt whatever is leased right now; the static list stays
            # as bootstrap when no member has joined yet
            self._fleet_watcher.poll_once()
            self._fleet_watcher.start()
        # fail fast when no scheduler is reachable; NOT pinned — the
        # probe loop re-resolves the primary per round because dynconfig
        # membership changes can close any cached channel
        self._selector.primary()

        from dragonfly2_tpu.client.piece_manager import TrafficShaper

        self.shaper = TrafficShaper(self.cfg.total_download_rate)
        self.shaper.start()
        self.task_manager = TaskManager(
            host_id=self.host_id,
            storage=self.storage,
            scheduler_client=self._selector,
            piece_manager=PieceManager(
                concurrent_pieces=self.cfg.piece_workers,
                shaper=self.shaper,
                download_delay_s=self.cfg.download_delay_s,
            ),
            options=ConductorOptions(
                piece_workers=self.cfg.piece_workers,
                schedule_timeout=self.cfg.schedule_timeout,
                piece_length=self.cfg.piece_length,
            ),
            host_info_fn=self.host_info,
        )
        service = DfdaemonService(
            task_manager=self.task_manager,
            storage=self.storage,
            upload_addr=self.upload.address,
        )
        extra = []
        if self.cfg.unix_socket:
            # local CLIs (dfget/dfcache/dfstore) reach the daemon through
            # the socket without touching the TCP stack (reference
            # pkg/rpc/mux.go unix listener; dfget root.go:279 dials it)
            sock = Path(self.cfg.unix_socket)
            sock.parent.mkdir(parents=True, exist_ok=True)
            if sock.exists():
                # connect-before-unlink: only a DEAD socket is stale. A
                # spawn race must not unbind a healthy daemon and orphan
                # it on a deleted inode
                probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    probe.settimeout(1.0)
                    probe.connect(str(sock))
                    probe.close()
                    raise RuntimeError(
                        f"another daemon is serving {sock}; refusing to unbind it"
                    )
                except socket.timeout:
                    # a connect TIMEOUT is a live-but-stalled daemon (GC
                    # pause, loaded host) — unbinding it would orphan a
                    # healthy server on a deleted inode
                    probe.close()
                    raise RuntimeError(
                        f"a daemon appears to be serving {sock} (slow to"
                        " accept); refusing to unbind it"
                    )
                except (ConnectionRefusedError, FileNotFoundError, OSError):
                    probe.close()
                    try:
                        sock.unlink()  # stale socket from an unclean shutdown
                    except FileNotFoundError:
                        pass  # raced: its owner already removed it
            extra.append(f"unix:{sock}")
        # flight recorder: crash dumps on SIGTERM/fatal + the Diagnose
        # snapshot RPC on the daemon's gRPC plane
        from dragonfly2_tpu.rpc.diagnose import DiagnoseService
        from dragonfly2_tpu.utils import flight, profiling

        flight.install("daemon")
        # continuous profiler: always-on sampler + phase ledger
        profiling.install("daemon")
        flight.register_probe(
            "daemon.tasks",
            lambda: {"conductors": len(self.task_manager.conductors)},
        )
        self._server, self.port = glue.serve(
            {DFDAEMON_SERVICE: service, glue.DIAGNOSE_SERVICE: DiagnoseService()},
            address=self.cfg.listen,
            extra_addresses=extra,
        )
        from dragonfly2_tpu.utils.metrics import set_build_info

        set_build_info("daemon")
        if self._manager_channel is not None and self.cfg.telemetry_interval > 0:
            # cluster telemetry: the daemon's data-plane rates to the
            # manager over the dynconfig channel it already holds
            from dragonfly2_tpu.utils.telemetry import TelemetryReporter
            from dragonfly2_tpu.version import __version__

            def _sections():
                return {
                    "build": {"service": "daemon", "version": __version__},
                    "endpoints": {
                        "rpc": f"{self.cfg.ip}:{self.port}",
                        "metrics": getattr(self, "metrics_addr", "") or "",
                    },
                }

            self._telemetry_reporter = TelemetryReporter(
                glue.ServiceClient(self._manager_channel, glue.TELEMETRY_SERVICE),
                service="daemon",
                instance=f"{self.cfg.ip}:{self.port}",
                prefixes=("dragonfly_daemon_", "dragonfly_flow_"),
                interval=self.cfg.telemetry_interval,
                collect_sections=_sections,
            )
            self._telemetry_reporter.start()
        # announce before the proxy/gateway open for business: a gateway
        # PUT may AnnounceTask immediately, which requires a known host
        self.announce_host()

        if self.cfg.proxy_port >= 0:
            from dragonfly2_tpu.client.proxy import ProxyServer, RegistryMirror
            from dragonfly2_tpu.client.transport import P2PTransport, ProxyRule

            rules = [
                r if isinstance(r, ProxyRule) else ProxyRule(**r)
                for r in self.cfg.proxy_rules
            ]
            issuer = None
            if self.cfg.proxy_mitm:
                issuer = self._load_spoofing_issuer()
            self.proxy = ProxyServer(
                P2PTransport(
                    self.task_manager,
                    rules=rules,
                    max_inflight=self.cfg.p2p_max_inflight,
                ),
                mirror=RegistryMirror(self.cfg.registry_mirror),
                address=self.cfg.proxy_host,
                port=self.cfg.proxy_port,
                issuer=issuer,
                intercept=self.cfg.proxy_mitm_hosts or None,
            )
            self.proxy.start()

        if self.cfg.object_storage_port >= 0 and self.cfg.object_storage_dir:
            import re as _re

            from dragonfly2_tpu.client.objectstorage import ObjectStorageGateway
            from dragonfly2_tpu.client.transport import P2PTransport, ProxyRule
            from dragonfly2_tpu.manager.objectstorage import FSObjectStorage

            backend_root = str(self.cfg.object_storage_dir)
            backend = FSObjectStorage(backend_root)
            # gateway GETs always ride P2P: one rule covering the backend
            transport = P2PTransport(
                self.task_manager,
                rules=[ProxyRule(regex=_re.escape(f"file://{backend_root}"))],
            )
            self.object_gateway = ObjectStorageGateway(
                backend,
                transport=transport,
                importer=self._import_object,
                url_for=lambda bucket, key: f"file://{backend_root}/{bucket}/{key}",
                address=self.cfg.object_storage_host,
                port=self.cfg.object_storage_port,
            )
            self.object_gateway.start()

        if self.cfg.metrics_port >= 0:
            from dragonfly2_tpu.client import metrics  # noqa: F401
            from dragonfly2_tpu.utils.metrics import MetricsServer, default_registry

            self._metrics = MetricsServer(default_registry, host=self.cfg.metrics_host, port=self.cfg.metrics_port)
            # liveness on the scrape port (/healthz): the gRPC plane up
            self._metrics.register_health("dfdaemon", lambda: self._server is not None)
            self.metrics_addr = self._metrics.start()
            logger.info("daemon metrics on %s", self.metrics_addr)

        self._spawn(self._announce_loop, "announcer")
        if self.cfg.probe_interval > 0:
            self._spawn(self._probe_loop, "prober")
        if self.cfg.host_type == "super" and self._manager_channel is not None:
            # seed peers are manager-visible infrastructure: register and
            # keep alive so preheat targeting and the console's seed-peer
            # view reflect them (reference seed-peer manager registration;
            # normal daemons stay scheduler-only). Registration is
            # best-effort here — the keepalive loop re-registers, so a
            # transient manager outage never kills a booting daemon
            try:
                self._register_seed_peer()
            except Exception as e:
                logger.warning("initial seed-peer registration failed: %s", e)
            self._spawn(self._seed_keepalive_loop, "seed-keepalive")

        self.gc.add(
            GCTask(
                "storage",
                interval=self.cfg.gc_interval,
                timeout=30.0,
                runner=self.storage.reclaim,
            )
        )
        self.gc.start()
        logger.info(
            "daemon up: host=%s grpc=:%d upload=%s", self.host_id, self.port, self.upload.address
        )

    def stop(self) -> None:
        self._stop.set()
        if self._telemetry_reporter is not None:
            self._telemetry_reporter.stop()
        if self._fleet_watcher is not None:
            self._fleet_watcher.stop()
        if self._fleet_kv is not None:
            self._fleet_kv.close()
        if self._dynconfig is not None:
            self._dynconfig.stop()
        if self._manager_channel is not None:
            self._manager_channel.close()
        selector = getattr(self, "_selector", None)
        if selector is not None:
            for client in selector.all():
                try:
                    client.LeaveHost(
                        scheduler_pb2.LeaveHostRequest(host_id=self.host_id)
                    )
                except Exception as e:
                    # best-effort; TTL GC reaps the host eventually
                    logger.debug("LeaveHost on shutdown failed: %s", e)
        if getattr(self, "_metrics", None) is not None:
            self._metrics.stop()
        if getattr(self, "shaper", None) is not None:
            self.shaper.stop()
        self.gc.stop()
        if self.proxy is not None:
            self.proxy.stop()
        if self.object_gateway is not None:
            self.object_gateway.stop()
        if self._server is not None:
            self._server.stop(grace=1).wait()
        self.upload.stop()
        if getattr(self, "_selector", None) is not None:
            self._selector.close()

    def _import_object(self, url: str, data: bytes, digest: str = "") -> None:
        """Register object bytes as a completed local task so this daemon
        P2P-serves it without a backend fetch (the gateway's seed-on-write
        replication mode). The digest is part of the task id, so an
        overwrite seeds a fresh task instead of colliding with the old
        content's swarm."""
        import io

        from dragonfly2_tpu.utils import flows
        from dragonfly2_tpu.utils.idgen import URLMeta, task_id_v1

        task_id = task_id_v1(url, URLMeta(digest=digest))
        # seed-on-write tasks belong to the object plane: later uploads
        # of these pieces to child peers attribute there
        flows.set_task_plane(task_id, "object")
        if self.storage.find_completed_task(task_id) is not None:
            return
        self.task_manager.import_completed_task(
            task_id,
            url,
            io.BytesIO(data).read,
            len(data),
            piece_length=self.cfg.piece_length,
            task_type=common_pb2.TASK_TYPE_DFSTORE,
        )

    def _register_seed_peer(self) -> None:
        import manager_pb2  # noqa: E402 — flat proto import

        from dragonfly2_tpu.manager.service import SERVICE_NAME as MANAGER_SERVICE

        client = glue.ServiceClient(self._manager_channel, MANAGER_SERVICE)
        client.UpdateSeedPeer(
            manager_pb2.UpdateSeedPeerRequest(
                hostname=self.cfg.hostname,
                ip=self.cfg.ip,
                port=int(self.port),
                download_port=int(self.upload.port),
                type="super",
                idc=self.cfg.idc,
                location=self.cfg.location,
                seed_peer_cluster_id=self.cfg.scheduler_cluster_id,
            )
        )
        logger.info("registered as seed peer with manager")

    def _seed_keepalive_loop(self) -> None:
        # UpdateSeedPeer is an idempotent upsert stamping last_keepalive,
        # so re-registering IS the keepalive — and it self-heals when the
        # manager-side row vanished (DB recreated, operator delete),
        # which a bare UPDATE-style keepalive would silently miss
        while not self._stop.wait(self.cfg.announce_interval):
            try:
                self._register_seed_peer()
            except Exception as e:
                logger.warning("seed-peer keepalive failed: %s", e)

    def _spawn(self, fn, name: str) -> None:
        t = threading.Thread(target=fn, name=name, daemon=True)
        t.start()
        self._threads.append(t)

    # ------------------------------------------------------------------
    # host announce (reference client/daemon/announcer/announcer.go:158-303)
    # ------------------------------------------------------------------
    def host_stats(self) -> hostinfo.HostStats:
        """Sample live host stats, then apply configured overrides (the
        harness models synthetic hosts; production runs sample-only)."""
        if self.cfg.collect_host_stats:
            stats = hostinfo.collect(
                data_dir=self.cfg.data_dir,
                upload_ports=(self.upload.port, self.port),
            )
        else:
            stats = hostinfo.HostStats()
        _apply_stat_overrides(stats, self.cfg.host_stats_override)
        return stats

    def host_info(self) -> common_pb2.HostInfo:
        s = self.host_stats()
        return common_pb2.HostInfo(
            id=self.host_id,
            type=self.cfg.host_type,
            hostname=self.cfg.hostname,
            ip=self.cfg.ip,
            port=self.port,
            download_port=self.upload.port,
            os="linux",
            concurrent_upload_limit=self.cfg.concurrent_upload_limit,
            cpu=common_pb2.CpuStat(
                logical_count=s.cpu.logical_count,
                physical_count=s.cpu.physical_count,
                percent=s.cpu.percent,
                process_percent=s.cpu.process_percent,
            ),
            memory=common_pb2.MemoryStat(
                total=s.memory.total,
                available=s.memory.available,
                used=s.memory.used,
                used_percent=s.memory.used_percent,
                process_used_percent=s.memory.process_used_percent,
                free=s.memory.free,
            ),
            network=common_pb2.NetworkStat(
                tcp_connection_count=s.network.tcp_connection_count,
                upload_tcp_connection_count=s.network.upload_tcp_connection_count,
                location=self.cfg.location,
                idc=self.cfg.idc,
            ),
            disk=common_pb2.DiskStat(
                total=s.disk.total,
                free=s.disk.free,
                used=s.disk.used,
                used_percent=s.disk.used_percent,
                inodes_total=s.disk.inodes_total,
                inodes_used=s.disk.inodes_used,
                inodes_used_percent=s.disk.inodes_used_percent,
            ),
            scheduler_cluster_id=self.cfg.scheduler_cluster_id,
        )

    def _load_spoofing_issuer(self):
        """CA for HTTPS interception, persisted across restarts so
        clients only provision trust once (reference proxy CA cert
        config)."""
        import os

        from dragonfly2_tpu.utils.issuer import CertificateAuthority, SpoofingIssuer

        ca_dir = os.path.join(self.cfg.data_dir, "ca")
        crt, key = os.path.join(ca_dir, "ca.crt"), os.path.join(ca_dir, "ca.key")
        if os.path.exists(crt) and os.path.exists(key):
            with open(crt, "rb") as f1, open(key, "rb") as f2:
                ca = CertificateAuthority.load(f1.read(), f2.read())
        else:
            os.makedirs(ca_dir, exist_ok=True)
            ca = CertificateAuthority(f"dragonfly2 proxy CA ({self.cfg.hostname})")
            with open(crt, "wb") as f:
                f.write(ca.cert_pem)
            # the CA key must never be world-readable, not even between
            # create and chmod — open with the final mode
            fd = os.open(key, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(ca.key_pem)
        logger.info("proxy MITM enabled; CA at %s", crt)
        return SpoofingIssuer(ca)

    def announce_host(self) -> None:
        # every scheduler must know this host: tasks pin to different
        # schedulers by consistent hash, and any of them may hand this
        # host out as a candidate parent
        info = self.host_info()
        for client in self._selector.all():
            try:
                client.AnnounceHost(scheduler_pb2.AnnounceHostRequest(host=info))
            except Exception as e:
                # one dead scheduler must not starve the others of
                # announcements — they'd expire this host and stop
                # offering it as a parent
                logger.warning("announce to one scheduler failed: %s", e)

    def _announce_loop(self) -> None:
        while not self._stop.wait(self.cfg.announce_interval):
            try:
                self.announce_host()
            except Exception as e:
                logger.warning("announce host failed: %s", e)

    # ------------------------------------------------------------------
    # prober (reference client/daemon/networktopology/network_topology.go:71-203)
    #
    # RTT measurement is ICMP echo first (reference pkg/net/ping/ping.go:
    # privileged pinger, 1 echo, 1s timeout) with a per-host rate limit,
    # falling back to a TCP connect round-trip to the target's upload
    # port when ICMP is unavailable (no CAP_NET_RAW and no unprivileged
    # ping range) — same latency signal, needs an open port instead of
    # privileges. utils/ping.py implements both ICMP modes.
    # ------------------------------------------------------------------
    def probe_once(self) -> int:
        """One SyncProbes round; returns number of hosts probed. The
        request side is queue-fed so the response iterator is only read
        from this thread (reading it from inside the request generator
        races gRPC's send loop)."""
        import queue as _queue

        me = self.host_info()
        q: "_queue.Queue[scheduler_pb2.SyncProbesRequest | None]" = _queue.Queue()
        q.put(
            scheduler_pb2.SyncProbesRequest(
                host=me, probe_started=scheduler_pb2.ProbeStartedRequest()
            )
        )
        responses = self._selector.primary().SyncProbes(iter(q.get, None))
        probed = 0
        try:
            resp = next(responses, None)
            if resp is not None and resp.hosts:
                probes, failed = [], []
                for ph in resp.hosts:
                    port = ph.host.download_port or ph.host.port
                    rtt = self._pinger.rtt(
                        ph.host.ip,
                        fallback=lambda ip, p=port: self._tcp_ping(ip, p),
                    )
                    if rtt is None:
                        failed.append(
                            scheduler_pb2.FailedProbeResult(
                                host_id=ph.host.id, description="unreachable"
                            )
                        )
                    else:
                        probes.append(
                            scheduler_pb2.ProbeResult(
                                host_id=ph.host.id,
                                rtt_ns=int(rtt * 1e9),
                                created_at_ns=time.time_ns(),
                            )
                        )
                if probes:
                    q.put(
                        scheduler_pb2.SyncProbesRequest(
                            host=me,
                            probe_finished=scheduler_pb2.ProbeFinishedRequest(probes=probes),
                        )
                    )
                if failed:
                    q.put(
                        scheduler_pb2.SyncProbesRequest(
                            host=me,
                            probe_failed=scheduler_pb2.ProbeFailedRequest(probes=failed),
                        )
                    )
                probed = len(probes)
        finally:
            q.put(None)
            for _ in responses:  # drain until the server closes
                pass
        return probed

    @staticmethod
    def _tcp_ping(ip: str, port: int, timeout: float = 2.0) -> float | None:
        t0 = time.monotonic()
        try:
            with socket.create_connection((ip, port), timeout=timeout):
                return time.monotonic() - t0
        except OSError:
            return None

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.cfg.probe_interval):
            try:
                self.probe_once()
            except Exception as e:
                logger.warning("probe round failed: %s", e)


# ---------------------------------------------------------------------------
# `python -m dragonfly2_tpu.client.daemon` — the dfdaemon binary
# (reference cmd/dfdaemon; daemon assembly client/daemon/daemon.go:114,524)
# ---------------------------------------------------------------------------


class _DaemonRunAdapter:
    """Adapts Daemon.start/stop onto the runner's serve/stop contract."""

    def __init__(self, daemon: "Daemon"):
        self.daemon = daemon

    def serve(self) -> str:
        self.daemon.start()
        host = self.daemon.cfg.listen.rsplit(":", 1)[0]
        if self.daemon.object_gateway is not None:
            # surfaced as a "GATEWAY <name> <addr>" line by the runner so
            # subprocess drivers (hack/run_cluster.py) can reach it —
            # advertise the gateway's OWN bind host, which may differ
            # from the gRPC listen host
            self.gateway_addr = (
                f"{self.daemon.cfg.object_storage_host}:"
                f"{self.daemon.object_gateway.port}"
            )
        return f"{host}:{self.daemon.port}"

    def stop(self) -> None:
        self.daemon.stop()


def main(argv=None) -> int:
    from dragonfly2_tpu.cli.runner import main_with_config

    def build(config_path, overrides):
        from dragonfly2_tpu.cli.config import load_config

        cfg = load_config(
            DaemonConfig, config_path, env_prefix="DF_DAEMON", overrides=overrides
        )
        return _DaemonRunAdapter(Daemon(cfg))

    return main_with_config("daemon", build, argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
