"""Back-to-source clients: protocol-pluggable origin fetch.

Role parity: reference pkg/source/source_client.go:102-161 (interface:
content length, range support, download, metadata, recursive list) with
clients under pkg/source/clients/{httpprotocol,...}. Scheme → client
registry mirrors pkg/source's loader; plugins register at import time.

http(s) and file are implemented here; s3 (SigV4), oss, hdfs
(WebHDFS), and oras (OCI registry artifacts) live in source_cloud.py —
real REST clients, no SDKs.
"""

from __future__ import annotations

import email.utils
import mimetypes
import os
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Iterator

CHUNK_SIZE = 1 << 20


class SourceError(Exception):
    pass


@dataclass
class Metadata:
    content_length: int = -1
    support_range: bool = False
    last_modified: float = 0.0
    etag: str = ""
    content_type: str = ""


@dataclass
class ListEntry:
    url: str
    name: str
    is_dir: bool
    content_length: int = -1


class SourceClient:
    """One origin protocol (reference pkg/source/source_client.go:102)."""

    def metadata(self, url: str, headers: dict | None = None) -> Metadata:
        raise NotImplementedError

    def content_length(self, url: str, headers: dict | None = None) -> int:
        return self.metadata(url, headers).content_length

    def download(
        self,
        url: str,
        headers: dict | None = None,
        offset: int = 0,
        length: int = -1,
    ) -> Iterator[bytes]:
        """Yield chunks of the object; ``offset``/``length`` select a
        byte range when the origin supports it."""
        raise NotImplementedError

    def list(self, url: str, headers: dict | None = None) -> list[ListEntry]:
        """Recursive-download directory listing (reference
        pkg/source list support, used by dfget --recursive)."""
        raise NotImplementedError


def open_url(req, timeout: float):
    """urlopen honoring ``DF_ORIGIN_CA``: a PEM bundle ADDED to the
    system trust store for origins behind a private CA (internal
    registries) — read per call so it can change at runtime (urllib's
    default opener freezes its SSL context on first use). Shared by the
    source clients and the daemon transport's direct route."""
    import os as _os
    import ssl as _ssl

    ca = _os.environ.get("DF_ORIGIN_CA")
    if ca:
        ctx = _ssl.create_default_context()  # system roots stay trusted
        ctx.load_verify_locations(cafile=ca)
        return urllib.request.urlopen(req, timeout=timeout, context=ctx)
    return urllib.request.urlopen(req, timeout=timeout)


class HTTPSourceClient(SourceClient):
    """http(s) origin (reference pkg/source/clients/httpprotocol)."""

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    def metadata(self, url: str, headers: dict | None = None) -> Metadata:
        req = urllib.request.Request(url, method="HEAD", headers=headers or {})
        try:
            with open_url(req, self.timeout) as resp:
                h = resp.headers
                lm = 0.0
                if h.get("Last-Modified"):
                    try:
                        lm = email.utils.parsedate_to_datetime(
                            h["Last-Modified"]
                        ).timestamp()
                    except (TypeError, ValueError):
                        pass
                return Metadata(
                    content_length=int(h.get("Content-Length", -1)),
                    support_range=h.get("Accept-Ranges", "") == "bytes",
                    last_modified=lm,
                    etag=h.get("ETag", ""),
                    content_type=h.get("Content-Type", ""),
                )
        except urllib.error.HTTPError as e:
            raise SourceError(f"HEAD {url}: {e.code}") from e
        except urllib.error.URLError as e:
            raise SourceError(f"HEAD {url}: {e.reason}") from e

    def download(
        self,
        url: str,
        headers: dict | None = None,
        offset: int = 0,
        length: int = -1,
    ) -> Iterator[bytes]:
        hdrs = dict(headers or {})
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            hdrs["Range"] = f"bytes={offset}-{end}"
        req = urllib.request.Request(url, headers=hdrs)
        try:
            resp = open_url(req, self.timeout)
        except urllib.error.HTTPError as e:
            raise SourceError(f"GET {url}: {e.code}") from e
        except urllib.error.URLError as e:
            raise SourceError(f"GET {url}: {e.reason}") from e
        with resp:
            while True:
                chunk = resp.read(CHUNK_SIZE)
                if not chunk:
                    break
                yield chunk

    def list(self, url: str, headers: dict | None = None) -> list[ListEntry]:
        raise SourceError("http origin does not support recursive listing")


class FileSourceClient(SourceClient):
    """file:// origin — used by tests and dfcache import."""

    @staticmethod
    def _path(url: str) -> str:
        return urllib.parse.unquote(urllib.parse.urlparse(url).path)

    def metadata(self, url: str, headers: dict | None = None) -> Metadata:
        p = self._path(url)
        if not os.path.exists(p):
            raise SourceError(f"no such file: {p}")
        st = os.stat(p)
        return Metadata(
            content_length=st.st_size,
            support_range=True,
            last_modified=st.st_mtime,
            content_type=mimetypes.guess_type(p)[0] or "",
        )

    def download(
        self,
        url: str,
        headers: dict | None = None,
        offset: int = 0,
        length: int = -1,
    ) -> Iterator[bytes]:
        p = self._path(url)
        try:
            f = open(p, "rb")
        except OSError as e:
            raise SourceError(f"open {p}: {e}") from e
        with f:
            f.seek(offset)
            remaining = length if length >= 0 else None
            while True:
                want = CHUNK_SIZE if remaining is None else min(CHUNK_SIZE, remaining)
                if want == 0:
                    break
                chunk = f.read(want)
                if not chunk:
                    break
                if remaining is not None:
                    remaining -= len(chunk)
                yield chunk

    def list(self, url: str, headers: dict | None = None) -> list[ListEntry]:
        p = self._path(url)
        if not os.path.isdir(p):
            raise SourceError(f"not a directory: {p}")
        out = []
        for name in sorted(os.listdir(p)):
            fp = os.path.join(p, name)
            out.append(
                ListEntry(
                    url=f"file://{fp}",
                    name=name,
                    is_dir=os.path.isdir(fp),
                    content_length=os.path.getsize(fp) if os.path.isfile(fp) else -1,
                )
            )
        return out


_REGISTRY: dict[str, SourceClient] = {}


def register_client(scheme: str, client: SourceClient) -> None:
    _REGISTRY[scheme] = client


def client_for(url: str) -> SourceClient:
    scheme = urllib.parse.urlparse(url).scheme or "file"
    client = _REGISTRY.get(scheme)
    if client is None and scheme in _LAZY_CLOUD:
        client = _load_cloud(scheme)
    if client is None:
        raise SourceError(f"no source client registered for scheme {scheme!r}")
    return client


register_client("http", HTTPSourceClient())
register_client("https", HTTPSourceClient())
register_client("file", FileSourceClient())


# cloud clients register lazily on first use — importing source_cloud
# here would re-enter it while partially initialized when a caller
# imports source_cloud first (it imports this module for the base types)
_LAZY_CLOUD = {
    "s3": "S3SourceClient",
    "oss": "OSSSourceClient",
    "hdfs": "HDFSSourceClient",
    "oras": "ORASSourceClient",
}


def _load_cloud(scheme: str) -> SourceClient:
    from dragonfly2_tpu.client import source_cloud as sc

    client = getattr(sc, _LAZY_CLOUD[scheme])()
    register_client(scheme, client)
    return client
