"""Host stat collection for the daemon announcer.

Role parity: reference client/daemon/announcer/announcer.go:158-303 —
the daemon ships full CPU/memory/network/disk stats (gopsutil there,
psutil/procfs here) with every AnnounceHost, which is what populates the
Download records' host columns and 5 of the 12 MLP pair features
(cpu.percent, memory.used_percent, tcp connection counts,
disk.used_percent). Without this the model trains on dead inputs.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

try:
    import psutil

    # one process handle reused across samples: cpu_percent(interval=None)
    # measures the delta since the *same instance's* previous call — a
    # fresh Process() every sample would report 0.0 forever
    _PROC = psutil.Process()
    _PROC.cpu_percent(interval=None)  # establish the baseline sample
    psutil.cpu_percent(interval=None)
except ImportError:  # pragma: no cover - psutil is in this image
    psutil = None
    _PROC = None


@dataclass
class CpuStats:
    logical_count: int = 0
    physical_count: int = 0
    percent: float = 0.0
    process_percent: float = 0.0


@dataclass
class MemoryStats:
    total: int = 0
    available: int = 0
    used: int = 0
    used_percent: float = 0.0
    process_used_percent: float = 0.0
    free: int = 0


@dataclass
class NetworkStats:
    tcp_connection_count: int = 0
    upload_tcp_connection_count: int = 0


@dataclass
class DiskStats:
    total: int = 0
    free: int = 0
    used: int = 0
    used_percent: float = 0.0
    inodes_total: int = 0
    inodes_used: int = 0
    inodes_used_percent: float = 0.0


@dataclass
class HostStats:
    cpu: CpuStats = field(default_factory=CpuStats)
    memory: MemoryStats = field(default_factory=MemoryStats)
    network: NetworkStats = field(default_factory=NetworkStats)
    disk: DiskStats = field(default_factory=DiskStats)


def collect(data_dir: str = "/", upload_ports: tuple[int, ...] = ()) -> HostStats:
    """One stats sample. ``upload_ports`` classifies established TCP
    connections terminating at the daemon's upload/gRPC ports as upload
    connections (reference announcer.go tcp stat split)."""
    s = HostStats()
    if psutil is not None:
        s.cpu.logical_count = psutil.cpu_count(logical=True) or 0
        s.cpu.physical_count = psutil.cpu_count(logical=False) or 0
        # interval=None: delta since the previous call — non-blocking
        s.cpu.percent = psutil.cpu_percent(interval=None)
        try:
            s.cpu.process_percent = _PROC.cpu_percent(interval=None)
            s.memory.process_used_percent = _PROC.memory_percent()
        except psutil.Error:  # pragma: no cover - racing process teardown
            pass
        vm = psutil.virtual_memory()
        s.memory.total = vm.total
        s.memory.available = vm.available
        s.memory.used = vm.used
        s.memory.used_percent = vm.percent
        s.memory.free = vm.free
        tcp_total, tcp_upload = _tcp_counts(upload_ports)
        s.network.tcp_connection_count = tcp_total
        s.network.upload_tcp_connection_count = tcp_upload
    try:
        st = os.statvfs(data_dir)
        s.disk.total = st.f_blocks * st.f_frsize
        s.disk.free = st.f_bavail * st.f_frsize
        s.disk.used = s.disk.total - st.f_bfree * st.f_frsize
        if s.disk.total > 0:
            s.disk.used_percent = 100.0 * s.disk.used / s.disk.total
        s.disk.inodes_total = st.f_files
        s.disk.inodes_used = st.f_files - st.f_ffree
        if s.disk.inodes_total > 0:
            s.disk.inodes_used_percent = 100.0 * s.disk.inodes_used / s.disk.inodes_total
    except OSError:  # pragma: no cover - data_dir vanished
        pass
    return s


def _tcp_counts(upload_ports: tuple[int, ...]) -> tuple[int, int]:
    """(established TCP connections, of which terminate at upload_ports).
    Reads /proc/net/tcp* directly — psutil.net_connections needs broad
    /proc access that may be restricted; procfs text is always there on
    Linux."""
    total = upload = 0
    ports = set(upload_ports)
    for path in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            with open(path) as f:
                next(f)  # header
                for line in f:
                    fields = line.split()
                    if len(fields) < 4 or fields[3] != "01":  # 01 = ESTABLISHED
                        continue
                    total += 1
                    try:
                        local_port = int(fields[1].rsplit(":", 1)[1], 16)
                    except (IndexError, ValueError):
                        continue
                    if local_port in ports:
                        upload += 1
        except OSError:
            continue
    return total, upload
