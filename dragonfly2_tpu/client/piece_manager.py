"""Piece manager: fetch pieces from parents or the origin into storage.

Role parity: reference client/daemon/peer/piece_manager.go —
``download_piece`` from a parent (:170) and ``download_source`` whole-file
from origin with optional concurrent ranged piece downloads
(:139-166,303-373). The parent dispatcher keeps a per-parent latency
EWMA with randomized tie-breaking (reference piece_dispatcher.go:103-149).
"""

# dfanalyze: hot — per-piece fetch/verify/write path + the rate limiter
# every transfer windows through

from __future__ import annotations

import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from dragonfly2_tpu.client import downloader, source
from dragonfly2_tpu.client.pieces import PieceRange, compute_piece_length, piece_ranges
from dragonfly2_tpu.client.storage import StorageError, TaskStorage
from dragonfly2_tpu.utils import dflog, faults, flight, flows, profiling

logger = dflog.get("client.piece")

# dfprof phases: the piece path's wall split — network read from the
# parent vs the verified write into the piece store (the wait-for-parent
# leg is accounted conductor-side, where the waiting happens)
PH_PIECE_READ = profiling.phase_type("daemon.piece_read")
PH_PIECE_WRITE = profiling.phase_type("daemon.piece_write")

# origin-path flight events: back-to-source is the expensive fallback,
# so every origin hit is worth a permanent ring entry
EV_SOURCE_START = flight.event_type("daemon.source_download_start")
EV_SOURCE_DONE = flight.event_type("daemon.source_download_done")

# fault point: the parent piece fetch — chaos schedules model flaky/lying
# parents here (errors, latency, payload truncation/corruption); the
# digest check downstream must catch every mutated payload
FP_PIECE_READ = faults.point("daemon.piece_read")

TRAFFIC_BACK_TO_SOURCE = "back_to_source"
TRAFFIC_REMOTE_PEER = "remote_peer"


@dataclass
class ParentInfo:
    peer_id: str
    upload_addr: str  # host:port of the parent's HTTP upload server
    finished_pieces: set[int] = field(default_factory=set)
    # latency EWMA (seconds) for dispatcher scoring
    latency: float = 0.0

    def observe(self, dt: float) -> None:
        self.latency = dt if self.latency == 0 else 0.8 * self.latency + 0.2 * dt


class PieceDispatcher:
    """Scores parents by observed latency with randomization so one fast
    parent doesn't absorb every piece (reference
    piece_dispatcher.go:103-149)."""

    def __init__(self, rand: random.Random | None = None):
        self.rand = rand or random.Random(0)

    def pick(
        self,
        parents: list[ParentInfo],
        piece_number: int,
        exclude: set[str] | None = None,
    ) -> ParentInfo | None:
        """Pick a parent for ``piece_number``. Parents advertising the piece
        win; otherwise any parent may be probed optimistically (an
        in-progress parent's finished_pieces snapshot goes stale the moment
        it downloads more — a 404 there is retryable, not disqualifying).
        ``exclude`` deprioritizes just-failed parents when alternatives
        exist."""
        if exclude:
            preferred = [p for p in parents if p.peer_id not in exclude]
            if preferred:
                parents = preferred
        eligible = [p for p in parents if piece_number in p.finished_pieces]
        if not eligible:
            # parents that may have the piece soon: any parent
            eligible = list(parents)
        if not eligible:
            return None
        # weight ∝ 1/(latency+ε), jittered
        weights = [
            (1.0 / (p.latency + 1e-3)) * (0.75 + 0.5 * self.rand.random())
            for p in eligible
        ]
        return eligible[max(range(len(eligible)), key=lambda i: weights[i])]


class PieceManager:
    def __init__(
        self,
        concurrent_pieces: int = 4,
        source_concurrency: int = 4,
        source_concurrency_threshold: int = 32 * 1024 * 1024,
        shaper: "TrafficShaper | None" = None,
        download_delay_s: float = 0.0,
    ):
        self.concurrent_pieces = concurrent_pieces
        self.source_concurrency = source_concurrency
        self.source_concurrency_threshold = source_concurrency_threshold
        self.shaper = shaper
        # synthetic receive-side latency per piece, landing INSIDE the
        # measured cost window — fault-injection knob modelling a loaded
        # host whose pressure slows its own downloads (the signal the
        # bad-node detectors read); 0 in production
        self.download_delay_s = download_delay_s

    # ------------------------------------------------------------------
    def download_piece_from_parent(
        self,
        ts: TaskStorage,
        parent: ParentInfo,
        pr: PieceRange,
        peer_id: str,
    ) -> "PieceResult":
        t0 = time.monotonic()
        try:
            FP_PIECE_READ()
        except faults.InjectedFault as e:
            raise downloader.PieceDownloadError(str(e)) from e
        with PH_PIECE_READ:
            data, digest, content_type = downloader.download_piece(
                parent.upload_addr, ts.meta.task_id, pr.number, peer_id=peer_id
            )
        data = FP_PIECE_READ.mutate(data)
        if self.download_delay_s > 0:
            time.sleep(self.download_delay_s)  # inside the cost window
        dt_transfer = time.monotonic() - t0
        if self.shaper is not None and self.shaper.enabled:
            # debit on SUCCESS, outside the measured window: optimistic
            # 404 probes transfer nothing and must not burn the budget,
            # and limiter stall must not poison the recorded piece cost
            # that trains the parent-ranking models
            self.shaper.limiter_for(ts.meta.task_id).acquire(len(data))
        dt = dt_transfer
        parent.observe(dt)
        if content_type and "Content-Type" not in ts.meta.headers:
            ts.meta.headers["Content-Type"] = content_type
        if len(data) != pr.length:
            raise downloader.PieceDownloadError(
                f"piece {pr.number}: want {pr.length}B got {len(data)}B"
            )
        try:
            with PH_PIECE_WRITE:
                pm = ts.write_piece(
                    pr.number,
                    pr.offset,
                    data,
                    digest=digest,
                    traffic_type=TRAFFIC_REMOTE_PEER,
                    cost_ns=int(dt * 1e9),
                    parent_id=parent.peer_id,
                )
        except StorageError as e:
            # a digest mismatch means THIS parent served corrupt bytes —
            # that's a retryable piece failure (another parent or the
            # origin may hold good bytes), not a terminal task error
            raise downloader.PieceDownloadError(
                f"piece {pr.number} from {parent.peer_id}: {e}"
            ) from e
        # flow ledger: one request per parent piece fetch, attributed
        # like the bytes were (a ref hit is a dedup request)
        flows.request(
            flows.task_plane(ts.meta.task_id),
            "dedup" if pm.ref_task else "parent",
            latency_s=dt,
        )
        return PieceResult(pm.number, pm.offset, pm.length, pm.digest, pm.traffic_type, pm.cost_ns, parent.peer_id)

    # ------------------------------------------------------------------
    def download_source(
        self,
        ts: TaskStorage,
        url: str,
        headers: dict | None = None,
        on_piece=None,
        offset: int = 0,
        length: int = -1,
        expected_digest: str = "",
    ) -> int:
        """Whole-file origin download: ranged concurrent pieces when the
        origin supports Range and the file is big enough, else one
        sequential stream chunked into pieces (reference
        piece_manager.go:303-373). Returns content length.

        ``offset``/``length`` select a byte range of the origin object
        (dfget --range / UrlMeta.range): the task's content IS that
        slice — pieces number from its start, and the task completes at
        ``length`` bytes."""
        t_start = time.monotonic()
        EV_SOURCE_START(
            task_id=ts.meta.task_id, url=url, offset=offset, length=length
        )
        client = source.client_for(url)
        meta = client.metadata(url, headers)
        content_length = meta.content_length
        ranged = bool(offset or length >= 0)
        if ranged:
            if not meta.support_range:
                raise ValueError(f"origin does not support ranges: {url}")
            if content_length < 0:
                raise ValueError("ranged download needs a known origin length")
            if offset < 0:
                # suffix form (-n = last n bytes): RFC 7233 clamps a
                # suffix longer than the object to the whole object
                offset = max(0, content_length + offset)
            if offset >= content_length:
                # HTTP 416 semantics: a start past the end is an error,
                # never an empty 'completed' task
                raise ValueError(
                    f"range start {offset} beyond object end {content_length}"
                )
            avail = content_length - offset
            content_length = min(length, avail) if length >= 0 else avail

        if meta.content_type:
            ts.meta.headers["Content-Type"] = meta.content_type
        if content_length >= 0 and ts.meta.content_length < 0:
            ts.meta.content_length = content_length
        if not ts.meta.piece_length:
            ts.meta.piece_length = compute_piece_length(content_length)

        use_concurrent = (
            meta.support_range
            and content_length >= self.source_concurrency_threshold
            and self.source_concurrency > 1
        )
        if use_concurrent:
            ranges = piece_ranges(content_length, ts.meta.piece_length)

            def fetch(pr: PieceRange):
                t0 = time.monotonic()
                # piece offsets are slice-relative; the origin fetch adds
                # the slice's own start
                data = b"".join(
                    client.download(url, headers, offset + pr.offset, pr.length)
                )
                if len(data) != pr.length:
                    # an origin that ignores Range (200 + full body) or
                    # truncates must fail the task, not poison pieces —
                    # the peer-download path enforces the same invariant
                    raise ValueError(
                        f"origin returned {len(data)} bytes for a"
                        f" {pr.length}-byte ranged piece"
                    )
                dt = time.monotonic() - t0
                if self.shaper is not None and self.shaper.enabled:
                    self.shaper.limiter_for(ts.meta.task_id).acquire(len(data))
                pm = ts.write_piece(
                    pr.number, pr.offset, data,
                    traffic_type=TRAFFIC_BACK_TO_SOURCE, cost_ns=int(dt * 1e9),
                )
                if on_piece:
                    on_piece(PieceResult(pm.number, pm.offset, pm.length, pm.digest, pm.traffic_type, pm.cost_ns, ""))

            with ThreadPoolExecutor(max_workers=self.source_concurrency) as pool:
                list(pool.map(fetch, ranges))
            ts.mark_done(content_length, expected_digest=expected_digest)
            EV_SOURCE_DONE(
                task_id=ts.meta.task_id,
                mode="concurrent",
                bytes=content_length,
                wall_s=round(time.monotonic() - t_start, 3),
            )
            self._account_source_request(ts, time.monotonic() - t_start)
            return content_length

        # sequential stream → pieces (write offsets are slice-relative)
        number, write_off, buf = 0, 0, b""
        pl = ts.meta.piece_length
        t0 = time.monotonic()
        stream = (
            client.download(url, headers, offset, content_length)
            if ranged
            else client.download(url, headers)
        )
        for chunk in stream:
            buf += chunk
            if ranged and write_off + len(buf) > content_length:
                # fail the moment the origin over-delivers (Range
                # ignored) — BEFORE more wrong-content pieces are
                # written and announced to the scheduler
                raise ValueError(
                    f"ranged origin delivered more than {content_length} bytes"
                )
            while len(buf) >= pl:
                piece, buf = buf[:pl], buf[pl:]
                dt = time.monotonic() - t0
                pm = ts.write_piece(
                    number, write_off, piece,
                    traffic_type=TRAFFIC_BACK_TO_SOURCE, cost_ns=int(dt * 1e9),
                )
                if on_piece:
                    on_piece(PieceResult(pm.number, pm.offset, pm.length, pm.digest, pm.traffic_type, pm.cost_ns, ""))
                number += 1
                write_off += len(piece)
                t0 = time.monotonic()
        if buf or number == 0:
            dt = time.monotonic() - t0
            pm = ts.write_piece(
                number, write_off, buf,
                traffic_type=TRAFFIC_BACK_TO_SOURCE, cost_ns=int(dt * 1e9),
            )
            if on_piece:
                on_piece(PieceResult(pm.number, pm.offset, pm.length, pm.digest, pm.traffic_type, pm.cost_ns, ""))
            write_off += len(buf)
        if ranged and write_off != content_length:
            # over-delivery = origin ignored the Range header; short =
            # truncated stream — both must fail, not complete wrong
            raise ValueError(
                f"ranged origin delivered {write_off} bytes, expected {content_length}"
            )
        ts.mark_done(write_off, expected_digest=expected_digest)
        EV_SOURCE_DONE(
            task_id=ts.meta.task_id,
            mode="sequential",
            bytes=write_off,
            wall_s=round(time.monotonic() - t_start, 3),
        )
        self._account_source_request(ts, time.monotonic() - t_start)
        return write_off

    @staticmethod
    def _account_source_request(ts: TaskStorage, wall_s: float) -> None:
        flows.request(
            flows.task_plane(ts.meta.task_id),
            "preheat" if flows.is_preheat(ts.meta.task_id) else "origin",
            latency_s=wall_s,
        )


@dataclass
class PieceResult:
    number: int
    offset: int
    length: int
    digest: str
    traffic_type: str
    cost_ns: int
    parent_id: str


class RateLimiter:
    """Token-bucket byte-rate limiter (one per task under the
    TrafficShaper's global budget)."""

    def __init__(self, rate_bytes_per_s: float):
        self.rate = rate_bytes_per_s
        self.tokens = rate_bytes_per_s
        self.last = time.monotonic()
        self.lock = threading.Lock()
        self.consumed = 0  # bytes since the shaper's last sample

    def acquire(self, n: int) -> None:
        with self.lock:
            self.consumed += n
        if self.rate <= 0:
            return
        while True:
            with self.lock:
                now = time.monotonic()
                self.tokens = min(self.rate, self.tokens + (now - self.last) * self.rate)
                self.last = now
                # debt-based: a request larger than one second's budget
                # (bucket capacity) admits once the bucket is full and
                # drives the balance negative — otherwise a piece bigger
                # than the task's share would spin forever
                need = min(float(n), self.rate)
                if self.tokens >= need:
                    self.tokens -= n
                    return
                wait = (need - self.tokens) / self.rate
            time.sleep(min(wait, 0.5))

    def acquire_nowait(self, n: int) -> float:
        """Non-blocking form for the readiness-based serve loop: debit
        ``n`` and return 0.0 when the budget allows it now, else return
        the seconds to wait (nothing debited — the caller parks the
        connection on a loop timer and retries). Debt-based exactly like
        :meth:`acquire`, so a window larger than one second's budget
        still admits once the bucket fills."""
        with self.lock:
            self.consumed += n
            if self.rate <= 0:
                return 0.0
            now = time.monotonic()
            self.tokens = min(self.rate, self.tokens + (now - self.last) * self.rate)
            self.last = now
            need = min(float(n), self.rate)
            if self.tokens >= need:
                self.tokens -= n
                return 0.0
            self.consumed -= n
            return (need - self.tokens) / self.rate

    def refund(self, n: int) -> None:
        """Return tokens debited for bytes that never hit the wire (a
        socket that went write-blocked mid-window)."""
        with self.lock:
            self.tokens = min(self.rate, self.tokens + n) if self.rate > 0 else self.tokens
            self.consumed = max(0, self.consumed - n)

    def set_rate(self, rate: float) -> None:
        with self.lock:
            self.rate = rate

    def take_usage(self) -> int:
        with self.lock:
            used, self.consumed = self.consumed, 0
            return used


class TrafficShaper:
    """Cross-task sampling traffic shaper (reference
    client/daemon/peer/traffic_shaper.go:126-175): one global download
    budget, re-allocated across active tasks every sampling interval.

    Allocation rule per sample: every task keeps a fair share
    (total/N); tasks that used less than their share in the last window
    donate the surplus, which is split among tasks that saturated theirs
    proportionally to observed demand — a lone hot task gets the whole
    budget, competing hot tasks converge to equal shares.
    """

    def __init__(self, total_rate: float, interval: float = 1.0):
        self.total_rate = total_rate
        self.interval = interval
        self._tasks: dict[str, RateLimiter] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def enabled(self) -> bool:
        return self.total_rate > 0

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="traffic-shaper", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def limiter_for(self, task_id: str) -> RateLimiter:
        with self._lock:
            lim = self._tasks.get(task_id)
            if lim is None:
                # a joining task starts at the fair share; the next sample
                # rebalances everyone
                share = (
                    self.total_rate / (len(self._tasks) + 1)
                    if self.enabled
                    else 0.0
                )
                lim = self._tasks[task_id] = RateLimiter(share)
                if self.enabled:
                    for other in self._tasks.values():
                        other.set_rate(share)
            return lim

    def release(self, task_id: str) -> None:
        with self._lock:
            self._tasks.pop(task_id, None)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.sample_once()

    def sample_once(self) -> None:
        with self._lock:
            tasks = list(self._tasks.values())
        if not tasks or not self.enabled:
            return
        share = self.total_rate / len(tasks)
        floor = 0.05 * self.total_rate  # a donor can always restart
        usages = [lim.take_usage() for lim in tasks]
        # saturated = used ≥ ~90% of its current per-window allowance
        saturated = [
            u >= 0.9 * lim.rate * self.interval for lim, u in zip(tasks, usages)
        ]
        if not any(saturated):
            # nobody is starved: plain fair shares (and a lone task keeps
            # the whole budget for instant ramp-up)
            for lim in tasks:
                lim.set_rate(share)
            return
        # donors are clamped near their observed demand (+20% headroom)
        # so allocated rates SUM to ≤ total_rate — handing a donor's
        # surplus away while it keeps its full share would over-admit;
        # a donor that turns hot saturates its clamp within one window
        # and gets promoted at the next sample
        donor_rates = {
            id(lim): min(share, max(u / self.interval * 1.2, floor))
            for lim, u, sat in zip(tasks, usages, saturated)
            if not sat
        }
        surplus = sum(share - r for r in donor_rates.values())
        demand = sum(u for u, sat in zip(usages, saturated) if sat)
        for lim, u, sat in zip(tasks, usages, saturated):
            if sat and demand > 0:
                rate = share + surplus * (u / demand)
            elif sat:
                rate = share + surplus / max(1, sum(saturated))
            else:
                rate = donor_rates[id(lim)]
            lim.set_rate(rate)

