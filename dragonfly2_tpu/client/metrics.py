"""Daemon Prometheus series (reference client daemon metrics: piece
traffic by type, proxy requests, upload serving)."""

from dragonfly2_tpu.utils.metrics import default_registry as _r

PIECE_DOWNLOADED_TOTAL = _r.counter(
    "daemon_piece_downloaded_total", "Pieces written locally", ("traffic_type",)
)
PIECE_TRAFFIC_BYTES = _r.counter(
    "daemon_piece_traffic_bytes_total", "Bytes written locally", ("traffic_type",)
)
PIECE_UPLOADED_TOTAL = _r.counter(
    "daemon_piece_uploaded_total", "Pieces served to children over HTTP"
)
PIECE_UPLOAD_BYTES = _r.counter(
    "daemon_piece_upload_bytes_total", "Bytes served to children over HTTP"
)
TASK_TOTAL = _r.counter("daemon_task_total", "Peer tasks started", ("type",))
TASK_FAILURE_TOTAL = _r.counter("daemon_task_failure_total", "Peer tasks failed")
BACK_TO_SOURCE_TOTAL = _r.counter(
    "daemon_back_to_source_total", "Tasks that fell back to the origin"
)
PROXY_REQUEST_TOTAL = _r.counter(
    "daemon_proxy_request_total", "Proxy requests", ("route",)
)
# --- zero-copy data plane (docs/data-plane.md) ---
CHILD_DISCONNECT_TOTAL = _r.counter(
    "daemon_child_disconnect_total",
    "Child peers that dropped the connection mid-response",
)
UPLOAD_CONNECTIONS = _r.gauge(
    "daemon_upload_connections", "Live child connections on the upload loop"
)
PIECE_DEDUP_TOTAL = _r.counter(
    "daemon_piece_dedup_total",
    "Pieces stored as content-addressed refs instead of a second copy",
)
PIECE_DEDUP_BYTES = _r.counter(
    "daemon_piece_dedup_bytes_total", "Bytes saved by content-addressed dedup"
)
PIECE_DEDUP_MIGRATE_TOTAL = _r.counter(
    "daemon_piece_dedup_migrate_total",
    "Owner-piece migrations performed by refcount-safe GC",
)
P2P_INFLIGHT_SHED_TOTAL = _r.counter(
    "daemon_p2p_inflight_shed_total",
    "Transport requests sent direct because the P2P in-flight bound was hit",
)
