"""HTTP piece downloader — the child side of piece transfer.

Role parity: reference client/daemon/peer/piece_downloader.go:165-204 —
``GET parent:uploadPort/download/<task>?peerId=&number=`` fetches one
piece's bytes from the parent's upload server.

Transport: rides the shared readiness-based :mod:`transfer` pool
(bounded keep-alive connections, one selector thread — a piece fetch no
longer pays TCP setup/teardown, and thousands of concurrent transfers
multiplex over a bounded fd set). ``DF_TRANSFER_LOOP=0`` falls back to
per-request urllib.
"""

# dfanalyze: hot — one call per piece on the child download path

from __future__ import annotations

import urllib.error
import urllib.request

from dragonfly2_tpu.client import transfer


class PieceDownloadError(Exception):
    """Piece fetch failed. ``not_found`` marks an HTTP 404 — the parent is
    healthy but hasn't written the piece yet (in-progress peer), which
    callers treat as retryable rather than as a bad parent."""

    def __init__(self, msg: str, not_found: bool = False):
        super().__init__(msg)
        self.not_found = not_found


def download_piece(
    parent_addr: str,
    task_id: str,
    number: int,
    peer_id: str = "",
    timeout: float = 30.0,
) -> tuple[bytes, str, str]:
    """Fetch piece ``number`` of ``task_id`` from a parent upload server
    at ``host:port``; returns (bytes, digest, origin_content_type)."""
    target = f"/download/{task_id}?number={number}&peerId={peer_id}"
    pool = transfer.default_pool()
    if pool is None:
        return _download_piece_urllib(parent_addr, target, number, timeout)
    try:
        status, headers, body = pool.fetch(parent_addr, target, timeout=timeout)
    except transfer.TransferError as e:
        raise PieceDownloadError(f"piece {number} from {parent_addr}: {e}") from e
    if status != 200:
        raise PieceDownloadError(
            f"piece {number} from {parent_addr}: HTTP {status}",
            not_found=status == 404,
        )
    return (
        body,
        headers.get("x-dragonfly-piece-digest", ""),
        headers.get("x-dragonfly-origin-content-type", ""),
    )


def release_parents(addrs) -> None:
    """Task finished: let the pool drop idle keep-alive connections to
    these parents (bounds steady-state fd usage in big swarms)."""
    pool = transfer.default_pool()
    if pool is not None:
        pool.release_idle(addrs)


def _download_piece_urllib(
    parent_addr: str, target: str, number: int, timeout: float
) -> tuple[bytes, str, str]:
    url = f"http://{parent_addr}{target}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            data = resp.read()
            digest = resp.headers.get("X-Dragonfly-Piece-Digest", "")
            content_type = resp.headers.get("X-Dragonfly-Origin-Content-Type", "")
            return data, digest, content_type
    except urllib.error.HTTPError as e:
        raise PieceDownloadError(
            f"piece {number} from {parent_addr}: HTTP {e.code}", not_found=e.code == 404
        ) from e
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise PieceDownloadError(f"piece {number} from {parent_addr}: {e}") from e
