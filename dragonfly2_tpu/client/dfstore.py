"""dfstore — object-storage CLI/SDK against the daemon's gateway.

Role parity: reference client/dfstore/dfstore.go (809 LoC SDK) +
cmd/dfstore — copy/stat/remove objects through the daemon's
object-storage HTTP gateway, so reads ride the P2P swarm and writes can
seed the writing daemon (reference objectstorage gateway replication).

SDK functions take the gateway address ("host:port"); the CLI maps
  dfstore cp <src> <dst>    (local ↔ df://bucket/key, or df://… → df://… object copy)
  dfstore stat df://bucket/key
  dfstore rm df://bucket/key
  dfstore ls df://bucket[/prefix]
  dfstore mb df://bucket          (make bucket)
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.parse
import urllib.request


class DfstoreError(Exception):
    pass


def _url(gateway: str, bucket: str, key: str = "", query: str = "") -> str:
    path = f"/buckets/{bucket}"
    if key:
        path += f"/objects/{urllib.parse.quote(key)}"
    return f"http://{gateway}{path}" + (f"?{query}" if query else "")


def _request(
    method: str,
    url: str,
    data: bytes | None = None,
    timeout: float = 300.0,
    headers: dict | None = None,
):
    req = urllib.request.Request(url, data=data, method=method, headers=headers or {})
    try:
        return urllib.request.urlopen(req, timeout=timeout)
    except urllib.error.HTTPError as e:
        raise DfstoreError(f"{method} {url}: HTTP {e.code} {e.reason}") from e
    except urllib.error.URLError as e:
        raise DfstoreError(f"{method} {url}: {e.reason}") from e


# -- SDK --------------------------------------------------------------------


def create_bucket(gateway: str, bucket: str) -> None:
    _request("PUT", _url(gateway, bucket)).close()


def put_object(
    gateway: str, bucket: str, key: str, data: bytes, seed_local: bool = True
) -> None:
    """Store an object; ``seed_local`` also imports it into the writing
    daemon's piece store so it P2P-serves without a backend fetch."""
    mode = 1 if seed_local else 0
    _request(
        "PUT", _url(gateway, bucket, key, query=f"mode={mode}"), data=data
    ).close()


def get_object(
    gateway: str, bucket: str, key: str, byte_range: str = ""
) -> bytes:
    """Fetch an object (or, with ``byte_range``, a slice of it — RFC
    7233 forms; the gateway answers 206 + Content-Range)."""
    headers = {"Range": byte_range} if byte_range else None
    with _request("GET", _url(gateway, bucket, key), headers=headers) as resp:
        return resp.read()


def head_object(gateway: str, bucket: str, key: str) -> int | None:
    """→ object size, or None when absent."""
    try:
        with _request("HEAD", _url(gateway, bucket, key)) as resp:
            return int(resp.headers.get("Content-Length", 0))
    except DfstoreError as e:
        if "HTTP 404" in str(e):
            return None
        raise


def copy_object(
    gateway: str,
    bucket: str,
    key: str,
    dst_bucket: str,
    dst_key: str,
    seed_local: bool = True,
) -> None:
    """Object→object copy through the gateway (reference dfstore
    CopyObject) — composed client-side as get+put; the destination write
    rides the normal seed-on-write path unless ``seed_local`` is off."""
    put_object(
        gateway, dst_bucket, dst_key, get_object(gateway, bucket, key),
        seed_local=seed_local,
    )


def delete_object(gateway: str, bucket: str, key: str) -> None:
    _request("DELETE", _url(gateway, bucket, key)).close()


def list_objects(gateway: str, bucket: str, prefix: str = "") -> list[str]:
    url = f"http://{gateway}/buckets/{bucket}/objects"
    if prefix:
        url += "?" + urllib.parse.urlencode({"prefix": prefix})
    with _request("GET", url) as resp:
        return json.loads(resp.read())["keys"]


# -- CLI --------------------------------------------------------------------


def _parse_df(uri: str) -> tuple[str, str]:
    """df://bucket/key → (bucket, key)."""
    if not uri.startswith("df://"):
        raise DfstoreError(f"not a df:// URI: {uri}")
    rest = uri[5:]
    bucket, _, key = rest.partition("/")
    if not bucket:
        raise DfstoreError(f"missing bucket in {uri}")
    return bucket, key


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="dfstore", description="object storage via daemon gateway")
    p.add_argument("--endpoint", default="127.0.0.1:65004", help="gateway host:port")
    sub = p.add_subparsers(dest="cmd", required=True)

    cp = sub.add_parser("cp", help="copy local↔object store")
    cp.add_argument("src")
    cp.add_argument("dst")
    cp.add_argument("--no-seed", action="store_true", help="don't seed the local daemon on upload")
    cp.add_argument(
        "--range", default="", dest="byte_range",
        help='byte range for a df://→local copy, e.g. "0-1023" or "bytes=-500"',
    )

    for name in ("stat", "rm"):
        s = sub.add_parser(name)
        s.add_argument("uri")

    ls = sub.add_parser("ls")
    ls.add_argument("uri")

    mb = sub.add_parser("mb", help="make bucket")
    mb.add_argument("uri")

    args = p.parse_args(argv)
    if getattr(args, "byte_range", ""):
        # validate client-side (like dfget): the gateway IGNORES a
        # malformed Range per RFC 7233, which would silently copy the
        # whole object; and a range only means something for df://→local
        from dragonfly2_tpu.client.pieces import normalize_byte_range

        try:
            args.byte_range = normalize_byte_range(args.byte_range)
        except ValueError as e:
            p.error(str(e))
        if not (args.src.startswith("df://") and not args.dst.startswith("df://")):
            p.error("--range applies only to df://→local copies")
    try:
        if args.cmd == "cp":
            if args.src.startswith("df://") and args.dst.startswith("df://"):
                sb, sk = _parse_df(args.src)
                db_, dk = _parse_df(args.dst)
                copy_object(
                    args.endpoint, sb, sk, db_, dk, seed_local=not args.no_seed
                )
            elif args.src.startswith("df://"):
                bucket, key = _parse_df(args.src)
                data = get_object(args.endpoint, bucket, key, byte_range=args.byte_range)
                with open(args.dst, "wb") as f:
                    f.write(data)
            else:
                bucket, key = _parse_df(args.dst)
                with open(args.src, "rb") as f:
                    data = f.read()
                put_object(args.endpoint, bucket, key, data, seed_local=not args.no_seed)
        elif args.cmd == "stat":
            bucket, key = _parse_df(args.uri)
            size = head_object(args.endpoint, bucket, key)
            if size is None:
                print(f"{args.uri}: not found", file=sys.stderr)
                return 1
            print(f"{args.uri}\t{size} bytes")
        elif args.cmd == "rm":
            bucket, key = _parse_df(args.uri)
            delete_object(args.endpoint, bucket, key)
        elif args.cmd == "ls":
            bucket, key = _parse_df(args.uri)
            for k in list_objects(args.endpoint, bucket, prefix=key):
                print(f"df://{bucket}/{k}")
        elif args.cmd == "mb":
            bucket, _ = _parse_df(args.uri)
            create_bucket(args.endpoint, bucket)
    except DfstoreError as e:
        print(f"dfstore: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
