"""Model serving: the consumption side of the train→serve loop.

The reference declared (but never wired) Triton serving for trained models
(reference manager/types/model.go:36-37 `tensorrt_plan` configs, the
undialed inference client pkg/rpc/inference/client/client_v1.go). Here the
equivalent is in-process XLA serving: the scheduler's ml evaluator loads
the params pytree the trainer uploaded and scores candidate parents with a
jitted forward — no sidecar, no extra hop, same XLA compiler on CPU or
chip.

Serialization: flat ``{dotted/path: ndarray}`` npz — same trick as the
columnar codec, readable anywhere numpy exists.
"""

# dfanalyze: device-hot — scorers dispatch jitted forwards per schedule
# decision; a per-instance jit wrapper recompiles on every model refresh

from __future__ import annotations

import io
from typing import Any

import numpy as np

# one compiled wrapper per forward function, shared across scorer
# instances: model_refresher installs a fresh scorer per refresh, and a
# per-instance jax.jit would recompile the same forward on every hot swap
from dragonfly2_tpu.utils.jitcache import jit_once as _jit_once


def _device_params(params: Any) -> Any:
    """Pin a parameter pytree on device ONCE, at scorer construction.
    The deserialized pytree is numpy, and feeding numpy leaves into a
    jitted forward re-uploads the whole model on EVERY predict — the
    implicit-transfer class the jit witness flags. Resident params ride
    HBM across predicts; only the features move per call."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.asarray, params)


def serialize_params(params: Any) -> bytes:
    """Parameter pytree (dicts/lists of arrays) → npz bytes."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    arrays = {}
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        arrays[key] = np.asarray(leaf)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def deserialize_params(blob: bytes, like: Any) -> Any:
    """npz bytes → pytree with the structure of ``like``."""
    import jax

    with np.load(io.BytesIO(blob)) as z:
        flat_like, treedef = jax.tree_util.tree_flatten_with_path(like)
        leaves = []
        for path, leaf in flat_like:
            key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
            leaves.append(z[key])
        return jax.tree_util.tree_unflatten(treedef, leaves)


def deserialize_params_auto(blob: bytes) -> Any:
    """npz bytes → pytree, structure reconstructed from the flat keys
    alone (all-integer dict levels become lists). The serving side needs
    this because a downloaded model's layer count/dims aren't known until
    the weights arrive."""
    with np.load(io.BytesIO(blob)) as z:
        tree: dict = {}
        for key in z.files:
            node = tree
            parts = key.split("/")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = z[key]

    def listify(node):
        if not isinstance(node, dict):
            return node
        if node and all(k.isdigit() for k in node):
            return [listify(node[k]) for k in sorted(node, key=int)]
        return {k: listify(v) for k, v in node.items()}

    return listify(tree)


class MLPScorer:
    """Jitted parent scorer around trained MLP params — the object the
    scheduler's MLEvaluator calls ``predict`` on."""

    def __init__(self, params: Any):
        from dragonfly2_tpu.models.mlp import score_parents

        self._params = _device_params(params)
        self._fn = _jit_once(score_parents)

    @property
    def feature_dim(self) -> int:
        """Input width the model was trained for — MLEvaluator.set_model
        refuses a scorer whose dim doesn't match the live schema."""
        return int(self._params["layers"][0]["w"].shape[0])

    def predict(self, features: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        return np.asarray(self._fn(self._params, jnp.asarray(features)))


class GNNScorer:
    """Edge-RTT predictor over a fixed probe graph: scores (src, dst) host
    pairs by predicted RTT (for seed placement / cross-host ranking)."""

    def __init__(self, params: Any, graph):
        import jax.numpy as jnp

        from dragonfly2_tpu.models.gnn import apply_graphsage, predict_edge

        self._params = _device_params(params)
        self._node_index = {hid: i for i, hid in enumerate(graph.node_ids)}
        emb = _jit_once(apply_graphsage)(
            self._params,
            jnp.asarray(graph.node_features),
            jnp.asarray(graph.neighbors),
            jnp.asarray(graph.neighbor_mask),
        )
        self._emb = emb
        self._predict = _jit_once(predict_edge)

    def has_host(self, host_id: str) -> bool:
        return host_id in self._node_index

    def predict_rtt_log_ms(self, src_ids: list[str], dst_ids: list[str]) -> np.ndarray:
        import jax.numpy as jnp

        src = jnp.asarray([self._node_index[s] for s in src_ids], jnp.int32)
        dst = jnp.asarray([self._node_index[d] for d in dst_ids], jnp.int32)
        return np.asarray(self._predict(self._params, self._emb, src, dst))


class GRUScorer:
    """Next-piece-cost predictor around trained GRU params — the
    scheduler's ml evaluator consults it for model-based bad-node
    detection (a parent whose latest piece cost blows far past the
    prediction from its own history is flagged)."""

    def __init__(self, params: Any):
        from dragonfly2_tpu.models.gru import predict_next_cost

        self._params = _device_params(params)
        self._fn = _jit_once(predict_next_cost)

    def predict_next_log_cost(self, cost_prefixes_ms: list) -> np.ndarray:
        """[B] predicted next log1p piece cost (ms) from per-parent piece
        cost history prefixes — features built exactly like the offline
        extractor (schema/features.extract_piece_sequences: log1p cost,
        normalized piece position)."""
        import jax.numpy as jnp

        from dragonfly2_tpu.schema.features import (
            GRU_FEATURE_DIM,
            GRU_MAX_SEQ,
        )
        from dragonfly2_tpu.schema.records import MAX_PIECES_PER_PARENT

        b = len(cost_prefixes_ms)
        seqs = np.zeros((b, GRU_MAX_SEQ, GRU_FEATURE_DIM), np.float32)
        lengths = np.zeros((b,), np.int32)
        # positions trained on are (true piece index + 1)/MAX, capped at
        # GRU_MAX_SEQ pieces per record — long live histories are tail-
        # truncated to the most recent costs with their TRUE positions,
        # clipped to the trained range (records never exceed MAX pieces,
        # so larger positions would be out-of-distribution)
        pos_cap = GRU_MAX_SEQ / MAX_PIECES_PER_PARENT
        for i, prefix in enumerate(cost_prefixes_ms):
            full = np.asarray(prefix, np.float64)
            start = max(0, len(full) - GRU_MAX_SEQ)
            p = full[start:]
            L = len(p)
            seqs[i, :L, 0] = np.log1p(p)
            pos = (start + np.arange(L) + 1) / MAX_PIECES_PER_PARENT
            seqs[i, :L, 1] = np.minimum(pos, pos_cap)
            lengths[i] = L
        return np.asarray(self._fn(self._params, jnp.asarray(seqs), jnp.asarray(lengths)))
