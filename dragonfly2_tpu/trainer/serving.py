"""Model serving: the consumption side of the train→serve loop.

The reference declared (but never wired) Triton serving for trained models
(reference manager/types/model.go:36-37 `tensorrt_plan` configs, the
undialed inference client pkg/rpc/inference/client/client_v1.go). Here the
equivalent is in-process XLA serving: the scheduler's ml evaluator loads
the params pytree the trainer uploaded and scores candidate parents with a
jitted forward — no sidecar, no extra hop, same XLA compiler on CPU or
chip.

Serialization: flat ``{dotted/path: ndarray}`` npz — same trick as the
columnar codec, readable anywhere numpy exists.
"""

# dfanalyze: device-hot — scorers dispatch jitted forwards per schedule
# decision; a per-instance jit wrapper recompiles on every model refresh

from __future__ import annotations

import io
from typing import Any

import numpy as np

# one compiled wrapper per forward function, shared across scorer
# instances: model_refresher installs a fresh scorer per refresh, and a
# per-instance jax.jit would recompile the same forward on every hot swap
from dragonfly2_tpu.utils.jitcache import jit_once as _jit_once

# -- shape-bucket ladder ------------------------------------------------------
# Every serving forward pads its batch dimension UP to a rung of this
# ladder, so the jitted executable compiles once per rung instead of once
# per candidate-set size (the per-batch retrace class ROADMAP item 1's
# jit-witness allowlist entries tracked). Above the top rung, sizes round
# up to the next multiple of the top — huge batches stay bounded at
# one extra compile per 64-row step, never one per size.
BUCKET_LADDER = (8, 16, 32, 64)


def bucket_rows(n: int) -> int:
    """Smallest ladder rung ≥ ``n`` (multiples of the top rung above it)."""
    for b in BUCKET_LADDER:
        if n <= b:
            return b
    top = BUCKET_LADDER[-1]
    return ((n + top - 1) // top) * top


def pad_batch(a: np.ndarray, rows: int) -> np.ndarray:
    """Zero-pad axis 0 up to ``rows`` (no copy when already there)."""
    n = a.shape[0]
    if n == rows:
        return a
    out = np.zeros((rows,) + a.shape[1:], a.dtype)
    out[:n] = a
    return out


def _device_params(params: Any) -> Any:
    """Pin a parameter pytree on device ONCE, at scorer construction.
    The deserialized pytree is numpy, and feeding numpy leaves into a
    jitted forward re-uploads the whole model on EVERY predict — the
    implicit-transfer class the jit witness flags. Resident params ride
    HBM across predicts; only the features move per call."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.asarray, params)


def serialize_params(params: Any) -> bytes:
    """Parameter pytree (dicts/lists of arrays) → npz bytes."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    arrays = {}
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        arrays[key] = np.asarray(leaf)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def deserialize_params(blob: bytes, like: Any) -> Any:
    """npz bytes → pytree with the structure of ``like``."""
    import jax

    with np.load(io.BytesIO(blob)) as z:
        flat_like, treedef = jax.tree_util.tree_flatten_with_path(like)
        leaves = []
        for path, leaf in flat_like:
            key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
            leaves.append(z[key])
        return jax.tree_util.tree_unflatten(treedef, leaves)


def deserialize_params_auto(blob: bytes) -> Any:
    """npz bytes → pytree, structure reconstructed from the flat keys
    alone (all-integer dict levels become lists). The serving side needs
    this because a downloaded model's layer count/dims aren't known until
    the weights arrive."""
    with np.load(io.BytesIO(blob)) as z:
        tree: dict = {}
        for key in z.files:
            node = tree
            parts = key.split("/")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = z[key]

    def listify(node):
        if not isinstance(node, dict):
            return node
        if node and all(k.isdigit() for k in node):
            return [listify(node[k]) for k in sorted(node, key=int)]
        return {k: listify(v) for k, v in node.items()}

    return listify(tree)


def _score_ranked(params, packed):
    """Fused forward + segment-grouped rank: scores AND the lexsort
    permutation (segment primary, score ascending, row index as the
    stable tie-break — scheduler.wave.rank_order's contract) leave the
    device in one dispatch. ``packed`` is [rows, F+1]: the feature
    matrix with the segment-id vector as a trailing float column, so
    the whole wave rides ONE host→device upload (the jit-witness
    one-feature-upload-per-wave contract)."""
    import jax.numpy as jnp

    from dragonfly2_tpu.models.mlp import score_parents

    x = packed[:, :-1]
    seg = packed[:, -1]
    s = score_parents(params, x)
    return s, jnp.lexsort((jnp.arange(s.shape[0]), s, seg))


class MLPScorer:
    """Jitted parent scorer around trained MLP params — the object the
    scheduler's MLEvaluator calls ``predict`` on."""

    def __init__(self, params: Any):
        from dragonfly2_tpu.models.mlp import score_parents

        self._params = _device_params(params)
        self._fn = _jit_once(score_parents)
        self._ranked = _jit_once(_score_ranked)

    @property
    def feature_dim(self) -> int:
        """Input width the model was trained for — MLEvaluator.set_model
        refuses a scorer whose dim doesn't match the live schema."""
        return int(self._params["layers"][0]["w"].shape[0])

    def predict(self, features: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        # bucketed dispatch: the forward sees ladder shapes only, so a
        # steady-state serve path compiles once per rung regardless of
        # the candidate count (retired the score_parents retrace entry)
        n = features.shape[0]
        padded = pad_batch(np.asarray(features, np.float32), bucket_rows(n))
        return np.asarray(self._fn(self._params, jnp.asarray(padded)))[:n]

    def predict_ranked(
        self, features: np.ndarray, seg_ids: np.ndarray
    ) -> "tuple[np.ndarray, np.ndarray]":
        """Wave scoring: [n, F] flattened candidate rows whose
        non-decreasing ``seg_ids`` mark decision boundaries → (scores
        [n], segment-grouped rank permutation [n]) from ONE fused
        dispatch — the wave unpack never host-sorts C floats per child.
        Bucketed like ``predict``: pad rows ride a sentinel segment
        that sorts strictly last and is sliced off, so the fused
        executable compiles once per ladder rung. The segment vector is
        packed as a trailing float column on the padded matrix — one
        upload per wave, not two (float32 holds segment ids exactly up
        to 2^24; a wave is bounded far below that)."""
        import jax.numpy as jnp

        n = features.shape[0]
        rows = bucket_rows(n)
        sentinel = int(seg_ids[-1]) + 1 if n else 0
        packed = np.full(
            (rows, features.shape[1] + 1), 0.0, np.float32
        )
        packed[:n, :-1] = np.asarray(features, np.float32)
        packed[:, -1] = sentinel
        packed[:n, -1] = np.asarray(seg_ids, np.float32)
        s, order = self._ranked(self._params, jnp.asarray(packed))
        # whole-rung D2H then host slice: a device-side [:n] would
        # compile one dynamic_slice per distinct n — the retrace class
        # the ladder exists to kill (allowlisted host-pull, like predict)
        return np.asarray(s)[:n], np.asarray(order)[:n]


def _np_gelu(x: np.ndarray) -> np.ndarray:
    """The tanh-approximate gelu jax.nn.gelu defaults to, in numpy."""
    return 0.5 * x * (1.0 + np.tanh(0.7978845608028654 * (x + 0.044715 * x**3)))


class NumpyMLPScorer:
    """Pure-numpy MLP parent scorer with the IDENTICAL batched API as
    :class:`MLPScorer` (bucket-padded ``predict``), so deployments (and
    tier-1) without a usable XLA backend exercise the exact same
    submit/pack/score/return machinery the device path runs — only the
    forward itself differs. Row-wise deterministic: scores for a given
    feature row don't depend on which batch the row rode in."""

    def __init__(self, params: Any):
        self._layers = [
            (np.asarray(l["w"], np.float32), np.asarray(l["b"], np.float32))
            for l in params["layers"]
        ]

    @property
    def feature_dim(self) -> int:
        return int(self._layers[0][0].shape[0])

    def predict(self, features: np.ndarray) -> np.ndarray:
        n = features.shape[0]
        # same bucket discipline as the jitted twin: the pad is free
        # correctness-wise (rows are independent) and keeps the two
        # implementations behaviorally interchangeable under the service
        h = pad_batch(np.asarray(features, np.float32), bucket_rows(n))
        last = len(self._layers) - 1
        for i, (w, b) in enumerate(self._layers):
            h = h @ w + b
            if i != last:
                h = _np_gelu(h)
        return np.ascontiguousarray(h[:n, 0])

    def predict_ranked(
        self, features: np.ndarray, seg_ids: np.ndarray
    ) -> "tuple[np.ndarray, np.ndarray]":
        """Numpy twin of :meth:`MLPScorer.predict_ranked`: same
        (scores, segment-grouped permutation) contract, same lexsort
        keys, so the service's wave unpack is backend-independent."""
        scores = self.predict(features)
        order = np.lexsort(
            (np.arange(scores.shape[0]), scores, np.asarray(seg_ids))
        )
        return scores, order


def _np_sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def np_predict_next_cost(params: Any, x: np.ndarray, lengths=None) -> np.ndarray:
    """Pure-numpy twin of ``models.gru.predict_next_cost`` — the same
    masked GRU recurrence and gelu head on ``[B, T, F]`` histories, so
    GRU-backed serving (bad-node detection, the preheat demand
    forecaster) has the same CI-parity fallback NumpyMLPScorer gives the
    MLP path. Accepts numpy or device params (leaves are converted)."""
    wz, uz, bz = (np.asarray(params[k], np.float32) for k in ("wz", "uz", "bz"))
    wr, ur, br = (np.asarray(params[k], np.float32) for k in ("wr", "ur", "br"))
    wh, uh, bh = (np.asarray(params[k], np.float32) for k in ("wh", "uh", "bh"))
    x = np.asarray(x, np.float32)
    b, t, _ = x.shape
    if lengths is None:
        lengths = np.full((b,), t, np.int32)
    else:
        lengths = np.asarray(lengths, np.int32)
    h = np.zeros((b, uz.shape[0]), np.float32)
    for step in range(t):
        xt = x[:, step, :]
        z = _np_sigmoid(xt @ wz + h @ uz + bz)
        r = _np_sigmoid(xt @ wr + h @ ur + br)
        n = np.tanh(xt @ wh + (r * h) @ uh + bh)
        h_new = (1.0 - z) * n + z * h
        # state stops updating past a sequence's length, exactly like
        # the scan's keep mask: the final hidden is the last REAL step
        h = np.where((step < lengths)[:, None], h_new, h)
    layers = params["head"]["layers"]
    out = h
    last = len(layers) - 1
    for i, layer in enumerate(layers):
        out = out @ np.asarray(layer["w"], np.float32) + np.asarray(
            layer["b"], np.float32
        )
        if i != last:
            out = _np_gelu(out)
    return out[:, 0]


class GNNScorer:
    """Edge-RTT predictor over a fixed probe graph: scores (src, dst) host
    pairs by predicted RTT (for seed placement / cross-host ranking, and
    the batched scoring service's GNN rung).

    Embeddings are computed ONCE at construction — swap time in the
    model-refresher's lifecycle — and stay resident on device next to
    the params; per predict only the (src, dst) index vectors move. With
    a multi-device ``mesh`` the embed forward runs graph-parallel
    (models.gnn_sharded): node tables row-sharded over ``mesh[axis]``,
    so a fleet-scale graph never materializes on one chip."""

    def __init__(self, params: Any, graph, mesh=None, axis: str = "gp"):
        import jax.numpy as jnp

        from dragonfly2_tpu.models.gnn import apply_graphsage, predict_edge

        self._params = _device_params(params)
        self._node_index = {hid: i for i, hid in enumerate(graph.node_ids)}
        if mesh is not None and dict(getattr(mesh, "shape", {})).get(axis, 1) > 1:
            self._emb = self._sharded_embed(graph, mesh, axis)
        else:
            self._emb = _jit_once(apply_graphsage)(
                self._params,
                jnp.asarray(graph.node_features),
                jnp.asarray(graph.neighbors),
                jnp.asarray(graph.neighbor_mask),
            )
        self._predict = _jit_once(predict_edge)

    def _sharded_embed(self, graph, mesh, axis: str):
        """Graph-parallel embed at swap time: pad node tables to the
        shard multiple, run the ring-gather SAGE forward, keep only the
        real rows (padded nodes self-neighbor with zero mask — inert)."""
        from dragonfly2_tpu.models.gnn_sharded import (
            make_sharded_embed,
            pad_node_arrays,
        )

        shards = dict(mesh.shape)[axis]
        feats, nbrs, mask = pad_node_arrays(graph, shards)
        dense = {k: v for k, v in self._params.items() if k != "node_embed"}
        embed = self._params.get("node_embed")
        if embed is not None:
            import jax.numpy as jnp

            embed = jnp.asarray(pad_batch(np.asarray(embed), feats.shape[0]))
        emb = make_sharded_embed(mesh, axis)(dense, embed, feats, nbrs, mask)
        return emb[: graph.num_nodes]

    def has_host(self, host_id: str) -> bool:
        return host_id in self._node_index

    def predict_rtt_log_ms(self, src_ids: list[str], dst_ids: list[str]) -> np.ndarray:
        import jax.numpy as jnp

        # bucketed like every serving forward: the pairwise head compiles
        # once per ladder rung, not once per candidate-set size. Pads
        # point at node 0 — scored and discarded by the slice.
        n = len(src_ids)
        rows = bucket_rows(n)
        src = np.zeros((rows,), np.int32)
        dst = np.zeros((rows,), np.int32)
        src[:n] = [self._node_index[s] for s in src_ids]
        dst[:n] = [self._node_index[d] for d in dst_ids]
        return np.asarray(
            self._predict(self._params, self._emb, jnp.asarray(src), jnp.asarray(dst))
        )[:n]


class GRUScorer:
    """Next-piece-cost predictor around trained GRU params — the
    scheduler's ml evaluator consults it for model-based bad-node
    detection (a parent whose latest piece cost blows far past the
    prediction from its own history is flagged)."""

    def __init__(self, params: Any):
        from dragonfly2_tpu.models.gru import predict_next_cost

        self._params = _device_params(params)
        self._fn = _jit_once(predict_next_cost)

    def predict_next_log_cost(self, cost_prefixes_ms: list) -> np.ndarray:
        """[B] predicted next log1p piece cost (ms) from per-parent piece
        cost history prefixes — features built exactly like the offline
        extractor (schema/features.extract_piece_sequences: log1p cost,
        normalized piece position)."""
        import jax.numpy as jnp

        from dragonfly2_tpu.schema.features import (
            GRU_FEATURE_DIM,
            GRU_MAX_SEQ,
        )
        from dragonfly2_tpu.schema.records import MAX_PIECES_PER_PARENT

        b = len(cost_prefixes_ms)
        # bucketed history batch: pad rows are all-zero sequences with
        # length 0 (the scan keeps h0 for them), sliced off below — the
        # recurrence compiles once per ladder rung, not once per batch
        # size (retired the predict_next_cost retrace entry)
        rows = bucket_rows(b)
        seqs = np.zeros((rows, GRU_MAX_SEQ, GRU_FEATURE_DIM), np.float32)
        lengths = np.zeros((rows,), np.int32)
        # positions trained on are (true piece index + 1)/MAX, capped at
        # GRU_MAX_SEQ pieces per record — long live histories are tail-
        # truncated to the most recent costs with their TRUE positions,
        # clipped to the trained range (records never exceed MAX pieces,
        # so larger positions would be out-of-distribution)
        pos_cap = GRU_MAX_SEQ / MAX_PIECES_PER_PARENT
        for i, prefix in enumerate(cost_prefixes_ms):
            full = np.asarray(prefix, np.float64)
            start = max(0, len(full) - GRU_MAX_SEQ)
            p = full[start:]
            L = len(p)
            seqs[i, :L, 0] = np.log1p(p)
            pos = (start + np.arange(L) + 1) / MAX_PIECES_PER_PARENT
            seqs[i, :L, 1] = np.minimum(pos, pos_cap)
            lengths[i] = L
        return np.asarray(
            self._fn(self._params, jnp.asarray(seqs), jnp.asarray(lengths))
        )[:b]
