"""Trainer RPC service: the `Train` client-stream endpoint (reference
trainer/service/service_v1.go:59-162) plus payload-format negotiation.

First message keys the uploading scheduler (hostID = sha256(ip,hostname),
reference :87); each chunk appends to that host's dataset file — CSV
chunks to ``*.csv``, binary columnar chunks (schema/wire.py) to
``*.dfb`` — and on EOF the fit runs asynchronously (:155-159) so the
stream ack isn't held for minutes of training.

`Capabilities` advertises the payload formats this trainer accepts; the
announcer probes it before uploading and falls back to CSV when the RPC
is missing (old trainer) or the binary token is absent.
"""

from __future__ import annotations

import threading

from dragonfly2_tpu.rpc import gen  # noqa: F401
import trainer_pb2  # noqa: E402

from dragonfly2_tpu.schema import wire
from dragonfly2_tpu.trainer.storage import TrainerStorage
from dragonfly2_tpu.trainer.training import Training
from dragonfly2_tpu.trainer import metrics as M
from dragonfly2_tpu.utils import dflog
from dragonfly2_tpu.utils.idgen import host_id_v2

logger = dflog.get("trainer.rpc")

from dragonfly2_tpu.rpc.glue import TRAINER_SERVICE as SERVICE_NAME


class TrainerService:
    # newest-preferred order; Capabilities returns it verbatim
    TRAIN_FORMATS = (wire.FORMAT_NAME, wire.CSV_FORMAT_NAME)

    def __init__(self, storage: TrainerStorage, training: Training, synchronous: bool = False):
        self.storage = storage
        self.training = training
        # synchronous=True runs the fit inline (tests); production forks
        self.synchronous = synchronous
        self.train_total = 0
        self.train_failure_total = 0  # mirrored into Prometheus (metrics.py)

    def Capabilities(self, request, context):
        return trainer_pb2.CapabilitiesResponse(train_formats=list(self.TRAIN_FORMATS))

    def Train(self, request_iterator, context):
        ip = hostname = None
        host_id = None
        self.train_total += 1
        M.TRAIN_TOTAL.inc()
        try:
            for req in request_iterator:
                if host_id is None:
                    ip, hostname = req.ip, req.hostname
                    host_id = host_id_v2(ip, hostname)
                which = req.WhichOneof("request")
                if which == "train_mlp":
                    M.DATASET_BYTES_TOTAL.labels("download").inc(len(req.train_mlp.dataset))
                    self.storage.append_download(host_id, req.train_mlp.dataset)
                elif which == "train_gnn":
                    M.DATASET_BYTES_TOTAL.labels("topology").inc(len(req.train_gnn.dataset))
                    self.storage.append_network_topology(host_id, req.train_gnn.dataset)
                elif which == "train_mlp_binary":
                    M.DATASET_BYTES_TOTAL.labels("download_binary").inc(
                        len(req.train_mlp_binary.dataset)
                    )
                    self.storage.append_download_blocks(
                        host_id, req.train_mlp_binary.dataset
                    )
                elif which == "train_gnn_binary":
                    M.DATASET_BYTES_TOTAL.labels("topology_binary").inc(
                        len(req.train_gnn_binary.dataset)
                    )
                    self.storage.append_network_topology_blocks(
                        host_id, req.train_gnn_binary.dataset
                    )
        except Exception:
            self.train_failure_total += 1
            M.TRAIN_FAILURE_TOTAL.inc()
            if host_id is not None:
                # a broken stream may have landed half an upload round —
                # for the binary files a torn block would poison every
                # later append (its length prefix points into the new
                # data), so cut every file back to its last complete
                # round before the announcer retries
                self.storage.truncate_to_round(host_id)
            raise

        if host_id is not None:
            # stream complete: everything appended so far is whole rounds —
            # mark the byte boundary incremental offsets may commit up to
            self.storage.mark_download_round(host_id)
            if self.synchronous:
                self.training.train(ip, hostname)
            else:
                from dragonfly2_tpu.utils import tracing

                # the async fit must stay in the uploader's trace: hand
                # the rpc.Train span to the worker thread (contextvars
                # don't cross threads on their own)
                threading.Thread(
                    target=self._train_safely,
                    args=(ip, hostname, tracing.current_span()),
                    name="trainer.fit",
                    daemon=True,
                ).start()
        return trainer_pb2.TrainResponse()

    def _train_safely(self, ip: str, hostname: str, parent_span=None) -> None:
        from dragonfly2_tpu.utils import tracing

        try:
            with tracing.use_span(parent_span):
                outcome = self.training.train(ip, hostname)
            if not outcome.ok:
                self.train_failure_total += 1
        except Exception:
            self.train_failure_total += 1
            logger.exception("training run failed for %s/%s", ip, hostname)
