"""Training orchestration — the component the reference shipped as a TODO
stub (reference trainer/training/training.go:33-98).

``Training.train(ip, hostname)`` runs the flow the reference's comments
promise: load the uploading scheduler's dataset from storage → preprocess
into tensors → fit (MLP on download records, GraphSAGE on the probe
graph, concurrently like the reference's errgroup) → upload both models
with their evaluation metrics to the manager (CreateModel) → clear the
consumed dataset.

A failed fit must never poison serving: models upload as inactive and the
manager's activation step gates rollout (reference
manager/models/model.go:20-26 state machine).
"""

from __future__ import annotations

import concurrent.futures
import time
from dataclasses import dataclass, field
from typing import Any, Protocol

import numpy as np

from dragonfly2_tpu.schema import native, wire
from dragonfly2_tpu.schema.columnar import records_to_columns
from dragonfly2_tpu.schema.features import build_probe_graph, extract_pair_features
from dragonfly2_tpu.trainer.storage import TrainerStorage
from dragonfly2_tpu.trainer.train import FitConfig, GNNFitConfig, train_gnn, train_mlp
from dragonfly2_tpu.trainer import metrics as M
from dragonfly2_tpu.utils import dflog, flight
from dragonfly2_tpu.utils.idgen import gnn_model_id_v1, host_id_v2, mlp_model_id_v1

logger = dflog.get("trainer")

# round milestones in the flight ring: one event per fit leg (with its
# outcome) and one per training round — the trainer's black box
EV_FIT = flight.event_type("trainer.fit")
EV_ROUND = flight.event_type("trainer.round")


class BelowMinRecords(ValueError):
    """The dataset (or era) holds too few records / no trainable pairs
    to fit — the condition the mixed-era fall-through is allowed to
    treat as 'drop the sub-minimum tail'. Any OTHER error (corrupt
    data, decode failure) must propagate and never silently discard an
    untrained dataset."""


class ManagerClient(Protocol):
    """The slice of the manager API the trainer needs (CreateModel,
    reference manager_server_v1.go:800-899)."""

    def create_model(
        self,
        model_id: str,
        model_type: str,  # "mlp" | "gnn"
        ip: str,
        hostname: str,
        params: Any,  # parameter pytree (serialized by the client)
        evaluation: dict[str, float],
    ) -> None: ...


@dataclass
class TrainingConfig:
    mlp: FitConfig = field(default_factory=FitConfig)
    gnn: GNNFitConfig = field(default_factory=GNNFitConfig)
    gnn_max_degree: int = 16
    min_download_records: int = 1
    min_topology_records: int = 1
    clear_after_train: bool = True
    # incremental rounds: keep dataset files, commit consumed byte offsets
    # after each successful fit and decode only newly appended uploads
    # next round (implies clear_after_train=False; needs native decode)
    incremental: bool = False
    # streaming ingestion (trainer.ingest): decode/train overlapped in
    # bounded memory once the dataset file crosses the threshold — the
    # 1B-record path. Below it, the batch decode (one pass, in-memory
    # shuffle across epochs) fits fine and trains with the full FitConfig
    # schedule.
    streaming: bool = True
    streaming_threshold_bytes: int = 64 * 1024 * 1024
    streaming_passes: int = 2
    # decode producer pool; 0 = sized off host cores (ingest.default_workers)
    streaming_workers: int = 0
    # optimizer steps folded into one device dispatch (lax.scan
    # superbatch) — raise on high-latency device links
    streaming_steps_per_call: int = 1
    # wall bound for one streamed fit; None = unbounded
    streaming_time_budget_s: "float | None" = None
    # third model family: GRU next-piece-cost predictor over per-parent
    # piece-cost sequences (Download records carry up to 10 piece costs
    # per parent, reference scheduler/storage/types.go:143-176). ON by
    # default since round 5: the third model family — and the ml
    # evaluator's model-based bad-node detection that consumes it — must
    # train under production defaults, not behind a knob (round-4
    # verdict). gru_error still never gates .ok, so a host with too few
    # sequences just skips the leg.
    gru: bool = True
    gru_min_sequences: int = 8
    # RAM bound for the GRU leg: sequences kept per fit (~70 B each);
    # past this, more history stops improving the next-cost model
    gru_max_sequences: int = 1_000_000
    gru_config: FitConfig = field(
        default_factory=lambda: FitConfig(hidden_dims=(32,), batch_size=128, epochs=10)
    )
    # data-parallel fit mesh (ISSUE 15): with no explicit mesh, build a
    # pure ``dp`` mesh over every addressable device when more than one
    # chip is present — record shards train data-parallel over ICI, the
    # paper's north-star sentence, as the production DEFAULT rather than
    # a dormant parameter. Single-device hosts (and False) keep the
    # plain feed. CI's forced-host-platform 8-device image exercises the
    # dp>1 path (sharded puts, replicated params, donation, scan+dp
    # layout) through this same switch every round.
    auto_mesh: bool = True
    # jax.profiler trace dir per fit ("" = off); view with TensorBoard
    profile_dir: str = ""
    # elastic restart: per-(model, host) orbax snapshots under this dir
    # (trainer/checkpoint.py) — a mid-fit crash resumes from the last
    # epoch snapshot on the next round instead of retraining from zero;
    # "" disables (the reference's behavior)
    checkpoint_dir: str = ""


@dataclass
class TrainingOutcome:
    mlp_metrics: dict[str, float] | None = None
    gnn_metrics: dict[str, float] | None = None
    gru_metrics: dict[str, float] | None = None
    mlp_error: str | None = None
    gnn_error: str | None = None
    gru_error: str | None = None  # GRU is optional; never gates .ok

    @property
    def ok(self) -> bool:
        return self.mlp_error is None and self.gnn_error is None


class Training:
    def __init__(
        self,
        storage: TrainerStorage,
        manager_client: ManagerClient | None = None,
        config: TrainingConfig | None = None,
        mesh=None,
    ):
        self.storage = storage
        self.manager_client = manager_client
        self.config = config or TrainingConfig()
        if mesh is None and self.config.auto_mesh:
            mesh = self._auto_mesh()
        self.mesh = mesh

    @staticmethod
    def _auto_mesh():
        """Every-addressable-device dp mesh, or None on a single-device
        host / unusable backend — a mesh-construction failure degrades
        to the single-device fit, never fails training."""
        try:
            from dragonfly2_tpu.parallel.mesh import auto_dp_mesh

            return auto_dp_mesh()
        except Exception:
            logger.warning(
                "auto dp mesh unavailable; fitting single-device", exc_info=True
            )
            return None

    def train(self, ip: str, hostname: str) -> TrainingOutcome:
        """Fit MLP + GNN for one uploading scheduler host, concurrently
        (reference training.go:60-78 errgroup)."""
        from dragonfly2_tpu.utils import tracing

        host_id = host_id_v2(ip, hostname)
        outcome = TrainingOutcome()
        # the caller's span (rpc.Train when driven by the Train stream):
        # fit spans in the pool threads parent under it explicitly —
        # contextvars don't cross ThreadPoolExecutor boundaries
        parent_span = tracing.current_span()
        # which payload form the MLP leg consumed (None until decided):
        # the post-fit clear drops exactly that form, so other-era data
        # from a format switch survives to train next round
        mlp_info: dict = {}
        with concurrent.futures.ThreadPoolExecutor(max_workers=3) as pool:
            f_mlp = pool.submit(
                self._timed_fit, "mlp", parent_span, self._train_mlp,
                host_id, ip, hostname, mlp_info,
            )
            f_gnn = pool.submit(
                self._timed_fit, "gnn", parent_span, self._train_gnn,
                host_id, ip, hostname,
            )
            f_gru = (
                pool.submit(
                    self._timed_fit, "gru", parent_span, self._train_gru,
                    host_id, ip, hostname,
                )
                if self.config.gru
                else None
            )
            try:
                outcome.mlp_metrics = f_mlp.result()
            except Exception as e:
                logger.exception("trainMLP failed for %s", host_id)
                outcome.mlp_error = str(e)
            try:
                outcome.gnn_metrics = f_gnn.result()
            except Exception as e:
                logger.exception("trainGNN failed for %s", host_id)
                outcome.gnn_error = str(e)
            if f_gru is not None:
                try:
                    outcome.gru_metrics = f_gru.result()
                except Exception as e:
                    logger.exception("trainGRU failed for %s", host_id)
                    outcome.gru_error = str(e)

        EV_ROUND(
            host_id=host_id,
            ok=outcome.ok,
            mlp_error=outcome.mlp_error or "",
            gnn_error=outcome.gnn_error or "",
            gru_error=outcome.gru_error or "",
        )
        if self.config.clear_after_train and not self.config.incremental:
            # the reference retrains from scratch each round and drops
            # consumed uploads (trainer/trainer.go:156-161). Only the
            # payload form the MLP leg actually trained on is dropped —
            # after a scheduler format switch the other era's records
            # remain and train next round.
            if outcome.mlp_error is None:
                self.storage.clear_download(host_id, binary=mlp_info.get("binary"))
            if outcome.gnn_error is None:
                self.storage.clear_network_topology(host_id)
        return outcome

    def _timed_fit(self, model: str, parent_span, fn, *args):
        from dragonfly2_tpu.utils import tracing

        span = tracing.get("trainer").start_span("fit", parent=parent_span, model=model)
        profiler_cm = self._maybe_profile(model)
        t0 = time.perf_counter()
        # the fit span is active while fn runs so the ingest pipeline can
        # stamp its exemplars with the owning trace_id
        with M.FIT_DURATION.labels(model).time(), profiler_cm, tracing.use_span(span):
            try:
                result = fn(*args)
            except Exception as e:
                EV_FIT(
                    model=model, outcome="failure", error=str(e),
                    wall_s=round(time.perf_counter() - t0, 3),
                )
                span.end("error")
                M.FIT_TOTAL.labels(model, "failure").inc()
                raise
            EV_FIT(
                model=model, outcome="success",
                wall_s=round(time.perf_counter() - t0, 3),
            )
        span.end("ok")
        M.FIT_TOTAL.labels(model, "success").inc()
        # fit-freshness source for the cluster telemetry plane: the SLO
        # engine alarms when (now - this) outgrows the train cadence
        M.LAST_FIT_TIMESTAMP.labels(model).set(time.time())
        return result

    def _maybe_profile(self, model: str):
        """jax.profiler trace per fit when profile_dir is set — the
        XLA-side observability the reference's pprof flag provides for
        Go (cmd/dependency/dependency.go:95)."""
        import contextlib

        if not self.config.profile_dir:
            return contextlib.nullcontext()
        import jax

        return jax.profiler.trace(
            f"{self.config.profile_dir}/{model}", create_perfetto_trace=False
        )

    # -- trainMLP (reference training.go:92-98) ---------------------------
    def _train_mlp(
        self, host_id: str, ip: str, hostname: str, info: dict | None = None
    ) -> dict[str, float]:
        # payload selection: binary columnar stream (zero-parse ingest)
        # or CSV via the native fused decoder (numpy fallback) — all
        # paths produce identical tensors (the equivalence tests pin
        # this). When BOTH eras hold pending data (the scheduler
        # switched formats), the OLDER era — CSV — drains first: it gets
        # trained and cleared this round, and the binary data trains at
        # the next round; preferring binary unconditionally would leave
        # a CSV leftover untrained (and re-merged into the GRU/FedAvg
        # legs) forever under continuous binary uploads. The consumed
        # form is reported back via ``info`` so train() clears only it.
        has_csv = self._pending_bytes(host_id, binary=False) > 0
        has_bin = self._pending_bytes(host_id, binary=True) > 0
        if has_csv and has_bin:
            try:
                return self._train_mlp_from(
                    host_id, ip, hostname, binary=False, info=info
                )
            except BelowMinRecords as e:
                # the CSV-era leftover alone can't train (below the
                # min-record gate / no pairs): fall through to the
                # binary era INSTEAD of failing this host every round
                # while its binary data grows unboundedly. The
                # sub-minimum tail rides out with this round's clear
                # (info["binary"]=None → both forms dropped): the
                # operator's own gate declared it too small to train on.
                logger.warning(
                    "csv-era leftover for %s untrainable (%s);"
                    " training the binary era and dropping the tail",
                    host_id,
                    e,
                )
                if info is not None:
                    info["binary"] = None
                return self._train_mlp_from(
                    host_id, ip, hostname, binary=True, info=None
                )
        return self._train_mlp_from(
            host_id, ip, hostname, binary=has_bin, info=info
        )

    def _train_mlp_from(
        self,
        host_id: str,
        ip: str,
        hostname: str,
        binary: bool,
        info: dict | None = None,
    ) -> dict[str, float]:
        if info is not None:
            info["binary"] = binary
        path = (
            self.storage.download_blocks_path(host_id)
            if binary
            else self.storage.download_path(host_id)
        )
        offset = (
            self.storage.download_offset(host_id, binary=binary)
            if self.config.incremental
            else 0
        )
        # the boundary is marked by the Train service at stream EOF (locked
        # against appends), so the committed offset never lands mid-record
        # (mid-block for the binary file)
        boundary = self.storage.download_round_boundary(host_id, binary=binary)
        if self._use_streaming(path, offset, binary):
            return self._train_mlp_streaming(
                host_id, ip, hostname, path, offset, boundary, binary
            )
        if binary:
            pairs = wire.read_train_pairs(path, offset=offset, end=boundary)
        else:
            # bounded at the round boundary exactly like the binary and
            # streaming paths: the in-flight tail past it may be
            # truncated by a failed stream, and the offset commit below
            # wouldn't cover it anyway
            pairs = native.decode_pairs_file(path, offset=offset, end=boundary)
            if pairs is None:
                recs = [
                    r
                    for chunk in self.storage.iter_download_chunks(
                        host_id, max_bytes=boundary
                    )
                    for r in chunk
                ]
                pairs = extract_pair_features(records_to_columns(recs))
        if pairs.num_downloads < self.config.min_download_records:
            raise BelowMinRecords(
                f"{pairs.num_downloads} download records for host {host_id}"
                f" < min {self.config.min_download_records}"
            )
        if pairs.features.shape[0] == 0:
            raise BelowMinRecords("no trainable (download, parent) pairs")
        result = train_mlp(
            pairs.features,
            pairs.labels,
            mesh=self.mesh,
            config=self._fit_config(self.config.mlp, "mlp", host_id),
        )
        if self.manager_client is not None:
            self.manager_client.create_model(
                model_id=mlp_model_id_v1(ip, hostname),
                model_type="mlp",
                ip=ip,
                hostname=hostname,
                params=_to_host(result.params),
                evaluation=result.metrics,
            )
        if self.config.incremental:
            # commit only after a fully successful round (incl. upload) —
            # a crashed round re-decodes from the previous offset
            self.storage.commit_download_offset(host_id, boundary, binary=binary)
        return result.metrics

    def _fit_config(self, cfg, model: str, host_id: str):
        """Stamp the per-(model, host) checkpoint dir onto a fit config
        when elastic restart is enabled — the fit loop then snapshots
        every epoch and resumes from the newest snapshot after a crash
        (trainer/checkpoint.py; cleared on successful completion)."""
        if not self.config.checkpoint_dir:
            return cfg
        import os
        from dataclasses import replace

        return replace(
            cfg,
            checkpoint_dir=os.path.join(
                self.config.checkpoint_dir, f"{model}-{host_id}"
            ),
        )

    def _pending_bytes(self, host_id: str, binary: bool) -> int:
        import os

        path = (
            self.storage.download_blocks_path(host_id)
            if binary
            else self.storage.download_path(host_id)
        )
        offset = (
            self.storage.download_offset(host_id, binary=binary)
            if self.config.incremental
            else 0
        )
        try:
            return os.path.getsize(path) - offset
        except OSError:
            return 0

    def _use_streaming(self, path, offset: int, binary: bool) -> bool:
        import os

        # the binary stream needs no native library — frombuffer IS the
        # decoder; CSV streaming still rides the fused C++ parser
        if not self.config.streaming:
            return False
        if not binary and not native.available():
            return False
        try:
            pending = os.path.getsize(path) - offset
        except OSError:
            return False
        return pending >= self.config.streaming_threshold_bytes

    def _train_mlp_streaming(
        self,
        host_id: str,
        ip: str,
        hostname: str,
        path,
        offset: int,
        boundary: int,
        binary: bool = False,
    ) -> dict[str, float]:
        """Large-dataset path: bounded-memory overlapped decode+train
        (trainer.ingest.stream_train_mlp) instead of materializing every
        pair in host RAM. Holdout mse/mae stands in for train_mlp's eval
        split; the model/optimizer family is identical."""
        from dragonfly2_tpu.trainer.ingest import stream_train_mlp

        cfg = self.config.mlp
        if self.config.min_download_records > 1:
            # cheap pre-gate (batch path checks before fitting too): a
            # bounded decode stops as soon as min records are seen, so a
            # sparse host fails here instead of after the full multi-pass
            # fit on the chip. Binary counts from block headers alone —
            # no payload bytes are touched.
            if binary:
                rows = wire.count_records(
                    path, offset=offset, max_records=self.config.min_download_records
                )
            else:
                rows = 0
                for _, _, rows in native.stream_pairs_file(
                    path, offset=offset, max_records=self.config.min_download_records
                ):
                    pass
            if rows < self.config.min_download_records:
                raise BelowMinRecords(
                    f"{rows} download records for host {host_id}"
                    f" < min {self.config.min_download_records}"
                )
        eval_every = (
            max(2, round(1.0 / cfg.eval_fraction)) if cfg.eval_fraction > 0 else 0
        )
        params, stats = stream_train_mlp(
            path,
            passes=self.config.streaming_passes,
            batch_size=max(cfg.batch_size, 1),
            hidden_dims=cfg.hidden_dims,
            learning_rate=cfg.learning_rate,
            weight_decay=cfg.weight_decay,
            offset=offset,
            # bound at the committed round boundary, exactly like the
            # batch path: bytes past it belong to an in-flight upload
            # whose failure may TRUNCATE them mid-read, and training
            # them would double-count records the offset commit below
            # doesn't cover
            end=boundary,
            workers=self.config.streaming_workers,
            eval_every=eval_every,
            mesh=self.mesh,
            steps_per_call=self.config.streaming_steps_per_call,
            time_budget_s=self.config.streaming_time_budget_s,
            # a stalled fit forces one jax.profiler capture through the
            # same profile_dir plumbing on-demand profiling uses
            stall_profile_dir=self.config.profile_dir,
        )
        # rows counted once per pass — gate on a single pass's worth.
        # A time-budget truncation may have stopped mid-pass; dividing
        # by the CONFIGURED pass count would then undercount what was
        # actually seen and fail a legitimately-trained fit, and the
        # pre-gate above already enforced the minimum on real rows.
        rows = stats.download_records // max(self.config.streaming_passes, 1)
        if rows < self.config.min_download_records and not stats.truncated:
            raise BelowMinRecords(
                f"{rows} download records for host {host_id}"
                f" < min {self.config.min_download_records}"
            )
        if stats.pairs == 0:
            raise BelowMinRecords("no trainable (download, parent) pairs")
        logger.info(
            "streamed fit for %s: %d records, %d pairs, %d steps, %.0f rec/s",
            host_id,
            rows,
            stats.pairs,
            stats.steps,
            stats.records_per_s,
        )
        if self.manager_client is not None:
            self.manager_client.create_model(
                model_id=mlp_model_id_v1(ip, hostname),
                model_type="mlp",
                ip=ip,
                hostname=hostname,
                params=_to_host(params),
                evaluation=stats.metrics,
            )
        if self.config.incremental:
            self.storage.commit_download_offset(host_id, boundary, binary=binary)
        return stats.metrics

    # -- trainGNN (reference training.go:82-88) ---------------------------
    def _train_gnn(self, host_id: str, ip: str, hostname: str) -> dict[str, float]:
        # the probe graph is cumulative state (EWMA RTT edges), so the GNN
        # always rebuilds from the whole history — no offset decode here;
        # the incremental win is on the (much larger) download stream
        bpath = self.storage.network_topology_blocks_path(host_id)
        cpath = self.storage.network_topology_path(host_id)
        has_bin = bpath.exists() and bpath.stat().st_size > 0
        has_csv = cpath.exists() and cpath.stat().st_size > 0
        graph = None
        if has_bin and has_csv:
            # format-switch history: merge BOTH eras (CSV rows first —
            # they predate the binary era, and edge RTT is
            # last-write-wins in the graph build)
            from dragonfly2_tpu.schema.columnar import concat_columns

            cols = concat_columns(
                [
                    records_to_columns(self.storage.list_network_topology(host_id)),
                    wire.read_columns(
                        bpath,
                        kind=wire.KIND_TOPOLOGY,
                        end=self.storage.network_topology_round_boundary(
                            host_id, binary=True
                        ),
                    ),
                ]
            )
            graph = build_probe_graph(cols, max_degree=self.config.gnn_max_degree)
        elif has_bin:
            # binary topology upload: raw record columns, decoded straight
            # into the vectorized graph build (read bounded by the round
            # boundary so a concurrent upload's tail is never decoded)
            cols = wire.read_columns(
                bpath,
                kind=wire.KIND_TOPOLOGY,
                end=self.storage.network_topology_round_boundary(host_id, binary=True),
            )
            graph = build_probe_graph(cols, max_degree=self.config.gnn_max_degree)
        else:
            graph = native.build_probe_graph_file(
                cpath, max_degree=self.config.gnn_max_degree
            )
        if graph is None:
            recs = self.storage.list_network_topology(host_id)
            graph = build_probe_graph(
                records_to_columns(recs), max_degree=self.config.gnn_max_degree
            )
        if graph.num_records < self.config.min_topology_records:
            raise ValueError(
                f"{graph.num_records} network topology records for host {host_id}"
                f" < min {self.config.min_topology_records}"
            )
        result = train_gnn(
            graph, mesh=self.mesh, config=self._fit_config(self.config.gnn, "gnn", host_id)
        )
        if self.manager_client is not None:
            self.manager_client.create_model(
                model_id=gnn_model_id_v1(ip, hostname),
                model_type="gnn",
                ip=ip,
                hostname=hostname,
                params=_to_host(result.params),
                evaluation=result.metrics,
            )
        return result.metrics


    # -- trainGRU (piece time-series; our addition over the reference) -----
    def _train_gru(self, host_id: str, ip: str, hostname: str) -> dict[str, float]:
        from dragonfly2_tpu.schema.features import PieceSequences, extract_piece_sequences
        from dragonfly2_tpu.trainer.train import train_gru
        from dragonfly2_tpu.utils.idgen import gru_model_id_v1

        # sequence extraction is row-local (each Download record yields
        # its own per-parent sequences), so read the dataset in bounded
        # chunks instead of materializing the whole file — this leg must
        # hold the same memory bound as the streaming MLP path. The
        # sequence count is capped at the NEWEST gru_max_sequences:
        # records append in time order, so trimming from the front keeps
        # the fit tracking recent link behavior — in incremental mode
        # the file is never cleared, and an oldest-first cap would pin
        # the model to stale history forever.
        parts: list[PieceSequences] = []
        total = 0
        cap = self.config.gru_max_sequences
        # read only up to the committed round boundary: this generator
        # stays open across extraction pauses, and a concurrent Train
        # stream may be appending past it (same protocol as the MLP
        # leg's offset/boundary machinery). Binary uploads carry the
        # sequences pre-extracted in each train block; CSV re-extracts
        # chunk-wise — both sides of the same bounded-memory contract.
        # BOTH sources are consumed (CSV era first, it's older): a host
        # that switched payload formats keeps its whole recent history
        # feeding the next-cost model, and the newest-kept cap below
        # still bounds memory.
        import itertools

        seq_iters = []
        cpath = self.storage.download_path(host_id)
        if cpath.exists() and cpath.stat().st_size:
            boundary = self.storage.download_round_boundary(host_id)
            seq_iters.append(
                extract_piece_sequences(records_to_columns(chunk))
                for chunk in self.storage.iter_download_chunks(
                    host_id, max_bytes=boundary
                )
            )
        bpath = self.storage.download_blocks_path(host_id)
        if bpath.exists() and bpath.stat().st_size:
            seq_iters.append(
                wire.stream_gru_sequences(
                    bpath,
                    end=self.storage.download_round_boundary(host_id, binary=True),
                )
            )
        for s in itertools.chain(*seq_iters):
            if s.sequences.shape[0]:
                parts.append(s)
                total += s.sequences.shape[0]
            while parts and total - parts[0].sequences.shape[0] >= cap:
                total -= parts[0].sequences.shape[0]
                parts.pop(0)
        if parts:
            seqs = PieceSequences(
                sequences=np.concatenate([p.sequences for p in parts])[-cap:],
                labels=np.concatenate([p.labels for p in parts])[-cap:],
                lengths=np.concatenate([p.lengths for p in parts])[-cap:],
            )
        else:
            seqs = extract_piece_sequences({})
        n = seqs.sequences.shape[0]
        if n < self.config.gru_min_sequences:
            raise ValueError(
                f"{n} piece sequences for host {host_id}"
                f" < min {self.config.gru_min_sequences}"
            )
        result = train_gru(
            seqs.sequences,
            seqs.labels,
            lengths=seqs.lengths,
            mesh=self.mesh,
            config=self._fit_config(self.config.gru_config, "gru", host_id),
        )
        if self.manager_client is not None:
            self.manager_client.create_model(
                model_id=gru_model_id_v1(ip, hostname),
                model_type="gru",
                ip=ip,
                hostname=hostname,
                params=_to_host(result.params),
                evaluation=result.metrics,
            )
        return result.metrics

    # -- federated round over every uploading host's shard ----------------
    def federated_round(
        self, config: FitConfig | None = None
    ) -> "dict[str, float]":
        """Fit every host shard independently, FedAvg-merge, upload ONE
        global model (trainer/federation.py). Returns the merged model's
        cross-shard holdout metrics."""
        from dragonfly2_tpu.trainer.federation import federated_fit_mlp
        from dragonfly2_tpu.utils.idgen import federated_model_id_v1

        host_ids = self.storage.host_ids()
        if not host_ids:
            raise ValueError("no host shards in trainer storage")
        result = federated_fit_mlp(
            self.storage, host_ids, config=config or self.config.mlp, mesh=self.mesh
        )
        if self.manager_client is not None:
            self.manager_client.create_model(
                model_id=federated_model_id_v1(),
                model_type="mlp",
                ip="",
                hostname="federated",
                params=_to_host(result.params),
                evaluation=result.metrics,
            )
        return result.metrics


def _to_host(params) -> Any:
    """Device → host numpy pytree (for serialization/upload)."""
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x), params)
