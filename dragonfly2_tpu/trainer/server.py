"""Trainer server assembly (reference trainer/trainer.go:49-187): manager
client + storage + training core + gRPC server, Serve/Stop lifecycle."""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path

from dragonfly2_tpu.rpc import glue
from dragonfly2_tpu.trainer.service import SERVICE_NAME, TrainerService
from dragonfly2_tpu.trainer.storage import TrainerStorage
from dragonfly2_tpu.trainer.train import FitConfig, GNNFitConfig
from dragonfly2_tpu.trainer.training import Training, TrainingConfig
from dragonfly2_tpu.utils import dflog, flight, profiling

logger = dflog.get("trainer.server")


@dataclass
class TrainerServerConfig:
    data_dir: str = "/tmp/dragonfly2-trainer"
    listen: str = "127.0.0.1:0"
    manager_address: str = ""
    # fit knobs (subset; full control through TrainingConfig in-process)
    mlp_epochs: int = 3
    mlp_batch_size: int = 8192
    gnn_epochs: int = 60
    min_download_records: int = 1
    min_topology_records: int = 1
    # third model family: GRU over per-(task,parent) piece-cost
    # sequences extracted from the same download records (our addition
    # over the reference's MLP+GNN pair — see trainer/training.py). ON
    # by default since round 5, matching TrainingConfig.gru: the ml
    # evaluator's model-based bad-node detection must train under
    # production defaults.
    gru: bool = True
    gru_min_sequences: int = 8
    incremental: bool = False
    streaming: bool = True
    streaming_workers: int = 1
    # data-parallel fit mesh over every addressable chip when >1 is
    # present (TrainingConfig.auto_mesh; parallel.mesh.auto_dp_mesh) —
    # the ICI data-parallel fit is the production default, disable only
    # to pin a deploy to single-device fits
    auto_mesh: bool = True
    # on-demand jax.profiler capture: a non-empty dir writes one XLA
    # trace per fit under <profile_dir>/<model> (view with TensorBoard);
    # settable per-deploy via config file or DF_TRAINER_PROFILE_DIR
    profile_dir: str = ""
    # elastic restart: per-(model, host) fit snapshots under this dir —
    # a crashed fit resumes from its last epoch after the process comes
    # back (trainer/checkpoint.py); "" keeps the reference's
    # retrain-from-zero behavior
    checkpoint_dir: str = ""
    # run fits inline with the Train RPC (tests/debug) instead of async
    synchronous: bool = False
    # Prometheus /metrics endpoint (reference trainer :8000): -1 = disabled
    metrics_port: int = -1
    metrics_host: str = "127.0.0.1"
    # cluster telemetry push cadence (utils/telemetry.py); <= 0 disables
    telemetry_interval: float = 15.0
    # gRPC TLS: PEM file paths; tls_client_ca_file enforces mTLS
    tls_cert_file: str = ""
    tls_key_file: str = ""
    tls_client_ca_file: str = ""
    # client-side root (and optional mTLS client pair) for the manager
    manager_tls_ca_file: str = ""
    manager_tls_server_name: str = ""
    manager_tls_client_cert_file: str = ""
    manager_tls_client_key_file: str = ""


class TrainerServer:
    def __init__(self, config: TrainerServerConfig):
        self.cfg = config
        Path(config.data_dir).mkdir(parents=True, exist_ok=True)
        self.storage = TrainerStorage(config.data_dir)

        self._manager_channel = None
        manager_client = None
        if config.manager_address:
            self._manager_channel = glue.dial(
                config.manager_address,
                **glue.dial_tls_args(
                    config.manager_tls_ca_file,
                    config.manager_tls_server_name,
                    config.manager_tls_client_cert_file,
                    config.manager_tls_client_key_file,
                ),
            )
            from dragonfly2_tpu.manager.service import ManagerGrpcClientAdapter

            manager_client = ManagerGrpcClientAdapter(self._manager_channel)

        self.training = Training(
            self.storage,
            manager_client=manager_client,
            config=TrainingConfig(
                mlp=FitConfig(
                    epochs=config.mlp_epochs, batch_size=config.mlp_batch_size
                ),
                gnn=GNNFitConfig(epochs=config.gnn_epochs),
                min_download_records=config.min_download_records,
                min_topology_records=config.min_topology_records,
                gru=config.gru,
                gru_min_sequences=config.gru_min_sequences,
                incremental=config.incremental,
                clear_after_train=not config.incremental,
                streaming=config.streaming,
                streaming_workers=config.streaming_workers,
                auto_mesh=config.auto_mesh,
                profile_dir=config.profile_dir,
                checkpoint_dir=config.checkpoint_dir,
            ),
        )
        self.service = TrainerService(
            self.storage, self.training, synchronous=config.synchronous
        )
        self._grpc = None
        self.telemetry_reporter = None

    def serve(self) -> str:
        # flight recorder: stall/crash dumps + the Diagnose snapshot RPC
        flight.install("trainer")
        # continuous profiler: always-on sampler + phase ledger
        # (/debug/prof, Diagnose profile section, dump windows)
        profiling.install("trainer")
        flight.register_probe(
            "trainer.storage",
            lambda: {"host_ids": self.storage.host_ids()},
        )
        from dragonfly2_tpu.rpc.diagnose import DiagnoseService

        self._grpc, port = glue.serve(
            {SERVICE_NAME: self.service, glue.DIAGNOSE_SERVICE: DiagnoseService()},
            self.cfg.listen,
            **glue.serve_tls_args(
                self.cfg.tls_cert_file, self.cfg.tls_key_file, self.cfg.tls_client_ca_file
            ),
        )
        addr = f"{self.cfg.listen.rsplit(':', 1)[0]}:{port}"
        from dragonfly2_tpu.utils.metrics import set_build_info

        set_build_info("trainer")
        if self._manager_channel is not None and self.cfg.telemetry_interval > 0:
            # cluster telemetry: ingest throughput + fit freshness to the
            # manager over the channel already dialed for CreateModel
            from dragonfly2_tpu.utils.telemetry import TelemetryReporter
            from dragonfly2_tpu.version import __version__

            def sections():
                return {
                    "build": {"service": "trainer", "version": __version__},
                    "endpoints": {
                        "rpc": addr,
                        "metrics": getattr(self, "metrics_addr", "") or "",
                    },
                }

            self.telemetry_reporter = TelemetryReporter(
                glue.ServiceClient(self._manager_channel, glue.TELEMETRY_SERVICE),
                service="trainer",
                instance=addr,
                prefixes=("dragonfly_trainer_",),
                interval=self.cfg.telemetry_interval,
                collect_sections=sections,
            )
            self.telemetry_reporter.start()
        if self.cfg.metrics_port >= 0:
            from dragonfly2_tpu.trainer import metrics  # noqa: F401
            from dragonfly2_tpu.utils.metrics import MetricsServer, default_registry

            self._metrics = MetricsServer(default_registry, host=self.cfg.metrics_host, port=self.cfg.metrics_port)
            # liveness on the scrape port (/healthz): the gRPC plane up
            self._metrics.register_health("trainer", lambda: self._grpc is not None)
            self.metrics_addr = self._metrics.start()
            logger.info("trainer metrics on %s", self.metrics_addr)
        logger.info("trainer gRPC on %s", addr)
        return addr

    def stop(self) -> None:
        if self.telemetry_reporter is not None:
            self.telemetry_reporter.stop()
        if getattr(self, "_metrics", None) is not None:
            self._metrics.stop()
        if self._grpc is not None:
            self._grpc.stop(grace=2).wait(5)
        if self._manager_channel is not None:
            self._manager_channel.close()
        # the reference clears trainer storage on shutdown
        # (trainer/trainer.go:156-161) unless running incremental rounds
        if not self.cfg.incremental:
            self.storage.clear()


def build(config_path, overrides):
    from dragonfly2_tpu.cli.config import load_config

    cfg = load_config(
        TrainerServerConfig, config_path, env_prefix="DF_TRAINER", overrides=overrides
    )
    return TrainerServer(cfg)
