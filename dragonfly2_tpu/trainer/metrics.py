"""Trainer Prometheus series (reference trainer/metrics/metrics.go:38-52
plus fit-duration/ingest visibility the TPU trainer adds)."""

from dragonfly2_tpu.utils import profiling
from dragonfly2_tpu.utils.metrics import default_registry as _r

TRAIN_TOTAL = _r.counter("trainer_train_total", "Train RPC streams accepted")
TRAIN_FAILURE_TOTAL = _r.counter(
    "trainer_train_failure_total", "Train RPC streams that failed"
)
FIT_TOTAL = _r.counter("trainer_fit_total", "Model fits", ("model", "outcome"))
FIT_DURATION = _r.histogram(
    "trainer_fit_duration_seconds", "Fit wall time", ("model",),
    buckets=(0.1, 0.5, 1, 5, 15, 60, 300, 1200, 3600, float("inf")),
)
INGEST_RECORDS_TOTAL = _r.counter(
    "trainer_ingest_records_total", "Download records decoded for training"
)
# Live pipeline splits of the streaming train loop (trainer/ingest.py),
# observed per shard / per superbatch WHILE a fit runs — the same
# decode/transfer/compute attribution StreamStats totals per run, but
# scrapeable mid-fit. Exemplars carry the owning fit's trace_id
# (OpenMetrics exposition), so a slow bucket links to its trace.
_INGEST_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, float("inf"),
)
INGEST_DECODE_WAIT_SECONDS = _r.histogram(
    "trainer_ingest_decode_wait_seconds",
    "Packing thread blocked on the decode queue, per shard",
    buckets=_INGEST_BUCKETS,
)
INGEST_H2D_SECONDS = _r.histogram(
    "trainer_ingest_h2d_seconds",
    "Host-to-device superbatch transfer dispatch",
    buckets=_INGEST_BUCKETS,
)
INGEST_STEP_SECONDS = _r.histogram(
    "trainer_ingest_step_seconds",
    "Compiled train-step dispatch + prior-step confirmation, per superbatch",
    buckets=_INGEST_BUCKETS,
)
# the packing thread blocked on the superbatch pool — the single
# largest wall component in BENCH_r06 (~79%), live per superbatch like
# its decode_wait/h2d/step siblings, exemplars carrying the fit's
# trace_id the same way
INGEST_BUFFER_WAIT_SECONDS = _r.histogram(
    "trainer_ingest_buffer_wait_seconds",
    "Packing thread blocked on the superbatch buffer pool, per superbatch",
    buckets=_INGEST_BUCKETS,
)
# device-side attribution for the jit-witness taps
# (hack/dfanalyze/jitwitness.py): transfers are timed, compiles are
# count-markers — both land in the dfprof phase ledger per fit
PH_JIT_COMPILE = profiling.phase_type("trainer.jit_compile")
PH_DEVICE_TRANSFER = profiling.phase_type("trainer.device_transfer")
DATASET_BYTES_TOTAL = _r.counter(
    "trainer_dataset_bytes_total", "Dataset bytes received on Train streams", ("kind",)
)
# dispatch-plane hygiene counters, fed by the jit witness's bench taps
# (hack/dfanalyze/jitwitness.py): XLA compilations and host→device
# conversions observed while a tap is armed. Steady state on a warm fit
# is ZERO recompiles and one H2D per superbatch — a moving recompile
# counter mid-fit is the retrace storm bench.py's
# jit_recompiles_per_fit key exists to catch.
JIT_RECOMPILES_TOTAL = _r.counter(
    "trainer_jit_recompiles_total",
    "XLA compilations observed by the jit witness taps",
)
H2D_TRANSFERS_TOTAL = _r.counter(
    "trainer_h2d_transfers_total",
    "Host-to-device conversions observed by the jit witness taps",
)
# unix timestamp of the last SUCCESSFUL fit per model: the telemetry
# plane's fit-freshness source (freshness = now - value; 0 = never) —
# a gauge, so the manager can compute staleness without rate math
LAST_FIT_TIMESTAMP = _r.gauge(
    "trainer_last_fit_timestamp_seconds",
    "Unix time of the last successful fit",
    ("model",),
)
