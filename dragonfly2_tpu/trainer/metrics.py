"""Trainer Prometheus series (reference trainer/metrics/metrics.go:38-52
plus fit-duration/ingest visibility the TPU trainer adds)."""

from dragonfly2_tpu.utils.metrics import default_registry as _r

TRAIN_TOTAL = _r.counter("trainer_train_total", "Train RPC streams accepted")
TRAIN_FAILURE_TOTAL = _r.counter(
    "trainer_train_failure_total", "Train RPC streams that failed"
)
FIT_TOTAL = _r.counter("trainer_fit_total", "Model fits", ("model", "outcome"))
FIT_DURATION = _r.histogram(
    "trainer_fit_duration_seconds", "Fit wall time", ("model",),
    buckets=(0.1, 0.5, 1, 5, 15, 60, 300, 1200, 3600, float("inf")),
)
INGEST_RECORDS_TOTAL = _r.counter(
    "trainer_ingest_records_total", "Download records decoded for training"
)
DATASET_BYTES_TOTAL = _r.counter(
    "trainer_dataset_bytes_total", "Dataset bytes received on Train streams", ("kind",)
)
