"""The TPU trainer — the compute plane.

Fills the reference's empty training core (reference
trainer/training/training.go:33-98: `Train` runs `trainGNN` + `trainMLP`,
both TODO-only) with real JAX/XLA fit loops:

  train.py       fit loops (MLP pair scorer, GraphSAGE edge-RTT, GRU)
  pipeline.py    record shards → device-resident batch tensors
  checkpoint.py  orbax save/restore of model+optimizer state
  service.py     the `Train` client-stream RPC service (rpc plane)
  storage.py     per-source-host dataset files (trainer/storage parity)
"""
