"""Trainer checkpoint/resume (orbax) — elastic restart for fit loops.

The reference has no trainer checkpoints at all: its fit is a stub and
each round retrains from the uploaded CSVs, deleting storage on shutdown
(reference trainer/trainer.go:156-161, SURVEY.md §5.4). At TPU scale a
1B-record round is minutes of work worth protecting: fit loops snapshot
(params, opt_state, epoch) every epoch through an orbax CheckpointManager
and resume from the latest snapshot after a crash — same rng schedule,
so an interrupted-and-resumed fit reproduces the uninterrupted one.

Also here: resumable ingestion offsets. When a trainer runs incremental
rounds (clear_after_train=False), the byte offset consumed per dataset
file is committed after a successful fit, so the next round decodes only
newly appended upload rounds (each upload is a complete CSV whose header
re-keys the native decoder mid-stream).
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Any

import numpy as np

from dragonfly2_tpu.utils import dflog

logger = dflog.get("trainer.ckpt")


class FitCheckpointer:
    """Orbax-backed (params, opt_state, epoch) snapshots for one fit run.

    Layout: ``<dir>/<step>/...`` managed by ocp.CheckpointManager with
    bounded retention. `restore_latest` needs the abstract structure of
    the state (a like-tree), which fit loops have by construction.
    """

    def __init__(self, directory: str | Path, max_to_keep: int = 2):
        import orbax.checkpoint as ocp

        self._dir = Path(directory).resolve()
        self._dir.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._ocp = ocp

    def save(self, epoch: int, state: Any) -> None:
        """Snapshot state after ``epoch`` (blocking — fit epochs are long
        compared to a snapshot write)."""
        self._mgr.save(epoch, args=self._ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()

    def latest_epoch(self) -> int | None:
        return self._mgr.latest_step()

    def restore_latest(self, like: Any) -> tuple[int, Any] | None:
        """→ (epoch, state) of the newest snapshot, or None. ``like`` is a
        matching pytree of arrays providing structure/shape/dtype."""
        step = self._mgr.latest_step()
        if step is None:
            return None
        import jax

        abstract = jax.tree.map(self._ocp.tree.to_shape_dtype_struct, like)
        state = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(abstract)
        )
        return int(step), state

    def clear(self) -> None:
        """Delete every snapshot — called on successful fit completion so
        the next round trains fresh instead of resuming into zero epochs."""
        for step in list(self._mgr.all_steps()):
            self._mgr.delete(step)

    def close(self) -> None:
        self._mgr.close()


# ---------------------------------------------------------------------------
# Resumable ingestion offsets
# ---------------------------------------------------------------------------


class OffsetLedger:
    """Byte offsets consumed per dataset file, committed only after a
    successful fit — a crashed round re-decodes from the previous commit
    (at-least-once ingestion; training is idempotent over a round)."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._lock = threading.Lock()
        self._offsets: dict[str, int] = {}
        if self.path.exists():
            try:
                self._offsets = json.loads(self.path.read_text())
            except (OSError, json.JSONDecodeError) as e:
                logger.warning("offset ledger unreadable, starting fresh: %s", e)

    def get(self, key: str) -> int:
        with self._lock:
            return int(self._offsets.get(key, 0))

    def has(self, key: str) -> bool:
        """Whether an entry exists — callers that must distinguish "never
        committed" from "committed at 0" (round-boundary recovery) need
        more than get()'s 0 default."""
        with self._lock:
            return key in self._offsets

    def commit(self, key: str, offset: int) -> None:
        with self._lock:
            self._offsets[key] = int(offset)
            tmp = self.path.with_suffix(".tmp")
            tmp.write_text(json.dumps(self._offsets, indent=0, sort_keys=True))
            tmp.replace(self.path)

    def reset(self, key: str) -> None:
        """Drop a file's offset (after the file itself is cleared)."""
        with self._lock:
            if key in self._offsets:
                del self._offsets[key]
                tmp = self.path.with_suffix(".tmp")
                tmp.write_text(json.dumps(self._offsets, indent=0, sort_keys=True))
                tmp.replace(self.path)


def params_equal(a: Any, b: Any, atol: float = 0.0) -> bool:
    """Structural + numeric equality of two parameter pytrees (test/debug
    helper for resume-reproducibility checks)."""
    import jax

    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    if ta != tb or len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        if not np.allclose(np.asarray(x), np.asarray(y), atol=atol):
            return False
    return True
