"""Trainer storage: per-source-host dataset files (reference
trainer/storage/storage.go:44-148).

The Train stream appends raw chunks under the uploading scheduler's
hostID, one file per dataset AND payload format:

- ``download_<hostID>.csv`` / ``networktopology_<hostID>.csv`` — the CSV
  fallback (old schedulers, reference-compatible bytes);
- ``download_<hostID>.dfb`` / ``networktopology_<hostID>.dfb`` — the
  binary columnar block stream (schema/wire.py), the zero-parse fast
  path. Blocks are self-delimiting, so chunked appends are always a
  valid stream.

The fit loops read whichever file has pending data (binary preferred).
Per-host keying is what makes multi-cluster federation natural: one
host's files = one FedAvg shard.
"""

from __future__ import annotations

import threading
from pathlib import Path

import csv

from dragonfly2_tpu.schema import records as R
from dragonfly2_tpu.trainer.checkpoint import OffsetLedger


class TrainerStorage:
    def __init__(self, directory: str | Path):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        # byte offsets consumed per dataset file (incremental rounds)
        self.offsets = OffsetLedger(self.dir / "offsets.json")
        # last complete upload-round boundary per file (marked by the Train
        # service at stream EOF, read under the same lock appends hold) —
        # offsets committed here can never land mid-record/mid-block.
        # PERSISTED: truncate_to_round consults this after a failed
        # stream, and an in-memory-only map would make a restart + one
        # failed upload destroy every previously-accumulated round.
        self.rounds = OffsetLedger(self.dir / "rounds.json")
        # files whose tail has been verified clean this process —
        # crash-mid-stream recovery (see _ensure_clean_tail)
        self._tail_checked: set[str] = set()

    def download_path(self, host_id: str) -> Path:
        return self.dir / f"download_{host_id}.csv"

    def network_topology_path(self, host_id: str) -> Path:
        return self.dir / f"networktopology_{host_id}.csv"

    def download_blocks_path(self, host_id: str) -> Path:
        return self.dir / f"download_{host_id}.dfb"

    def network_topology_blocks_path(self, host_id: str) -> Path:
        return self.dir / f"networktopology_{host_id}.dfb"

    def _round_files(self, host_id: str) -> list[Path]:
        return [
            self.download_path(host_id),
            self.network_topology_path(host_id),
            self.download_blocks_path(host_id),
            self.network_topology_blocks_path(host_id),
        ]

    # -- stream append (Train RPC demux target) ---------------------------
    def _safe_boundary(self, path: Path) -> int:
        """The byte count worth keeping after a failed/interrupted
        stream: the persisted round boundary when one exists (bytes past
        it are a partial round the announcer's retry re-ships), else a
        content-derived parse-safe cut — the SAME rule for in-process
        failures (truncate_to_round) and crash recovery
        (_ensure_clean_tail), so neither path keeps half-rounds the
        other would drop."""
        if self.rounds.has(path.name):
            return self.rounds.get(path.name)
        return self._content_boundary(path)

    def _ensure_clean_tail(self, path: Path) -> None:
        """Once per file per process, before the first append: drop any
        partial tail a PREVIOUS process left by dying mid-stream (the
        in-process failure path runs truncate_to_round, but a killed
        trainer never does). Without this, appending complete data after
        a torn block poisons the file forever — the torn block's length
        prefix points into the new bytes — and even block-complete
        half-rounds would be double-trained once the retry re-ships
        them. Called under ``self._lock``."""
        if path.name in self._tail_checked:
            return
        self._tail_checked.add(path.name)
        if not path.exists():
            return
        good = self._safe_boundary(path)
        if good < path.stat().st_size:
            with open(path, "ab") as f:
                f.truncate(good)
        if good == 0:
            path.unlink(missing_ok=True)

    def _append(self, path: Path, chunk: bytes) -> None:
        with self._lock:
            self._ensure_clean_tail(path)
            with open(path, "ab") as f:
                f.write(chunk)

    def append_download(self, host_id: str, chunk: bytes) -> None:
        self._append(self.download_path(host_id), chunk)

    def append_network_topology(self, host_id: str, chunk: bytes) -> None:
        self._append(self.network_topology_path(host_id), chunk)

    def append_download_blocks(self, host_id: str, chunk: bytes) -> None:
        self._append(self.download_blocks_path(host_id), chunk)

    def append_network_topology_blocks(self, host_id: str, chunk: bytes) -> None:
        self._append(self.network_topology_blocks_path(host_id), chunk)

    # -- reads ------------------------------------------------------------
    def list_download(self, host_id: str) -> list[R.DownloadRecord]:
        return list(self._iter_concatenated(self.download_path(host_id), R.DownloadRecord))

    def list_network_topology(self, host_id: str) -> list[R.NetworkTopologyRecord]:
        return list(
            self._iter_concatenated(
                self.network_topology_path(host_id), R.NetworkTopologyRecord
            )
        )

    def iter_download_chunks(
        self,
        host_id: str,
        chunk_records: int = 50_000,
        max_bytes: int | None = None,
    ):
        """Yield lists of ≤ ``chunk_records`` DownloadRecords — the
        bounded-memory read of an arbitrarily large dataset file (the
        GRU leg consumes this chunk-wise; the MLP leg streams through
        the native decoder instead). ``max_bytes`` stops the read at a
        record-aligned byte boundary (pass a committed round boundary):
        this generator stays open across long extraction pauses, so
        without a bound a concurrent Train-stream append could be read
        mid-write as a torn trailing row."""
        chunk: list = []
        for rec in self._iter_concatenated(
            self.download_path(host_id), R.DownloadRecord, max_bytes=max_bytes
        ):
            chunk.append(rec)
            if len(chunk) >= chunk_records:
                yield chunk
                chunk = []
        if chunk:
            yield chunk

    @staticmethod
    def _iter_concatenated(path: Path, cls: type, max_bytes: int | None = None):
        """Parse a file made of appended CSV uploads: every upload round
        (and every rotated backup within a round) starts with its own
        header line, so embedded headers must be skipped, not parsed as
        data rows. A generator so callers can bound memory. With
        ``max_bytes``, only lines that END at or before that offset are
        parsed — callers pass a record-aligned boundary, so no torn or
        in-flight trailing data is ever decoded."""
        if not path.exists():
            return
        with open(path, "rb") as bf:
            def lines():
                consumed = 0
                for raw in bf:
                    consumed += len(raw)
                    if max_bytes is not None and consumed > max_bytes:
                        return
                    yield raw.decode("utf-8", errors="replace")

            reader = csv.reader(lines())
            header: list[str] | None = None
            for row in reader:
                if header is None:
                    header = row
                    continue
                # embedded header from a later upload/backup — match on
                # the first column name, not the whole row, so a header
                # that drifted between scheduler versions is re-adopted
                # instead of being parsed as a data row against stale
                # column positions
                if row and header and row[0] == header[0]:
                    header = row
                    continue
                yield R.unflatten(cls, dict(zip(header, row)))

    def host_ids(self) -> list[str]:
        """Every host with at least one dataset file (the FedAvg shards),
        whichever payload format it uploaded in."""
        ids = set()
        for pattern, prefix in (
            ("download_*.csv", "download_"),
            ("networktopology_*.csv", "networktopology_"),
            ("download_*.dfb", "download_"),
            ("networktopology_*.dfb", "networktopology_"),
        ):
            for p in self.dir.glob(pattern):
                ids.add(p.stem.removeprefix(prefix))
        return sorted(ids)

    # -- resumable ingestion offsets --------------------------------------
    @staticmethod
    def _offset_key(host_id: str, binary: bool) -> str:
        return f"download_blocks_{host_id}" if binary else f"download_{host_id}"

    def download_offset(self, host_id: str, binary: bool = False) -> int:
        return self.offsets.get(self._offset_key(host_id, binary))

    def commit_download_offset(
        self, host_id: str, offset: int, binary: bool = False
    ) -> None:
        self.offsets.commit(self._offset_key(host_id, binary), offset)

    def mark_download_round(self, host_id: str) -> int:
        """Record the current size of every dataset file for this host as
        a round boundary — called by the Train service once a stream
        finishes, so boundaries always sit between complete uploads (and,
        for the binary files, between complete blocks). Returns the
        download boundary of the binary file when it has data, else of
        the CSV file — the same preference order the fits use."""
        with self._lock:
            for path in self._round_files(host_id):
                size = path.stat().st_size if path.exists() else 0
                self.rounds.commit(path.name, size)
            bpath = self.download_blocks_path(host_id)
            if bpath.exists() and bpath.stat().st_size:
                return self.rounds.get(bpath.name)
            return self.rounds.get(self.download_path(host_id).name)

    def download_round_boundary(self, host_id: str, binary: bool = False) -> int:
        """Last marked round boundary; falls back to a locked size stat
        (direct-API callers that never interleave appends with training)."""
        path = (
            self.download_blocks_path(host_id)
            if binary
            else self.download_path(host_id)
        )
        return self._boundary_of(path)

    def network_topology_round_boundary(self, host_id: str, binary: bool = False) -> int:
        path = (
            self.network_topology_blocks_path(host_id)
            if binary
            else self.network_topology_path(host_id)
        )
        return self._boundary_of(path)

    def _boundary_of(self, path: Path) -> int:
        with self._lock:
            if self.rounds.has(path.name):
                return self.rounds.get(path.name)
            return path.stat().st_size if path.exists() else 0

    @staticmethod
    def _content_boundary(path: Path) -> int:
        """A parse-safe cut point derived from file CONTENT — the
        recovery fallback when no round boundary was ever persisted
        (ledger predates the file, or was lost): the end of the last
        complete block for ``.dfb``, the byte after the last newline for
        CSV. Data before it decodes cleanly; it may include complete
        chunks of the failed stream, which the announcer's retry then
        re-ships (at-least-once, same as the offset ledger's contract)."""
        if path.suffix == ".dfb":
            from dragonfly2_tpu.schema import wire

            try:
                extents = wire.scan_block_extents(path)
            except Exception:
                return 0  # leading corruption: nothing salvageable
            return extents[-1][1] if extents else 0
        # CSV: last newline at EVEN RFC4180 quote parity — a newline
        # inside a quoted field is data (same rule as
        # native.split_file_spans), and cutting there would leave a
        # dangling open quote that swallows every later append into one
        # giant field. One forward streaming pass, bounded memory
        # (bytes.count/rfind are memchr-speed; this runs only in the
        # rare recovery path).
        last_even_nl = 0
        quotes = 0
        pos = 0
        chunk_size = 1 << 20
        with open(path, "rb") as f:
            while True:
                chunk = f.read(chunk_size)
                if not chunk:
                    break
                at = len(chunk)
                while True:
                    nl = chunk.rfind(b"\n", 0, at)
                    if nl < 0:
                        break
                    if (quotes + chunk.count(b'"', 0, nl)) % 2 == 0:
                        last_even_nl = pos + nl + 1
                        break
                    at = nl
                quotes += chunk.count(b'"')
                pos += len(chunk)
        return last_even_nl

    def truncate_to_round(self, host_id: str) -> None:
        """Drop the partial tail of a FAILED Train stream: every dataset
        file is cut back to its last persisted round boundary — or, when
        none was ever recorded for it, to a content-derived parse-safe
        point. Without this, the next successful upload would append
        complete data AFTER a torn half-round — which a CSV read
        mis-parses as one garbage row and a block scan cannot get past
        at all (the torn block's length prefix points into the new
        data)."""
        with self._lock:
            for path in self._round_files(host_id):
                if not path.exists():
                    continue
                boundary = self._safe_boundary(path)
                if path.stat().st_size > boundary:
                    with open(path, "ab") as f:
                        f.truncate(boundary)
                if boundary == 0:
                    path.unlink(missing_ok=True)

    # -- cleanup ----------------------------------------------------------
    def clear_download(self, host_id: str, binary: "bool | None" = None) -> None:
        """Drop consumed download data. ``binary=None`` clears both
        payload forms; True/False clears only that form — the training
        round clears exactly what its MLP leg consumed, so a host that
        switched formats keeps its other-era records for the next round
        instead of losing them."""
        targets = {
            None: (self.download_path(host_id), self.download_blocks_path(host_id)),
            False: (self.download_path(host_id),),
            True: (self.download_blocks_path(host_id),),
        }[binary]
        for p in targets:
            p.unlink(missing_ok=True)
            self.rounds.reset(p.name)
        if binary in (None, False):
            self.offsets.reset(self._offset_key(host_id, binary=False))
        if binary in (None, True):
            self.offsets.reset(self._offset_key(host_id, binary=True))

    def clear_network_topology(self, host_id: str) -> None:
        for p in (
            self.network_topology_path(host_id),
            self.network_topology_blocks_path(host_id),
        ):
            p.unlink(missing_ok=True)
            self.rounds.reset(p.name)
        self.offsets.reset(f"networktopology_{host_id}")

    def clear(self) -> None:
        for host_id in self.host_ids():
            self.clear_download(host_id)
            self.clear_network_topology(host_id)
