"""Trainer storage: per-source-host dataset files (reference
trainer/storage/storage.go:44-148).

The Train stream appends raw CSV chunks under the uploading scheduler's
hostID — ``download_<hostID>.csv`` / ``networktopology_<hostID>.csv`` —
and the fit loops list them back as records. Per-host keying is what makes
multi-cluster federation natural: one host's files = one FedAvg shard.
"""

from __future__ import annotations

import threading
from pathlib import Path

import csv

from dragonfly2_tpu.schema import records as R
from dragonfly2_tpu.trainer.checkpoint import OffsetLedger


class TrainerStorage:
    def __init__(self, directory: str | Path):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        # byte offsets consumed per dataset file (incremental rounds)
        self.offsets = OffsetLedger(self.dir / "offsets.json")
        # last complete upload-round boundary per file (marked by the Train
        # service at stream EOF, read under the same lock appends hold) —
        # offsets committed here can never land mid-record
        self._round_boundaries: dict[str, int] = {}

    def download_path(self, host_id: str) -> Path:
        return self.dir / f"download_{host_id}.csv"

    def network_topology_path(self, host_id: str) -> Path:
        return self.dir / f"networktopology_{host_id}.csv"

    # -- stream append (Train RPC demux target) ---------------------------
    def append_download(self, host_id: str, chunk: bytes) -> None:
        with self._lock, open(self.download_path(host_id), "ab") as f:
            f.write(chunk)

    def append_network_topology(self, host_id: str, chunk: bytes) -> None:
        with self._lock, open(self.network_topology_path(host_id), "ab") as f:
            f.write(chunk)

    # -- reads ------------------------------------------------------------
    def list_download(self, host_id: str) -> list[R.DownloadRecord]:
        return list(self._iter_concatenated(self.download_path(host_id), R.DownloadRecord))

    def list_network_topology(self, host_id: str) -> list[R.NetworkTopologyRecord]:
        return list(
            self._iter_concatenated(
                self.network_topology_path(host_id), R.NetworkTopologyRecord
            )
        )

    def iter_download_chunks(
        self,
        host_id: str,
        chunk_records: int = 50_000,
        max_bytes: int | None = None,
    ):
        """Yield lists of ≤ ``chunk_records`` DownloadRecords — the
        bounded-memory read of an arbitrarily large dataset file (the
        GRU leg consumes this chunk-wise; the MLP leg streams through
        the native decoder instead). ``max_bytes`` stops the read at a
        record-aligned byte boundary (pass a committed round boundary):
        this generator stays open across long extraction pauses, so
        without a bound a concurrent Train-stream append could be read
        mid-write as a torn trailing row."""
        chunk: list = []
        for rec in self._iter_concatenated(
            self.download_path(host_id), R.DownloadRecord, max_bytes=max_bytes
        ):
            chunk.append(rec)
            if len(chunk) >= chunk_records:
                yield chunk
                chunk = []
        if chunk:
            yield chunk

    @staticmethod
    def _iter_concatenated(path: Path, cls: type, max_bytes: int | None = None):
        """Parse a file made of appended CSV uploads: every upload round
        (and every rotated backup within a round) starts with its own
        header line, so embedded headers must be skipped, not parsed as
        data rows. A generator so callers can bound memory. With
        ``max_bytes``, only lines that END at or before that offset are
        parsed — callers pass a record-aligned boundary, so no torn or
        in-flight trailing data is ever decoded."""
        if not path.exists():
            return
        with open(path, "rb") as bf:
            def lines():
                consumed = 0
                for raw in bf:
                    consumed += len(raw)
                    if max_bytes is not None and consumed > max_bytes:
                        return
                    yield raw.decode("utf-8", errors="replace")

            reader = csv.reader(lines())
            header: list[str] | None = None
            for row in reader:
                if header is None:
                    header = row
                    continue
                # embedded header from a later upload/backup — match on
                # the first column name, not the whole row, so a header
                # that drifted between scheduler versions is re-adopted
                # instead of being parsed as a data row against stale
                # column positions
                if row and header and row[0] == header[0]:
                    header = row
                    continue
                yield R.unflatten(cls, dict(zip(header, row)))

    def host_ids(self) -> list[str]:
        """Every host with at least one dataset file (the FedAvg shards)."""
        ids = set()
        for p in self.dir.glob("download_*.csv"):
            ids.add(p.stem.removeprefix("download_"))
        for p in self.dir.glob("networktopology_*.csv"):
            ids.add(p.stem.removeprefix("networktopology_"))
        return sorted(ids)

    # -- resumable ingestion offsets --------------------------------------
    def download_offset(self, host_id: str) -> int:
        return self.offsets.get(f"download_{host_id}")

    def commit_download_offset(self, host_id: str, offset: int) -> None:
        self.offsets.commit(f"download_{host_id}", offset)

    def mark_download_round(self, host_id: str) -> int:
        """Record (and return) the current download-file size as a round
        boundary — called by the Train service once a stream finishes, so
        the boundary always sits between complete uploads."""
        with self._lock:
            path = self.download_path(host_id)
            size = path.stat().st_size if path.exists() else 0
            self._round_boundaries[f"download_{host_id}"] = size
            return size

    def download_round_boundary(self, host_id: str) -> int:
        """Last marked round boundary; falls back to a locked size stat
        (direct-API callers that never interleave appends with training)."""
        with self._lock:
            key = f"download_{host_id}"
            if key in self._round_boundaries:
                return self._round_boundaries[key]
            path = self.download_path(host_id)
            return path.stat().st_size if path.exists() else 0

    # -- cleanup ----------------------------------------------------------
    def clear_download(self, host_id: str) -> None:
        self.download_path(host_id).unlink(missing_ok=True)
        self.offsets.reset(f"download_{host_id}")

    def clear_network_topology(self, host_id: str) -> None:
        self.network_topology_path(host_id).unlink(missing_ok=True)
        self.offsets.reset(f"networktopology_{host_id}")

    def clear(self) -> None:
        for host_id in self.host_ids():
            self.clear_download(host_id)
            self.clear_network_topology(host_id)
