"""Model fit loops — the real implementation of the reference's training
stubs (reference trainer/training/training.go:60-98; intended flow per its
comments: load from storage → preprocess → train → upload model to manager).

Throughput design (north star: 1B records in <10 min on v5e-8):
- whole-epoch `lax.scan` over device-resident minibatches — one XLA call
  per epoch, zero host↔device traffic inside the loop;
- bfloat16 matmuls with float32 accumulation (models.*);
- data parallelism by sharding the batch dim over the mesh's `dp` axis
  with NamedSharding and letting XLA insert the gradient all-reduce;
- optional tensor parallelism of hidden dims over `mp`
  (parallel.sharding.mlp_param_spec).
"""

# dfanalyze: device-hot — every fit loop here dispatches jitted epochs;
# per-call jit wrappers or implicit host feeds cost a compile/transfer
# per fit

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from dragonfly2_tpu.models import gnn as gnn_mod
from dragonfly2_tpu.models import gru as gru_mod
from dragonfly2_tpu.models import mlp as mlp_mod
from dragonfly2_tpu.utils import faults
from dragonfly2_tpu.utils.jitcache import jit_once

# fault point: fires once per fit epoch (the checkpoint granularity) —
# an ``abort`` rule here is the crash drill for checkpoint/resume, a
# ``delay`` rule models a stalling device link
FP_FIT_STEP = faults.point("trainer.fit_step")


@dataclass
class FitConfig:
    hidden_dims: tuple[int, ...] = (128, 128)
    batch_size: int = 8192
    epochs: int = 3
    learning_rate: float = 3e-3
    weight_decay: float = 1e-4
    warmup_fraction: float = 0.1
    eval_fraction: float = 0.1
    seed: int = 0
    compute_dtype: Any = jnp.bfloat16
    # elastic restart: snapshot (params, opt_state) every N epochs here
    # and resume from the latest snapshot (trainer.checkpoint)
    checkpoint_dir: str | None = None
    checkpoint_every: int = 1


@dataclass
class FitResult:
    params: Any
    metrics: dict[str, float]
    history: list[float] = field(default_factory=list)  # per-epoch mean loss


def _optimizer(cfg: FitConfig, total_steps: int) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=max(1, int(total_steps * cfg.warmup_fraction)),
        decay_steps=max(2, total_steps),
    )
    return optax.adamw(schedule, weight_decay=cfg.weight_decay)


def _split_eval(n: int, eval_fraction: float, seed: int) -> tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    n_eval = int(n * eval_fraction)
    return perm[n_eval:], perm[:n_eval]


# eval forwards ride the shared memoized jit (utils.jitcache.jit_once);
# this local cache only keys the (mesh, axis)-specific sharded forward
_jit_cache: dict = {}


def _shard_arrays(mesh, *arrays, axis: str = "dp"):
    if mesh is None:
        # explicit H2D at the boundary: feeding numpy straight into the
        # jitted epoch is an implicit per-epoch transfer the jit witness
        # (rightly) flags; the cost is identical, the site is visible
        return tuple(jnp.asarray(a) for a in arrays)
    if arrays and arrays[0].shape[1] % mesh.shape[axis]:
        # _batch_steps clamps the batch to tiny shards, and a clamped
        # batch rarely divides the dp axis — feed replicated rather
        # than fail the fit (the auto-mesh default must be safe for
        # every dataset size; one small fit doesn't need parallelism)
        return tuple(jnp.asarray(a) for a in arrays)
    s = NamedSharding(mesh, P(None, axis))  # [steps, batch, ...] — batch dim sharded
    return tuple(jax.device_put(a, s) for a in arrays)


def _batch_steps(n: int, batch: int) -> tuple[int, int, int]:
    """→ (steps, rows_used, batch) with batch clamped to the training-set
    size. Shared by every fit loop so small per-host datasets and the
    empty case behave identically everywhere."""
    if n <= 0:
        raise ValueError("no training examples (empty dataset after eval split)")
    batch = min(batch, n)
    steps = max(1, n // batch)
    return steps, steps * batch, batch


def make_epoch_fn(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
):
    """Build a jitted whole-epoch function: scan over [steps, batch, ...]
    stacked minibatches, donating the carried state."""

    def epoch(params, opt_state, batches):
        def body(carry, batch):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(body, (params, opt_state), batches)
        return params, opt_state, losses.mean()

    return jax.jit(epoch, donate_argnums=(0, 1))


# ---------------------------------------------------------------------------
# MLP parent scorer  (reference trainMLP stub, training.go:92-98)
# ---------------------------------------------------------------------------


def train_mlp(
    features: np.ndarray,
    labels: np.ndarray,
    mesh=None,
    config: FitConfig | None = None,
) -> FitResult:
    """Fit the pair scorer: features [N, F] → label log piece cost [N].

    Evaluation metrics are MSE/MAE, matching what the manager stores with
    an MLP model upload (reference manager_server_v1.go:847-851).
    """
    cfg = config or FitConfig()
    n, f = features.shape
    train_idx, eval_idx = _split_eval(n, cfg.eval_fraction, cfg.seed)
    steps, used, batch = _batch_steps(len(train_idx), cfg.batch_size)

    key = jax.random.PRNGKey(cfg.seed)
    params = mlp_mod.init_mlp(key, [f, *cfg.hidden_dims, 1])
    # warm-start the output bias at the label mean — the regression head
    # starts unbiased instead of spending its first epochs drifting there
    params["layers"][-1]["b"] = jnp.full((1,), float(labels.mean()))
    if mesh is not None:
        from dragonfly2_tpu.parallel.sharding import replicate

        params = replicate(mesh, params)

    total_steps = steps * cfg.epochs
    optimizer = _optimizer(cfg, total_steps)
    opt_state = optimizer.init(params)

    def loss_fn(p, batch):
        x, y = batch
        pred = mlp_mod.score_parents(p, x)
        return jnp.mean((pred - y) ** 2)

    epoch_fn = make_epoch_fn(loss_fn, optimizer)

    ckpt, start_epoch = _open_checkpoint(cfg)
    try:
        if ckpt is not None and start_epoch > 0:
            restored = ckpt.restore_latest({"params": params, "opt_state": opt_state})
            if restored is not None:
                _, state = restored
                params, opt_state = state["params"], state["opt_state"]

        history: list[float] = []
        for epoch in range(start_epoch, cfg.epochs):
            FP_FIT_STEP()
            # per-epoch rng: a resumed run replays the exact shuffle schedule
            rng = np.random.default_rng(cfg.seed + 1 + epoch)
            order = train_idx[rng.permutation(len(train_idx))][:used]
            xb = features[order].reshape(steps, batch, f)
            yb = labels[order].reshape(steps, batch)
            xb, yb = _shard_arrays(mesh, xb, yb)
            params, opt_state, mean_loss = epoch_fn(params, opt_state, (xb, yb))
            history.append(float(mean_loss))
            _maybe_save_tree(ckpt, cfg, epoch, {"params": params, "opt_state": opt_state})

        metrics = evaluate_mlp(params, features[eval_idx], labels[eval_idx]) if len(eval_idx) else {}
        _finish_checkpoint(ckpt)
        ckpt = None
        return FitResult(params=params, metrics=metrics, history=history)
    finally:
        if ckpt is not None:
            ckpt.close()


def _open_checkpoint(cfg: FitConfig):
    """→ (FitCheckpointer | None, start_epoch). Epoch ``k`` snapshots are
    taken *after* epoch k runs, so resume starts at latest+1."""
    if not cfg.checkpoint_dir:
        return None, 0
    from dragonfly2_tpu.trainer.checkpoint import FitCheckpointer

    ckpt = FitCheckpointer(cfg.checkpoint_dir)
    latest = ckpt.latest_epoch()
    return ckpt, (latest + 1 if latest is not None else 0)


def _maybe_save_tree(ckpt, cfg: FitConfig, epoch: int, state) -> None:
    if ckpt is not None and (epoch + 1) % max(cfg.checkpoint_every, 1) == 0:
        ckpt.save(epoch, state)


def _finish_checkpoint(ckpt) -> None:
    """Successful completion: drop the run's snapshots (the next round
    must train fresh, not resume into zero epochs) and release the
    manager's background resources."""
    if ckpt is not None:
        ckpt.clear()
        ckpt.close()


def evaluate_mlp(params, features: np.ndarray, labels: np.ndarray) -> dict[str, float]:
    pred = np.asarray(jit_once(mlp_mod.score_parents)(params, jnp.asarray(features)))
    err = pred - labels
    return {"mse": float(np.mean(err**2)), "mae": float(np.mean(np.abs(err)))}


# ---------------------------------------------------------------------------
# GraphSAGE edge-RTT  (reference trainGNN stub, training.go:82-88)
# ---------------------------------------------------------------------------


@dataclass
class GNNFitConfig(FitConfig):
    hidden_dims: tuple[int, ...] = (64, 64)
    batch_size: int = 2048  # edges per step
    epochs: int = 60  # probe graphs are small; the embedding table needs steps
    learning_rate: float = 2e-2


def _init_gnn(graph, cfg: GNNFitConfig):
    """Shared GraphSAGE init for the single-device and sharded fits: same
    seed, same embedding table, same head-bias warm start — the sharded
    path's semantics must match train_gnn's."""
    if len(graph.edge_src) == 0:
        raise ValueError("probe graph has no edges to train on")
    key = jax.random.PRNGKey(cfg.seed)
    params = gnn_mod.init_graphsage(
        key, graph.node_features.shape[1], cfg.hidden_dims, num_nodes=graph.num_nodes
    )
    params["head"]["layers"][-1]["b"] = jnp.full(
        (1,), float(graph.edge_rtt_log_ms.mean())
    )
    return params


def train_gnn(
    graph,
    mesh=None,
    config: GNNFitConfig | None = None,
) -> FitResult:
    """Fit GraphSAGE on a schema.features.ProbeGraph: predict per-edge
    log-RTT from host embeddings.

    Evaluation reports MSE/MAE plus precision/recall/f1 on the derived
    binary task "edge is faster than the median RTT" — the tuple the
    manager stores with a GNN upload (reference manager_server_v1.go:
    CreateModel GNN evaluation fields).
    """
    cfg = config or GNNFitConfig()
    e = len(graph.edge_src)
    train_idx, eval_idx = _split_eval(e, cfg.eval_fraction, cfg.seed)
    params = _init_gnn(graph, cfg)
    if mesh is not None:
        from dragonfly2_tpu.parallel.sharding import replicate

        params = replicate(mesh, params)

    node_features = jnp.asarray(graph.node_features)
    neighbors = jnp.asarray(graph.neighbors)
    neighbor_mask = jnp.asarray(graph.neighbor_mask)

    steps, used, batch = _batch_steps(len(train_idx), cfg.batch_size)
    optimizer = _optimizer(cfg, steps * cfg.epochs)
    opt_state = optimizer.init(params)

    def loss_fn(p, b):
        src, dst, y = b
        pred = gnn_mod.forward_edge_rtt(p, node_features, neighbors, neighbor_mask, src, dst)
        return jnp.mean((pred - y) ** 2)

    epoch_fn = make_epoch_fn(loss_fn, optimizer)

    ckpt, start_epoch = _open_checkpoint(cfg)
    try:
        if ckpt is not None and start_epoch > 0:
            restored = ckpt.restore_latest({"params": params, "opt_state": opt_state})
            if restored is not None:
                _, state = restored
                params, opt_state = state["params"], state["opt_state"]

        history: list[float] = []
        for epoch in range(start_epoch, cfg.epochs):
            rng = np.random.default_rng(cfg.seed + 1 + epoch)
            order = train_idx[rng.permutation(len(train_idx))][:used]
            sb = graph.edge_src[order].reshape(steps, batch)
            db = graph.edge_dst[order].reshape(steps, batch)
            yb = graph.edge_rtt_log_ms[order].reshape(steps, batch)
            params, opt_state, mean_loss = epoch_fn(params, opt_state, (jnp.asarray(sb), jnp.asarray(db), jnp.asarray(yb)))
            history.append(float(mean_loss))
            _maybe_save_tree(ckpt, cfg, epoch, {"params": params, "opt_state": opt_state})

        metrics: dict[str, float] = {}
        if len(eval_idx):
            metrics = evaluate_gnn(params, graph, eval_idx)
        _finish_checkpoint(ckpt)
        ckpt = None
        return FitResult(params=params, metrics=metrics, history=history)
    finally:
        if ckpt is not None:
            ckpt.close()


def train_gnn_sharded(
    graph,
    mesh,
    axis: str = "gp",
    config: GNNFitConfig | None = None,
) -> FitResult:
    """Graph-parallel GraphSAGE fit: node feature/embedding tables and
    edge blocks row-sharded over ``mesh[axis]``, neighbor and endpoint
    gathers riding the ICI ring (models.gnn_sharded). Per-device HBM is
    O(N/devices) — the path for probe graphs too large for one chip;
    semantics (loss, params) match train_gnn's full-batch limit.
    """
    from dragonfly2_tpu.models import gnn_sharded as gs

    cfg = config or GNNFitConfig()
    e = len(graph.edge_src)
    shards = mesh.shape[axis]
    _, eval_idx = _split_eval(e, cfg.eval_fraction, cfg.seed)
    params = _init_gnn(graph, cfg)

    nf, nbrs, mask, src_all, dst_all, y_all, w_all = gs.pad_graph(graph, shards)
    # hold out the eval edges by zeroing their loss weight — shapes stay
    # static, sharding stays even
    w_all[eval_idx] = 0.0

    # node embedding table sharded over the axis; dense weights replicated
    embed = params.pop("node_embed", None)
    if embed is not None:
        embed = jnp.asarray(gs.pad_rows(np.asarray(embed), shards))
    from dragonfly2_tpu.parallel.sharding import replicate

    dense = replicate(mesh, params)
    if embed is not None:
        embed = jax.device_put(embed, NamedSharding(mesh, P(axis, None)))
    nf_d, nbrs_d, mask_d, src_d, dst_d, y_d, w_d = gs.shard_graph_arrays(
        mesh, axis, nf, nbrs, mask, src_all, dst_all, y_all, w_all
    )

    loss_fn = gs.make_sharded_loss(mesh, axis)
    optimizer = _optimizer(cfg, cfg.epochs)
    opt_state = optimizer.init((dense, embed))

    @jax.jit
    def step(dense, embed, opt_state):
        def wrapped(de):
            d, em = de
            return loss_fn(d, em, nf_d, nbrs_d, mask_d, src_d, dst_d, y_d, w_d)

        loss, grads = jax.value_and_grad(wrapped)((dense, embed))
        updates, opt_state2 = optimizer.update(grads, opt_state, (dense, embed))
        dense2, embed2 = optax.apply_updates((dense, embed), updates)
        return dense2, embed2, opt_state2, loss

    ckpt, start_epoch = _open_checkpoint(cfg)
    if ckpt is not None and start_epoch > 0:
        restored = ckpt.restore_latest(
            {"dense": dense, "embed": embed, "opt_state": opt_state}
        )
        if restored is not None:
            _, state = restored
            dense, embed, opt_state = state["dense"], state["embed"], state["opt_state"]

    history: list[float] = []
    for epoch in range(start_epoch, cfg.epochs):
        dense, embed, opt_state, loss = step(dense, embed, opt_state)
        history.append(float(loss))
        _maybe_save_tree(
            ckpt, cfg, epoch, {"dense": dense, "embed": embed, "opt_state": opt_state}
        )
    _finish_checkpoint(ckpt)

    metrics: dict[str, float] = {}
    if len(eval_idx):
        # eval through the sharded forward too — the whole point of this
        # path is that the graph doesn't fit one chip. The jitted
        # forward is memoized per (mesh, axis): make_sharded_forward
        # returns a fresh closure each call, and jitting that fresh
        # closure per fit recompiled an identical executable
        fwd_key = ("sharded_fwd", mesh, axis)
        fwd_jit = _jit_cache.get(fwd_key)
        if fwd_jit is None:
            fwd_jit = _jit_cache[fwd_key] = jax.jit(gs.make_sharded_forward(mesh, axis))
        # index on device, transfer only the eval rows — pulling the
        # whole padded prediction host-side to slice it was a full-array
        # D2H for a fraction of the rows
        pred = np.asarray(
            fwd_jit(dense, embed, nf_d, nbrs_d, mask_d, src_d, dst_d)[:e][eval_idx]
        )
        metrics = _edge_metrics(
            pred, graph.edge_rtt_log_ms[eval_idx], float(np.median(graph.edge_rtt_log_ms))
        )

    out_params = jax.tree_util.tree_map(np.asarray, dense)
    if embed is not None:
        # slice the padding off on device; transfer only the real rows
        out_params["node_embed"] = np.asarray(embed[: graph.num_nodes])
    return FitResult(params=out_params, metrics=metrics, history=history)


def _edge_metrics(pred: np.ndarray, y: np.ndarray, thresh: float) -> dict[str, float]:
    """MSE/MAE + precision/recall/f1 on "edge faster than median RTT" —
    the evaluation tuple the manager stores with a GNN upload (reference
    manager_server_v1.go CreateModel GNN evaluation fields)."""
    err = pred - y
    actual_fast = y < thresh
    pred_fast = pred < thresh
    tp = float(np.sum(pred_fast & actual_fast))
    fp = float(np.sum(pred_fast & ~actual_fast))
    fn = float(np.sum(~pred_fast & actual_fast))
    precision = tp / max(tp + fp, 1.0)
    recall = tp / max(tp + fn, 1.0)
    f1 = 2 * precision * recall / max(precision + recall, 1e-9)
    return {
        "mse": float(np.mean(err**2)),
        "mae": float(np.mean(np.abs(err))),
        "precision": precision,
        "recall": recall,
        "f1": f1,
    }


def evaluate_gnn(params, graph, edge_idx: np.ndarray) -> dict[str, float]:
    pred = np.asarray(
        jit_once(gnn_mod.forward_edge_rtt)(
            params,
            jnp.asarray(graph.node_features),
            jnp.asarray(graph.neighbors),
            jnp.asarray(graph.neighbor_mask),
            jnp.asarray(graph.edge_src[edge_idx]),
            jnp.asarray(graph.edge_dst[edge_idx]),
        )
    )
    return _edge_metrics(
        pred, graph.edge_rtt_log_ms[edge_idx], float(np.median(graph.edge_rtt_log_ms))
    )


# ---------------------------------------------------------------------------
# GRU piece time-series
# ---------------------------------------------------------------------------


def train_gru(
    sequences: np.ndarray,  # [N, T, F]
    labels: np.ndarray,  # [N]
    lengths: np.ndarray | None = None,
    mesh=None,
    config: FitConfig | None = None,
) -> FitResult:
    """Fit the next-piece-cost predictor over piece history sequences."""
    cfg = config or FitConfig(hidden_dims=(64,), batch_size=256, epochs=5)
    n, t, f = sequences.shape
    train_idx, eval_idx = _split_eval(n, cfg.eval_fraction, cfg.seed)
    if lengths is None:
        lengths = np.full((n,), t, np.int32)

    key = jax.random.PRNGKey(cfg.seed)
    params = gru_mod.init_gru(key, f, cfg.hidden_dims[0])
    params["head"]["layers"][-1]["b"] = jnp.full((1,), float(labels.mean()))
    if mesh is not None:
        from dragonfly2_tpu.parallel.sharding import replicate

        params = replicate(mesh, params)

    steps, used, batch = _batch_steps(len(train_idx), cfg.batch_size)
    optimizer = _optimizer(cfg, steps * cfg.epochs)
    opt_state = optimizer.init(params)

    def loss_fn(p, b):
        x, y, ln = b
        pred = gru_mod.predict_next_cost(p, x, ln)
        return jnp.mean((pred - y) ** 2)

    epoch_fn = make_epoch_fn(loss_fn, optimizer)

    history: list[float] = []
    rng = np.random.default_rng(cfg.seed + 1)
    for _ in range(cfg.epochs):
        order = train_idx[rng.permutation(len(train_idx))][:used]
        xb = sequences[order].reshape(steps, batch, t, f)
        yb = labels[order].reshape(steps, batch)
        lb = lengths[order].reshape(steps, batch)
        xb, yb, lb = _shard_arrays(mesh, xb, yb, lb)
        params, opt_state, mean_loss = epoch_fn(params, opt_state, (xb, yb, lb))
        history.append(float(mean_loss))

    metrics: dict[str, float] = {}
    if len(eval_idx):
        pred = np.asarray(
            jit_once(gru_mod.predict_next_cost)(
                params, jnp.asarray(sequences[eval_idx]), jnp.asarray(lengths[eval_idx])
            )
        )
        err = pred - labels[eval_idx]
        metrics = {"mse": float(np.mean(err**2)), "mae": float(np.mean(np.abs(err)))}
    return FitResult(params=params, metrics=metrics, history=history)
