"""`python -m dragonfly2_tpu.trainer` — the trainer binary (reference
cmd/trainer/main.go)."""

import sys

from dragonfly2_tpu.cli.runner import main_with_config
from dragonfly2_tpu.trainer.server import build

if __name__ == "__main__":
    sys.exit(main_with_config("trainer", build))
