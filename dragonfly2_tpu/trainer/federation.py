"""Federated training round: per-host record shards → per-shard fits →
example-weighted FedAvg merge → one global model (SURVEY §7 stage 7).

The trainer's storage keys dataset files by uploading scheduler host
(reference trainer/storage/storage.go:141-148); each host's shard is a
cluster's view of the swarm. A merged model generalizes across clusters
without ever pooling their raw records — the cross-datacenter shape,
where clusters are separate jobs and only parameters cross the DCN
(parallel/fedavg.fedavg_trees; the in-mesh psum variant rides a
``fed`` mesh axis, exercised in __graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from dragonfly2_tpu.parallel.fedavg import fedavg_trees
from dragonfly2_tpu.schema import native
from dragonfly2_tpu.schema.columnar import records_to_columns
from dragonfly2_tpu.schema.features import extract_pair_features
from dragonfly2_tpu.trainer.train import FitConfig, evaluate_mlp, train_mlp
from dragonfly2_tpu.utils import dflog

logger = dflog.get("trainer.federation")


@dataclass
class FederatedResult:
    params: object
    metrics: dict[str, float]
    per_host: dict[str, dict] = field(default_factory=dict)
    total_examples: int = 0


def _host_pairs(storage, host_id: str):
    pairs = native.decode_pairs_file(storage.download_path(host_id))
    if pairs is None:
        pairs = extract_pair_features(
            records_to_columns(storage.list_download(host_id))
        )
    return pairs


def federated_fit_mlp(
    storage,
    host_ids: list[str],
    config: FitConfig | None = None,
    mesh=None,
    eval_fraction: float = 0.1,
) -> FederatedResult:
    """One federated round over the given hosts' download shards.

    Per shard: an independent MLP fit (identical init seed — FedAvg of
    one round from a common init). Merge: example-weighted parameter
    average. Evaluation: the merged model scored on a held-out slice
    drawn from EVERY shard, so the metric reflects cross-cluster
    generalization, not any single cluster's distribution.
    """
    cfg = config or FitConfig()
    models, weights = [], []
    eval_x, eval_y = [], []
    per_host: dict[str, dict] = {}
    for host_id in host_ids:
        pairs = _host_pairs(storage, host_id)
        n = pairs.features.shape[0]
        if n == 0:
            per_host[host_id] = {"examples": 0, "skipped": True}
            continue
        n_eval = max(1, int(n * eval_fraction)) if n > 1 else 0
        rng = np.random.default_rng(cfg.seed)
        perm = rng.permutation(n)
        ev, tr = perm[:n_eval], perm[n_eval:]
        if len(tr) == 0:
            per_host[host_id] = {"examples": n, "skipped": True}
            continue
        result = train_mlp(pairs.features[tr], pairs.labels[tr], mesh=mesh, config=cfg)
        models.append(result.params)
        weights.append(float(len(tr)))
        if n_eval:
            eval_x.append(pairs.features[ev])
            eval_y.append(pairs.labels[ev])
        per_host[host_id] = {
            "examples": int(len(tr)),
            "metrics": result.metrics,
        }
    if not models:
        raise ValueError("no host shard produced trainable examples")

    merged = fedavg_trees(models, weights)
    metrics: dict[str, float] = {}
    if eval_x:
        metrics = evaluate_mlp(
            merged, np.concatenate(eval_x), np.concatenate(eval_y)
        )
    logger.info(
        "federated round: %d shards, %d examples, merged mse=%s",
        len(models),
        int(sum(weights)),
        metrics.get("mse"),
    )
    return FederatedResult(
        params=merged,
        metrics=metrics,
        per_host=per_host,
        total_examples=int(sum(weights)),
    )
