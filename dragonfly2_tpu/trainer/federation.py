"""Federated training round: per-host record shards → per-shard fits →
example-weighted FedAvg merge → one global model (SURVEY §7 stage 7).

The trainer's storage keys dataset files by uploading scheduler host
(reference trainer/storage/storage.go:141-148); each host's shard is a
cluster's view of the swarm. A merged model generalizes across clusters
without ever pooling their raw records — the cross-datacenter shape,
where clusters are separate jobs and only parameters cross the DCN
(parallel/fedavg.fedavg_trees; the in-mesh psum variant rides a
``fed`` mesh axis, exercised in __graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from dragonfly2_tpu.parallel.fedavg import fedavg_trees
from dragonfly2_tpu.schema import native
from dragonfly2_tpu.schema.columnar import records_to_columns
from dragonfly2_tpu.schema.features import PairExamples, extract_pair_features
from dragonfly2_tpu.trainer.train import FitConfig, evaluate_mlp, train_mlp
from dragonfly2_tpu.utils import dflog

logger = dflog.get("trainer.federation")


@dataclass
class FederatedResult:
    params: object
    metrics: dict[str, float]
    per_host: dict[str, dict] = field(default_factory=dict)
    total_examples: int = 0


def _host_pairs(storage, host_id: str):
    # a host that uploaded the binary columnar stream carries its pairs
    # pre-extracted (schema/wire.py); CSV shards decode via the native
    # parser with the numpy path as fallback — identical tensors either
    # way. A host holding BOTH forms (scheduler switched payload formats
    # mid-history) contributes the union, not just the newer era.
    cpath = storage.download_path(host_id)
    pairs = None
    if cpath.exists() and cpath.stat().st_size:
        # bounded at the committed round boundary, same as the binary
        # read below: an in-flight upload's tail may be truncated by a
        # failed stream mid-read
        csv_boundary = storage.download_round_boundary(host_id)
        pairs = native.decode_pairs_file(cpath, end=csv_boundary)
        if pairs is None:
            recs = [
                r
                for chunk in storage.iter_download_chunks(
                    host_id, max_bytes=csv_boundary
                )
                for r in chunk
            ]
            pairs = extract_pair_features(records_to_columns(recs))
    bpath = storage.download_blocks_path(host_id)
    if bpath.exists() and bpath.stat().st_size:
        from dragonfly2_tpu.schema import wire

        # bounded at the committed round boundary like every other
        # block reader: bytes past it belong to an in-flight upload
        # whose failure may truncate them under this reader's mmap
        bin_pairs = wire.read_train_pairs(
            bpath, end=storage.download_round_boundary(host_id, binary=True)
        )
        if pairs is None or pairs.features.shape[0] == 0:
            return bin_pairs
        return PairExamples(
            features=np.concatenate([pairs.features, bin_pairs.features]),
            labels=np.concatenate([pairs.labels, bin_pairs.labels]),
            download_index=np.concatenate(
                [
                    pairs.download_index,
                    bin_pairs.download_index + pairs.num_downloads,
                ]
            ),
            num_downloads=pairs.num_downloads + bin_pairs.num_downloads,
        )
    if pairs is None:
        pairs = extract_pair_features(
            records_to_columns(storage.list_download(host_id))
        )
    return pairs


def federated_fit_mlp(
    storage,
    host_ids: list[str],
    config: FitConfig | None = None,
    mesh=None,
    eval_fraction: float = 0.1,
) -> FederatedResult:
    """One federated round over the given hosts' download shards.

    Per shard: an independent MLP fit (identical init seed — FedAvg of
    one round from a common init). Merge: example-weighted parameter
    average. Evaluation: the merged model scored on a held-out slice
    drawn from EVERY shard, so the metric reflects cross-cluster
    generalization, not any single cluster's distribution.
    """
    cfg = config or FitConfig()
    models, weights = [], []
    eval_x, eval_y = [], []
    per_host: dict[str, dict] = {}
    for host_id in host_ids:
        pairs = _host_pairs(storage, host_id)
        n = pairs.features.shape[0]
        if n == 0:
            per_host[host_id] = {"examples": 0, "skipped": True}
            continue
        n_eval = max(1, int(n * eval_fraction)) if n > 1 else 0
        rng = np.random.default_rng(cfg.seed)
        perm = rng.permutation(n)
        ev, tr = perm[:n_eval], perm[n_eval:]
        if len(tr) == 0:
            per_host[host_id] = {"examples": n, "skipped": True}
            continue
        result = train_mlp(pairs.features[tr], pairs.labels[tr], mesh=mesh, config=cfg)
        models.append(result.params)
        weights.append(float(len(tr)))
        if n_eval:
            eval_x.append(pairs.features[ev])
            eval_y.append(pairs.labels[ev])
        per_host[host_id] = {
            "examples": int(len(tr)),
            "metrics": result.metrics,
        }
    if not models:
        raise ValueError("no host shard produced trainable examples")

    merged = fedavg_trees(models, weights)
    metrics: dict[str, float] = {}
    if eval_x:
        metrics = evaluate_mlp(
            merged, np.concatenate(eval_x), np.concatenate(eval_y)
        )
    logger.info(
        "federated round: %d shards, %d examples, merged mse=%s",
        len(models),
        int(sum(weights)),
        metrics.get("mse"),
    )
    return FederatedResult(
        params=merged,
        metrics=metrics,
        per_host=per_host,
        total_examples=int(sum(weights)),
    )
