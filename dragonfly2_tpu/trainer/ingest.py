"""Streaming ingestion: bytes-on-disk → native decode → device feed →
trained params, in bounded host memory with decode/transfer/compute
overlapped.

This is the hard part of the 1B-records-in-10-min north star (SURVEY §7:
~1.7M records/s sustained): the reference's Train stream lands CSV files
on the trainer's disk (reference trainer/storage/storage.go:44-148,
announcer 128 MiB-chunk upload announcer.go:39-41); from there this
module drives the fused C++ CSV→tensor decoder (native/dfnative.cc) in a
producer thread, packs pair shards into fixed-size minibatches, and feeds
the jitted train step — the decode of chunk k+1 overlaps the device step
on batch k (ctypes releases the GIL during native parsing; XLA dispatch
is async).

Memory bound: the shard queue holds ≤ ``queue_depth`` chunks of decoded
pairs (~chunk_bytes of CSV each) plus one packing buffer — independent of
file size.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from dragonfly2_tpu.schema.features import MLP_FEATURE_DIM
from dragonfly2_tpu.schema import native
from dragonfly2_tpu.utils import dflog

logger = dflog.get("trainer.ingest")


@dataclass
class StreamStats:
    download_records: int = 0
    pairs: int = 0
    steps: int = 0
    wall_s: float = 0.0
    decode_wait_s: float = 0.0  # consumer time blocked on the decoder
    losses: list = field(default_factory=list)

    @property
    def records_per_s(self) -> float:
        return self.download_records / self.wall_s if self.wall_s else 0.0


def stream_shards(
    paths,
    passes: int = 1,
    max_records: int | None = None,
    queue_depth: int = 4,
    chunk_bytes: int = 8 * 1024 * 1024,
):
    """Generator of (feats, labels, cumulative_rows) shards, decoded by a
    background producer thread through a bounded queue."""
    q: "queue.Queue" = queue.Queue(maxsize=queue_depth)
    error: list[BaseException] = []

    def produce():
        try:
            for shard in native.stream_pairs_file(
                paths, passes=passes, chunk_bytes=chunk_bytes, max_records=max_records
            ):
                q.put(shard)
        except BaseException as e:  # surfaced to the consumer
            error.append(e)
        finally:
            q.put(None)

    t = threading.Thread(target=produce, name="ingest-decode", daemon=True)
    t.start()
    while True:
        shard = q.get()
        if shard is None:
            break
        yield shard
    t.join()
    if error:
        raise error[0]


def stream_train_mlp(
    paths,
    passes: int = 1,
    max_records: int | None = None,
    batch_size: int = 65_536,
    hidden_dims: tuple[int, ...] = (256, 256),
    learning_rate: float = 3e-3,
    queue_depth: int = 4,
    params=None,
) -> tuple[object, StreamStats]:
    """Fit the MLP parent scorer directly off disk bytes. Returns
    (params, StreamStats). Partial trailing batches are dropped (static
    shapes keep one XLA executable hot)."""
    import jax
    import jax.numpy as jnp
    import optax

    from dragonfly2_tpu.models import mlp as mlp_mod

    optimizer = optax.adamw(learning_rate, weight_decay=1e-4)
    if params is None:
        params = mlp_mod.init_mlp(
            jax.random.PRNGKey(0), [MLP_FEATURE_DIM, *hidden_dims, 1]
        )
    opt_state = optimizer.init(params)

    def loss_fn(p, xb, yb):
        pred = mlp_mod.score_parents(p, xb)
        return jnp.mean((pred - yb) ** 2)

    @jax.jit
    def step(params, opt_state, xb, yb):
        loss, grads = jax.value_and_grad(loss_fn)(params, xb, yb)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    stats = StreamStats()
    # packing buffer: fixed [batch_size, F], filled from variable shards
    xbuf = np.empty((batch_size, MLP_FEATURE_DIM), np.float32)
    ybuf = np.empty((batch_size,), np.float32)
    fill = 0
    pending_loss = None
    t0 = time.perf_counter()

    for feats, labels, rows in stream_shards(
        paths,
        passes=passes,
        max_records=max_records,
        queue_depth=queue_depth,
    ):
        stats.download_records = rows
        stats.pairs += feats.shape[0]
        off = 0
        while off < feats.shape[0]:
            take = min(batch_size - fill, feats.shape[0] - off)
            xbuf[fill : fill + take] = feats[off : off + take]
            ybuf[fill : fill + take] = labels[off : off + take]
            fill += take
            off += take
            if fill == batch_size:
                # async dispatch: the host returns to decoding while the
                # chip trains this batch
                params, opt_state, pending_loss = step(
                    params, opt_state, jnp.asarray(xbuf), jnp.asarray(ybuf)
                )
                stats.steps += 1
                fill = 0
    if pending_loss is not None:
        stats.losses.append(float(jax.block_until_ready(pending_loss)))
    stats.wall_s = time.perf_counter() - t0
    return params, stats
