"""Streaming ingestion: bytes-on-disk → decode → device feed → trained
params, in bounded host memory with decode/transfer/compute overlapped.

This is the hard part of the 1B-records-in-10-min north star (SURVEY §7:
~1.7M records/s sustained): the Train stream lands dataset files on the
trainer's disk (reference trainer/storage/storage.go:44-148, announcer
128 MiB-chunk upload announcer.go:39-41) in one of two payload formats,
sniffed from the file's magic bytes:

- **binary columnar blocks** (schema/wire.py, the negotiated production
  format): pair tensors precomputed scheduler-side — producer threads
  mmap block-aligned spans, verify checksums, and cast to the staging
  dtype; decode_wait collapses to I/O.
- **CSV** (the old-peer fallback): producer threads drive the fused C++
  CSV→tensor decoder (native/dfnative.cc) over newline-aligned spans
  (ctypes releases the GIL during native parsing).

Either way the consumer packs pair shards into fixed-size minibatches
and hands full superbatches to a two-stage device leg — a TRANSFER
thread issuing the H2D put and a STEP thread driving the jitted
(buffer-donating) train step — so decode, H2D, and device compute all
overlap: superbatch N+1's transfer is issued while step N executes,
and the hidden transfer wall is measured per run
(``StreamStats.h2d_overlap_s``). With a multi-chip ``mesh`` the put is
a per-device sharded upload (each chip receives only its row shard).
Multiple dataset files decode in parallel, one producer thread per
span.

Memory bound: the shard queue holds ≤ ``queue_depth`` chunks of decoded
pairs (~chunk_bytes of CSV each) plus a six-buffer packing pool
(6 × batch_size·steps_per_call superbatches: one packing, up to three
queued/in-transfer, up to two staged for the step, one awaiting
confirmation) and a capped eval holdout — independent of file size.
"""

# dfanalyze: device-hot — the dispatcher thread drives the jitted train
# step per superbatch; a fresh jit wrapper or stray host sync here costs
# a compile/transfer per dispatch

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

import numpy as np

from dragonfly2_tpu.schema.features import MLP_FEATURE_DIM
from dragonfly2_tpu.schema import native, wire
from dragonfly2_tpu.trainer import metrics as M
from dragonfly2_tpu.utils import dflog, flight, profiling

logger = dflog.get("trainer.ingest")

# flight-recorder events: the per-superbatch h2d/step split (the live
# form of the StreamStats totals), the end-of-stream milestone with the
# full decode/transfer/compute attribution, and the stall verdicts the
# watchdogs reach — all under the owning fit's trace_id
EV_SUPERBATCH = flight.event_type("trainer.superbatch")
EV_STREAM_DONE = flight.event_type("trainer.stream_done")
EV_STALL = flight.event_type("trainer.stall")

# dfprof phase ledger: the StreamStats wall split as LIVE cross-service
# phases — buffer_wait's share of the trainer group on /debug/prof must
# agree with the per-fit StreamStats ratio (acceptance-tested)
PH_DECODE_WAIT = profiling.phase_type("trainer.decode_wait")
PH_BUFFER_WAIT = profiling.phase_type("trainer.buffer_wait")
PH_H2D = profiling.phase_type("trainer.h2d")
PH_STEP = profiling.phase_type("trainer.step")


@dataclass
class StreamStats:
    download_records: int = 0
    pairs: int = 0
    steps: int = 0
    eval_pairs: int = 0
    wall_s: float = 0.0
    truncated: bool = False  # stopped early by a time budget
    # wall-clock split of the packing thread (the pipeline's spine):
    # decode_wait_s — blocked on the decode queue (decoders too slow);
    # buffer_wait_s — blocked on the superbatch pool (device leg too
    # slow). The remainder is packing work itself. Together these say
    # WHICH stage bounded a run — recorded per run so a bench artifact
    # carries the bottleneck, not a guess.
    decode_wait_s: float = 0.0
    buffer_wait_s: float = 0.0
    # device-leg split, per superbatch, one field per pipeline stage
    # (each with a single writer thread): h2d_s — host→device transfer
    # wall, recorded on the TRANSFER stage; step_s — compiled-step
    # dispatch + the prior step's confirmation wait, recorded on the
    # STEP stage. The stages overlap (that's the point), so h2d_s no
    # longer serializes into the superbatch wall:
    # h2d_overlap_s — the portion of h2d_s spent while the step stage
    # was busy, i.e. transfer wall HIDDEN behind device compute
    # (h2d_overlap_s / h2d_s is bench.py's h2d_overlap_pct)
    h2d_s: float = 0.0
    step_s: float = 0.0
    h2d_overlap_s: float = 0.0
    # producer-side per-stage split, summed across the worker pool (so
    # with W workers the totals can exceed wall time): read_s — I/O +
    # block decode + checksum (binary) / fused read+parse (CSV, where
    # the native decoder doesn't separate them); cast_s — staging-dtype
    # conversion (binary; fused into read_s on CSV); enqueue_s — blocked
    # on the bounded shard queue (consumer too slow). When the e2e rate
    # disappoints, this names the NEXT bottleneck instead of leaving it
    # to archaeology.
    read_s: float = 0.0
    cast_s: float = 0.0
    enqueue_s: float = 0.0
    # per-dispatch training losses, most recent last (bounded to the
    # final _LOSS_KEEP dispatches so a million-step run stays O(1))
    losses: list = field(default_factory=list)
    metrics: dict = field(default_factory=dict)  # mse/mae on the holdout

    @property
    def records_per_s(self) -> float:
        return self.download_records / self.wall_s if self.wall_s else 0.0

    @property
    def h2d_overlap_pct(self) -> float:
        """Percentage of the H2D wall hidden behind device steps — the
        overlapped pipeline's direct measure, shared by every artifact
        that reports it (bench.py, soak_ingest, multichip_fit) so the
        key can never drift between them."""
        return (
            round(100.0 * self.h2d_overlap_s / self.h2d_s, 1) if self.h2d_s else 0.0
        )


_LOSS_KEEP = 1024


def default_workers(ncpu: int | None = None) -> int:
    """Producer pool size off host_cores: decode parallelism helps up to
    a point (the packing thread needs a core too), so leave one core
    free and cap the pool — beyond ~6 decoders the bounded queue, not
    decode, is the limit."""
    ncpu = ncpu or os.cpu_count() or 1
    return max(1, min(6, ncpu - 1))


def stream_shards(
    paths,
    passes: int = 1,
    max_records: int | None = None,
    queue_depth: int = 8,
    chunk_bytes: int = 8 * 1024 * 1024,
    offset: int = 0,
    end: int | None = None,
    workers: int = 1,
    half: bool = False,
    stats: "StreamStats | None" = None,
):
    """Generator of ``(feats, labels, total_rows)`` shards, decoded by
    background producer thread(s) through a bounded queue. ``total_rows``
    is the CUMULATIVE download-record count across everything yielded so
    far (per-worker deltas are summed internally), so the last yielded
    value is the whole stream's row count.

    Payload format is sniffed from the first file's magic bytes:

    - binary columnar blocks (schema/wire.py) — the zero-parse path:
      producers mmap block-aligned spans, verify checksums, and cast the
      precomputed pair tensors to the staging dtype. All residual decode
      work (CRC, f16 cast) runs IN the producer pool.
    - CSV — the fallback: producers drive the fused native parser
      (native/dfnative.cc) over newline-aligned byte spans.

    With ``workers > 1`` the dataset splits across that many producer
    threads (``workers=0`` → sized off host cores, ``default_workers``).
    Fewer files than workers is fine: files are split into aligned spans,
    so one big per-host dataset file decodes in parallel too. Shard
    order is then interleaved (fine for SGD). ``offset`` (a committed
    round boundary in the first file) is excluded on every pass, and
    ``end`` bounds the first file's read at the CURRENT round boundary —
    bytes a concurrent upload appends past it (which a failed stream's
    truncation may later remove) are never touched.
    ``stats``, when given, accumulates the producer-side read/cast/
    enqueue stage split. Abandoning the generator (consumer breaks
    early / errors) releases the producers: they observe the stop event
    instead of blocking forever on a full queue.
    """
    if isinstance(paths, (str, Path)):
        paths = [paths]
    paths = list(paths)
    if not paths:
        # an empty glob must be a clear error, not a ZeroDivisionError
        # from the span-splitting arithmetic below
        raise ValueError("stream_shards: no input files")
    if workers <= 0:
        workers = default_workers()
    binary = wire.is_block_file(paths[0])
    # resolve to (path, start, end) spans: applies the committed offset
    # once (so every pass skips consumed history) and gives each worker
    # a balanced byte share even when files < workers
    spans: list = []
    if binary:
        bounded = [
            (str(p), offset if j == 0 else 0, end if j == 0 else None)
            for j, p in enumerate(paths)
        ]
        spans = wire.split_block_spans(bounded)
    else:
        per_file = max(1, -(-workers // len(paths)))  # ceil
        for j, p in enumerate(paths):
            spans.extend(
                native.split_file_spans(
                    p,
                    per_file,
                    offset=offset if j == 0 else 0,
                    end=end if j == 0 else None,
                )
            )
    if not spans:
        return  # binary file with no complete blocks past the offset
    workers = max(1, min(workers, len(spans)))
    # queue items: per-worker rows are deltas, so interleaving is additive
    q: "queue.Queue" = queue.Queue(maxsize=queue_depth)
    stop = threading.Event()
    errors: list[BaseException] = []
    stats_lock = threading.Lock()

    def add_stage(stage: str, dt: float) -> None:
        if stats is None:
            return
        with stats_lock:
            if stage == "read":
                stats.read_s += dt
            elif stage == "cast":
                stats.cast_s += dt
            else:
                stats.enqueue_s += dt

    def produce(worker_spans):
        try:
            prev_rows = 0
            if binary:
                shard_iter = wire.stream_train_pairs(
                    worker_spans,
                    passes=passes,
                    max_records=max_records,
                    half=half,
                    stage_timer=add_stage,
                )
            else:
                # the native parser fuses file read + parse + (optional)
                # f16 emit, so its whole cost lands in read_s
                def csv_iter():
                    it = native.stream_pairs_file(
                        worker_spans,
                        passes=passes,
                        chunk_bytes=chunk_bytes,
                        max_records=max_records,
                        half=half,
                    )
                    while True:
                        t0 = time.perf_counter()
                        try:
                            item = next(it)
                        except StopIteration:
                            return
                        add_stage("read", time.perf_counter() - t0)
                        yield item

                shard_iter = csv_iter()
            for feats, labels, rows in shard_iter:
                item = (feats, labels, rows - prev_rows)
                prev_rows = rows
                t0 = time.perf_counter()
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.2)
                        break
                    except queue.Full:
                        continue
                add_stage("enqueue", time.perf_counter() - t0)
                if stop.is_set():
                    return
        except BaseException as e:  # surfaced to the consumer
            errors.append(e)
        finally:
            while not stop.is_set():
                try:
                    q.put(None, timeout=0.2)
                    break
                except queue.Full:
                    continue

    threads = []
    for w in range(workers):
        t = threading.Thread(
            target=produce,
            args=(spans[w::workers],),
            # <service>.<role> so dfprof/flight/Diagnose attribute by
            # role; the numeric suffix folds away in thread_role()
            name=f"trainer.ingest-decode-{w}",
            daemon=True,
        )
        t.start()
        threads.append(t)

    done = 0
    total_rows = 0
    try:
        while done < len(threads):
            item = q.get()
            if errors:
                # fail fast: one broken producer must abort the whole
                # stream now, not after the surviving workers finish a
                # multi-pass run whose result gets discarded anyway
                break
            if item is None:
                done += 1
                continue
            feats, labels, delta_rows = item
            if delta_rows:
                M.INGEST_RECORDS_TOTAL.inc(delta_rows)
            total_rows += delta_rows
            yield feats, labels, total_rows
            if max_records is not None and total_rows >= max_records:
                break
    finally:
        stop.set()
        # drain so producers blocked on put() can see the event and exit
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        for t in threads:
            t.join(timeout=5.0)
    if errors:
        raise errors[0]


_step_cache: dict = {}


def _optimizer_and_loss(learning_rate: float, weight_decay: float, warmup_steps: int):
    """Shared by the single-step and k-step factories — the scan path's
    'identical math' guarantee rests on there being exactly one
    definition of the schedule, optimizer, and loss."""
    import jax.numpy as jnp
    import optax

    from dragonfly2_tpu.models import mlp as mlp_mod

    schedule = optax.linear_schedule(0.0, learning_rate, max(warmup_steps, 1))
    optimizer = optax.adamw(schedule, weight_decay=weight_decay)

    def loss_fn(p, xb, yb):
        pred = mlp_mod.score_parents(p, xb)
        return jnp.mean((pred - yb) ** 2)

    return optimizer, loss_fn


def _get_step(learning_rate: float, weight_decay: float, warmup_steps: int = 64):
    """(optimizer, jitted step) cached per optimizer config, so repeated
    fits (and bench warmup vs timed run) reuse one compiled executable
    per batch shape instead of retracing a fresh closure each call.

    The schedule is linear warmup → constant: the streaming horizon is
    unknown up front (records arrive as bytes decode), so the batch
    path's cosine decay has no defined endpoint here; warmup covers the
    same early-drift window (train.py warmup_fraction).

    Everything host-side the feed once did lives INSIDE the jit now —
    the staging-dtype upcast, the feature/label split — and the carried
    state (params, opt_state) is donated: XLA writes each step's updates
    into the SAME HBM buffers instead of allocating a fresh copy per
    dispatch, and the donated inputs are invalidated (re-reading them
    raises — the dp>1 test pins this). The xy superbatch is deliberately
    NOT donated: no output shares its [.., F+1] shape, so XLA could
    never alias it — donating it would only emit a "donated buffer not
    usable" warning per compile while the buffer frees at its last use
    regardless."""
    key = (learning_rate, weight_decay, warmup_steps)
    if key in _step_cache:
        return _step_cache[key]
    import jax
    import jax.numpy as jnp

    optimizer, loss_fn = _optimizer_and_loss(learning_rate, weight_decay, warmup_steps)
    import optax

    def step(params, opt_state, xy):
        # one fused [B, F+1] transfer per batch (features ‖ label column):
        # H2D calls have per-call cost, and the upcast from the reduced
        # transfer dtype is free device-side (XLA fuses it into the first
        # matmul's compute-dtype cast)
        xy = xy.astype(jnp.float32)
        xb, yb = xy[:, :MLP_FEATURE_DIM], xy[:, MLP_FEATURE_DIM]
        loss, grads = jax.value_and_grad(loss_fn)(params, xb, yb)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(step, donate_argnums=(0, 1))
    _step_cache[key] = (optimizer, step)
    return optimizer, step


def _get_scan_step(
    learning_rate: float, weight_decay: float, k: int, warmup_steps: int = 64
):
    """(optimizer, jitted k-step call): one device dispatch runs ``k``
    sequential optimizer steps via ``lax.scan`` over a [k, B, F+1]
    superbatch. Amortizes per-dispatch overhead (host→device RPC,
    transfer setup, executable launch) over k steps — the lever that
    matters when the device link has per-call latency (remote chips,
    small batches). Identical math to k calls of the single step."""
    key = (learning_rate, weight_decay, warmup_steps, "scan", k)
    if key in _step_cache:
        return _step_cache[key]
    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax

    optimizer, loss_fn = _optimizer_and_loss(learning_rate, weight_decay, warmup_steps)

    def scan_step(params, opt_state, xy):
        xy = xy.astype(jnp.float32)

        def body(carry, slab):
            params, opt_state = carry
            xb, yb = slab[:, :MLP_FEATURE_DIM], slab[:, MLP_FEATURE_DIM]
            loss, grads = jax.value_and_grad(loss_fn)(params, xb, yb)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, opt_state), losses = lax.scan(body, (params, opt_state), xy)
        return params, opt_state, losses[-1]

    # same donation contract as _get_step: the carried state updates in
    # place; the [k, B, F+1] superbatch is shape-unaliasable (see above)
    scan_step = jax.jit(scan_step, donate_argnums=(0, 1))
    _step_cache[key] = (optimizer, scan_step)
    return optimizer, scan_step


def stream_train_mlp(
    paths,
    passes: int = 1,
    max_records: int | None = None,
    batch_size: int = 65_536,
    hidden_dims: tuple[int, ...] = (256, 256),
    learning_rate: float = 3e-3,
    weight_decay: float = 1e-4,
    queue_depth: int = 4,
    offset: int = 0,
    end: int | None = None,
    workers: int = 1,
    eval_every: int = 10,
    eval_max_batches: int = 16,
    params=None,
    mesh=None,
    transfer_dtype=np.float16,
    time_budget_s: float | None = None,
    steps_per_call: int = 1,
    stall_profile_dir: str = "",
) -> tuple[object, StreamStats]:
    """Fit the MLP parent scorer directly off disk bytes. Returns
    (params, StreamStats with holdout mse/mae in .metrics).

    Holdout: with ``eval_every`` > 0, pairs whose content hash lands in
    a 1/eval_every bucket are excluded from training on EVERY pass and
    scored at the end (collection capped at ``eval_max_batches`` worth of
    pairs to bound memory) — the streaming analogue of train_mlp's eval
    split. Content hashing keeps the holdout disjoint from the training
    set across multiple passes, which stream-position selection would
    not. Partial trailing
    batches are dropped when at least one full batch trained (static
    shapes keep one XLA executable hot); a dataset smaller than one batch
    trains a single ragged step so tiny hosts still fit. With ``mesh``,
    batches shard over its ``dp`` axis.

    ``transfer_dtype`` packs the host-side minibatch buffers (default
    float16): features are ratios/log-scales ≤ ~8, so halving H2D bytes
    costs ~5e-4 relative precision — upcast on device, where bf16 is the
    compute dtype anyway. Pass np.float32 for bit-exact feeds.

    ``time_budget_s`` bounds the wall clock: the stream stops consuming
    at the first shard boundary past the budget (``stats.truncated``
    set). The fit over what WAS consumed stays real — rates computed
    from ``stats.download_records`` remain honest. Benchmarks and
    interval-scheduled training rounds use this so a slow device link
    degrades to a shorter measurement, never an unbounded run.

    ``steps_per_call`` > 1 packs k minibatches into one [k, B, F+1]
    superbatch and runs k optimizer steps per device dispatch
    (``lax.scan`` device-side) — same math, 1/k the per-call overhead.
    Up to k·B trailing pairs are dropped at stream end (vs B with k=1),
    so keep k modest relative to the dataset.

    Stall watchdogs (utils/flight) ride the pipeline: a step-time or
    decode-wait observation regressing past ``DF_STALL_FACTOR`` × the
    trailing median dumps the flight rings to ``DF_DIAG_DIR`` while the
    stall is live, and — with ``stall_profile_dir`` set (the trainer
    passes its ``profile_dir``) — forces one ``jax.profiler`` capture
    of the stalled device leg.
    """
    import jax
    import jax.numpy as jnp

    from dragonfly2_tpu.models import mlp as mlp_mod

    optimizer, step = _get_step(learning_rate, weight_decay)
    k = max(1, int(steps_per_call))
    if k > 1:
        # same optimizer config (pytree-compatible opt_state); the scan
        # variant only changes how many steps one dispatch covers
        optimizer, scan_step = _get_scan_step(learning_rate, weight_decay, k)
    warm_bias = params is None  # fresh model: warm-start the output bias
    if params is None:
        params = mlp_mod.init_mlp(
            jax.random.PRNGKey(0), [MLP_FEATURE_DIM, *hidden_dims, 1]
        )
    if mesh is not None:
        from dragonfly2_tpu.parallel.sharding import replicate

        params = replicate(mesh, params)
    opt_state = None  # initialized at the first shard (after bias warm-start)

    if mesh is not None and batch_size % mesh.shape["dp"] == 0:
        from dragonfly2_tpu.parallel.sharding import shard_superbatch

        # rows shard over dp via per-device puts — each chip receives
        # ONLY its row shard (parallel.sharding.shard_superbatch; the
        # jit-witness mesh gate pins dp transfers per superbatch). The
        # superbatch's leading scan axis (k>1) stays unsharded — each
        # scan step is one dp-parallel batch.
        batch_dim = 0 if k == 1 else 1

        def put(buf):
            return shard_superbatch(mesh, buf, batch_dim=batch_dim)
    else:
        if mesh is not None:
            # a batch that doesn't divide the dp axis can't shard evenly;
            # feed replicated rather than fail the fit (the degenerate
            # twin of the ragged tiny-dataset rule below)
            logger.warning(
                "batch_size %d not divisible by dp=%d; feeding unsharded",
                batch_size,
                mesh.shape["dp"],
            )

        def put(buf):
            return jnp.asarray(buf)

    stats = StreamStats()
    # exemplar for the live pipeline histograms: the owning trace
    # (the fit span activated by Training._timed_fit) — None when no
    # sampled trace owns this run, which skips exemplar recording
    from dragonfly2_tpu.utils import tracing

    _owner = tracing.current_span()
    trace_exemplar = (
        {"trace_id": _owner.trace_id}
        if _owner is not None and _owner.sampled
        else None
    )
    # stall watchdogs: step-time regression (the device leg wedging —
    # the classic "TPU fit stalls and nobody sampled it") and decode
    # starvation. One shared profiler callback: the first stall forces
    # one jax.profiler capture via the trainer's profile_dir plumbing.
    _on_stall = (
        (lambda: flight.one_shot_profile(stall_profile_dir))
        if stall_profile_dir
        else None
    )
    step_watch = flight.StallWatchdog(
        "trainer.step", floor_s=0.25, on_stall=_on_stall, event=EV_STALL
    )
    decode_watch = flight.StallWatchdog(
        "trainer.decode_wait", floor_s=0.5, on_stall=_on_stall, event=EV_STALL
    )
    # Pipelined packing: fixed [batch_size·k, F+1] (features ‖ label)
    # buffers cycle through a free pool → packing → a TRANSFER stage →
    # a STEP stage, each stage its own thread. Dedicated device-leg
    # threads matter on a host whose device link has variable latency
    # (tunneled/remote chips): H2D transfer time under decode contention
    # was measured at 100-600 ms per superbatch, and paying that on the
    # packing thread stalls the decode pipeline behind it — measured
    # 110k → 200k records/s on a 1-core host by moving dispatch
    # off-thread. Splitting transfer from step (ISSUE 15) removes the
    # last serial bubble: the H2D for superbatch N+1 is issued WHILE
    # step N executes on device, so transfer wall hides behind compute
    # (measured per run as stats.h2d_overlap_s). A buffer is reused only
    # after the step that read it has materialized its loss: the CPU
    # backend's asarray/device_put can be ZERO-COPY, so the
    # asynchronously dispatched step may still read the numpy buffer
    # after dispatch returns (a real TPU always copies on H2D, but
    # correctness can't depend on the backend's copy behavior) — and the
    # in-flight transfer extends the same rule: a staged device array is
    # consumed (donated) by exactly one step before its host buffer
    # recycles.
    rows_per_call = batch_size * k
    free_bufs: "queue.Queue" = queue.Queue()
    # Six buffers / filled depth 3 / staged depth 2: one packing + up to
    # three queued-or-in-transfer + up to two transferred-awaiting-step
    # + one awaiting step confirmation. The device link's throughput is
    # bursty (tunneled chips measured 75 MB/s–1.5 GB/s within one run);
    # in-flight superbatches let decode run ahead through a slow patch
    # instead of stalling behind one delayed transfer. Memory cost:
    # 6 × k·B·(F+1) half-words (~126 MB at the bench shape) — bounded
    # and config-independent of file size.
    for _ in range(6):
        free_bufs.put(np.empty((rows_per_call, MLP_FEATURE_DIM + 1), transfer_dtype))
    filled_bufs: "queue.Queue" = queue.Queue(maxsize=3)
    staged_bufs: "queue.Queue" = queue.Queue(maxsize=2)
    disp_errors: list[BaseException] = []
    buf = free_bufs.get()
    fill = 0
    eval_cap_pairs = eval_max_batches * batch_size
    eval_x: list[np.ndarray] = []
    eval_y: list[np.ndarray] = []
    eval_collected = 0
    import collections

    loss_ring: "collections.deque" = collections.deque(maxlen=_LOSS_KEEP)
    t0 = time.perf_counter()

    # Two-stage device leg, one thread per stage, started together at
    # the first full superbatch:
    #
    #   transfer stage — consumes filled_bufs, issues the H2D put, hands
    #     (device array, host buffer, h2d wall) to staged_bufs. Because
    #     this runs on its own thread, superbatch N+1's transfer
    #     overlaps step N's execution; the overlap actually achieved is
    #     measured per put against the step stage's busy flag
    #     (stats.h2d_overlap_s).
    #   step stage — owns params/opt_state from its start to its join;
    #     dispatches the jitted (donating) step per staged superbatch
    #     and confirms the PREVIOUS step before recycling that step's
    #     host buffer (the reuse rule above).
    #
    # Each stage records ITS OWN wall (h2d on transfer, step on step) so
    # /debug/prof phases and the EV_SUPERBATCH event never double-count
    # one superbatch's wall; EV_SUPERBATCH is emitted once per
    # superbatch by the step stage, carrying the transfer stage's h2d
    # measurement forwarded through staged_bufs. stats.steps/loss_ring
    # writes are GIL-atomic with a single writer. On error either stage
    # keeps draining its input queue to the None sentinel (recycling
    # buffers) so the packing thread never deadlocks.
    state: dict = {}
    stage_threads: "list[threading.Thread]" = []
    # step-stage busy CLOCK (single writer: the step thread): "total"
    # accumulates completed busy intervals, "since" is nonzero while a
    # step is in flight. The transfer stage reads the clock at both
    # edges of each put and credits only the INTERSECTION of the put's
    # wall with step-busy time as overlap — an all-or-nothing edge
    # sample would credit a 600 ms transfer as fully hidden behind a
    # 5 ms step. Unlocked reads are safe: each field is written by one
    # thread and read whole under the GIL; a torn total/since pair can
    # only skew one put's credit, and the delta is clamped to [0, dt_h].
    step_busy = {"total": 0.0, "since": 0.0}

    def _step_busy_clock() -> float:
        t = step_busy["total"]
        since = step_busy["since"]
        if since:
            t += time.perf_counter() - since
        return t

    fn = step if k == 1 else scan_step

    def _transfer_loop():
        saw_sentinel = False
        # the owning fit span activates on this thread too (contextvars
        # don't cross threads), so the transfer-side histograms carry
        # the fit's trace_id exemplars
        span_cm = tracing.use_span(_owner)
        try:
            span_cm.__enter__()
            while True:
                b = filled_bufs.get()
                if b is None:
                    saw_sentinel = True
                    break
                if disp_errors:
                    # dead step stage: recycle so the packer unblocks,
                    # keep draining to the sentinel
                    free_bufs.put(b)
                    continue
                arg = b if k == 1 else b.reshape(k, batch_size, -1)
                busy0 = _step_busy_clock()
                t_h = time.perf_counter()
                dev = put(arg)
                dt_h = time.perf_counter() - t_h
                stats.h2d_s += dt_h
                # overlap = step-busy seconds elapsed DURING this put —
                # the transfer wall genuinely hidden behind device
                # compute, not an edge sample
                stats.h2d_overlap_s += min(
                    max(_step_busy_clock() - busy0, 0.0), dt_h
                )
                M.INGEST_H2D_SECONDS.observe(dt_h, exemplar=trace_exemplar)
                PH_H2D.observe(dt_h)
                staged_bufs.put((dev, b, dt_h))
        except BaseException as e:
            disp_errors.append(e)
            while not saw_sentinel:
                b = filled_bufs.get()
                if b is None:
                    break
                free_bufs.put(b)
        finally:
            # ALWAYS forward the shutdown downstream — the step stage's
            # only sentinel source is this stage
            staged_bufs.put(None)
            span_cm.__exit__(None, None, None)

    def _step_loop():
        prev_loss = prev_buf = None
        saw_sentinel = False
        span_cm = tracing.use_span(_owner)
        try:
            span_cm.__enter__()
            while True:
                item = staged_bufs.get()
                if item is None:
                    saw_sentinel = True
                    break
                dev, b, dt_h = item
                t_s = time.perf_counter()
                step_busy["since"] = t_s
                try:
                    state["params"], state["opt_state"], loss = fn(
                        state["params"], state["opt_state"], dev
                    )
                    loss_ring.append(loss)
                    stats.steps += k
                    if prev_loss is not None:
                        jax.block_until_ready(prev_loss)
                        free_bufs.put(prev_buf)
                    # step split = this dispatch + the prior step's
                    # confirmation wait: how long the device leg held
                    # the pipeline for one superbatch, as the host sees
                    # it — the h2d wall is NOT in here (it ran on the
                    # transfer stage, possibly concurrently)
                    dt_s = time.perf_counter() - t_s
                finally:
                    step_busy["total"] += time.perf_counter() - step_busy["since"]
                    step_busy["since"] = 0.0
                stats.step_s += dt_s
                M.INGEST_STEP_SECONDS.observe(dt_s, exemplar=trace_exemplar)
                PH_STEP.observe(dt_s)
                EV_SUPERBATCH(
                    h2d_s=round(dt_h, 6), step_s=round(dt_s, 6), steps=k
                )
                step_watch.observe(dt_s)
                prev_loss, prev_buf = loss, b
            if prev_loss is not None:
                jax.block_until_ready(prev_loss)
                free_bufs.put(prev_buf)
        except BaseException as e:
            disp_errors.append(e)
            if prev_buf is not None:
                free_bufs.put(prev_buf)
            # drain to the sentinel so the transfer stage never blocks
            # on staged_bufs — but only if the sentinel hasn't been
            # consumed yet: a failure in the post-sentinel tail (e.g.
            # the final block_until_ready raising on a dropped device
            # link) must not wait for a second sentinel that will never
            # come while the packer sits in join()
            while not saw_sentinel:
                item = staged_bufs.get()
                if item is None:
                    break
                free_bufs.put(item[1])
        finally:
            span_cm.__exit__(None, None, None)

    # native-side f16 emit skips the GIL-held f32→f16 numpy convert in
    # the packing loop below — the consumer thread is the bottleneck on
    # small hosts
    half = transfer_dtype == np.float16
    budget_end = None if time_budget_s is None else t0 + time_budget_s
    # the shutdown handshake lives in a finally: an exception out of the
    # packing loop (producer decode error re-raised by stream_shards, a
    # KeyboardInterrupt, …) must still send the sentinel and join, or the
    # dispatcher thread leaks blocked on filled_bufs.get() with its
    # buffers pinned — the long-lived trainer service calls this every
    # training round
    try:
        shard_iter = iter(
            stream_shards(
                paths,
                passes=passes,
                max_records=max_records,
                queue_depth=queue_depth,
                offset=offset,
                end=end,
                workers=workers,
                half=half,
                stats=stats,
            )
        )
        while True:
            w0 = time.perf_counter()
            try:
                feats, labels, rows = next(shard_iter)
            except StopIteration:
                break
            dt_w = time.perf_counter() - w0
            stats.decode_wait_s += dt_w
            M.INGEST_DECODE_WAIT_SECONDS.observe(dt_w, exemplar=trace_exemplar)
            PH_DECODE_WAIT.observe(dt_w)
            decode_watch.observe(dt_w)
            if budget_end is not None and time.perf_counter() > budget_end:
                stats.truncated = True
                break  # generator abandonment releases the producers
            if disp_errors:
                break
            stats.download_records = rows
            stats.pairs += feats.shape[0]
            if warm_bias and labels.size:
                # warm-start the output bias at (an estimate of) the label
                # mean so the regression head doesn't spend its first steps
                # drifting there (train_mlp does the same with the full-data
                # mean, train.py:137-138). dtype pinned to the init value's:
                # a weak-typed scalar fill would give the first step a
                # different jit signature than every later step — one extra
                # XLA compile mid-stream
                b = params["layers"][-1]["b"]
                params["layers"][-1]["b"] = jnp.full((1,), float(labels.mean()), dtype=b.dtype)
                warm_bias = False
            if opt_state is None:
                opt_state = optimizer.init(params)
            if eval_every > 0 and feats.shape[0]:
                # content-hash holdout: same pair → same bucket on every pass
                # (bucket assignment depends on the transfer dtype's bit
                # pattern; deterministic within a run config either way)
                u = np.uint16 if feats.dtype == np.float16 else np.uint32
                hv = feats.view(u).sum(axis=1, dtype=np.uint64)
                hv = (hv * np.uint64(2654435761) + labels.view(u)) & np.uint64(
                    0xFFFFFFFF
                )
                emask = (hv % np.uint64(eval_every)) == 0
                if emask.any():
                    if eval_collected < eval_cap_pairs:
                        # exclusion from training is the invariant that must
                        # hold on every pass; collection is cap-bounded (a
                        # later pass may re-collect a pair it already holds,
                        # which only reweights identical content in the
                        # metric, never leaks it into training)
                        ef = feats[emask]
                        eval_x.append(ef)
                        eval_y.append(labels[emask])
                        eval_collected += ef.shape[0]
                    feats = feats[~emask]
                    labels = labels[~emask]
            off = 0
            while off < feats.shape[0]:
                take = min(rows_per_call - fill, feats.shape[0] - off)
                buf[fill : fill + take, :MLP_FEATURE_DIM] = feats[off : off + take]
                buf[fill : fill + take, MLP_FEATURE_DIM] = labels[off : off + take]
                fill += take
                off += take
                if fill == rows_per_call:
                    # hand the full buffer to the device-leg stages and keep
                    # packing: transfer + step latency (large and variable on
                    # a tunneled device link) never stalls the decode pipeline
                    if not stage_threads:
                        state["params"], state["opt_state"] = params, opt_state
                        for target, role in (
                            (_transfer_loop, "transfer"),
                            (_step_loop, "step"),
                        ):
                            t = threading.Thread(
                                target=target,
                                name=f"trainer.ingest-{role}",
                                daemon=True,
                            )
                            t.start()
                            stage_threads.append(t)
                    w0 = time.perf_counter()
                    filled_bufs.put(buf)  # may block at queue depth
                    buf = free_bufs.get()
                    dt_b = time.perf_counter() - w0
                    stats.buffer_wait_s += dt_b
                    # the largest wall component finally has a live
                    # series + ledger phase next to its trio of siblings
                    M.INGEST_BUFFER_WAIT_SECONDS.observe(
                        dt_b, exemplar=trace_exemplar
                    )
                    PH_BUFFER_WAIT.observe(dt_b)
                    fill = 0
                    if disp_errors:
                        break
    finally:
        if stage_threads:
            # one sentinel into the head of the pipeline; the transfer
            # stage forwards it (its finally), so joining in order
            # drains both stages
            filled_bufs.put(None)
            for t in stage_threads:
                t.join()
            params, opt_state = state["params"], state["opt_state"]
    if disp_errors:
        raise disp_errors[0]
    stats.eval_pairs = eval_collected

    # Post-stream tail, in NAMED functions on purpose: the jit-witness
    # crosscheck fails any device feed attributed to stream_train_mlp's
    # own frame (the packing loop must never dispatch device work — it
    # would stall decode behind the device link), and these two run
    # once AFTER the pipeline drained, where a boundary conversion on
    # this thread is exactly right.
    def _ragged_tail(params, opt_state):
        # tiny dataset (< one batch): one ragged step so the fit is real.
        # Replicated (plain asarray), not dp-sharded — the ragged length
        # rarely divides the mesh axis, and one degenerate step doesn't
        # need data parallelism
        if opt_state is None:
            opt_state = optimizer.init(params)
        params, opt_state, pending_loss = step(
            params, opt_state, jnp.asarray(buf[:fill].copy())
        )
        loss_ring.append(pending_loss)
        stats.steps += 1
        return params, opt_state

    if stats.steps == 0 and fill > 0:
        params, opt_state = _ragged_tail(params, opt_state)
    stats.losses = [float(jax.block_until_ready(v)) for v in loss_ring]
    stats.wall_s = time.perf_counter() - t0
    # round milestone: the whole run's decode/transfer/compute split in
    # one ring entry — what bounded THIS fit, on permanent record
    EV_STREAM_DONE(
        records=stats.download_records,
        pairs=stats.pairs,
        steps=stats.steps,
        wall_s=round(stats.wall_s, 3),
        decode_wait_s=round(stats.decode_wait_s, 3),
        buffer_wait_s=round(stats.buffer_wait_s, 3),
        h2d_s=round(stats.h2d_s, 3),
        h2d_overlap_s=round(stats.h2d_overlap_s, 3),
        step_s=round(stats.step_s, 3),
        read_s=round(stats.read_s, 3),
        cast_s=round(stats.cast_s, 3),
        enqueue_s=round(stats.enqueue_s, 3),
        truncated=stats.truncated,
        stalls=step_watch.stalls + decode_watch.stalls,
    )

    def _eval_holdout():
        xe = np.concatenate(eval_x)
        ye = np.concatenate(eval_y)
        # the fit-end eval rides the shared memoized jit: a fresh
        # jax.jit wrapper per fit recompiled this same executable
        from dragonfly2_tpu.utils.jitcache import jit_once

        pred = np.asarray(jit_once(mlp_mod.score_parents)(params, jnp.asarray(xe)))
        err = pred - ye
        stats.metrics = {
            "mse": float(np.mean(err**2)),
            "mae": float(np.mean(np.abs(err))),
        }

    if eval_x:
        _eval_holdout()
    return params, stats
