"""Config loading for service binaries: YAML file + environment overrides
onto dataclass configs (reference cobra+viper yaml config per binary,
cmd/*/cmd/root.go; validation per scheduler/config/config.go Validate).

Precedence (last wins): dataclass defaults < YAML file < env vars <
explicit CLI flags (applied by the caller).

Env vars are ``<PREFIX>_<FIELD>`` with the field name upper-cased, e.g.
``DF_SCHEDULER_LISTEN=0.0.0.0:8002``. Values parse by the field's type
(int/float/bool/str); dict/list fields are YAML-parsed.
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path
from typing import Any, Type, TypeVar

import yaml

T = TypeVar("T")


class ConfigError(ValueError):
    pass


def _parse_scalar(raw: str, typ: Any) -> Any:
    if typ is bool or typ == "bool":
        return raw.strip().lower() in ("1", "true", "yes", "on")
    for t in (int, float):
        if typ is t:
            return t(raw)
    if typ is str:
        return raw
    # lists/dicts/optionals: YAML covers all of them
    return yaml.safe_load(raw)


def load_config(
    cls: Type[T],
    path: str | Path | None = None,
    env_prefix: str | None = None,
    overrides: dict[str, Any] | None = None,
) -> T:
    """Build a dataclass config from defaults + YAML + env + overrides,
    rejecting unknown keys (a typo'd key must fail loudly, not silently
    keep the default — the host_stats_override lesson)."""
    fields = {f.name: f for f in dataclasses.fields(cls)}
    values: dict[str, Any] = {}

    if path is not None:
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        if not isinstance(doc, dict):
            raise ConfigError(f"{path}: top level must be a mapping")
        for k, v in doc.items():
            if k not in fields:
                raise ConfigError(f"{path}: unknown config key {k!r} for {cls.__name__}")
            values[k] = v

    if env_prefix:
        for name, f in fields.items():
            raw = os.environ.get(f"{env_prefix}_{name.upper()}")
            if raw is not None:
                try:
                    values[name] = _parse_scalar(raw, f.type if isinstance(f.type, type) else _hint(cls, name))
                except Exception as e:
                    raise ConfigError(
                        f"{env_prefix}_{name.upper()}={raw!r}: {e}"
                    ) from e

    for k, v in (overrides or {}).items():
        if k not in fields:
            raise ConfigError(f"unknown config key {k!r} for {cls.__name__}")
        if v is None and not _allows_none(cls, k):
            # an explicit null may clear Optional fields, but injecting
            # None into an int/str/float field would surface later as an
            # unrelated TypeError deep in the service
            raise ConfigError(
                f"config key {k!r} of {cls.__name__} cannot be null"
            )
        values[k] = v

    return cls(**values)


def _allows_none(cls, name: str) -> bool:
    import types
    import typing

    h = typing.get_type_hints(cls).get(name)
    if h is None:
        return True
    if h is type(None):
        return True
    origin = typing.get_origin(h)
    # typing.Optional[X] and PEP 604 `X | None` both count
    if origin is typing.Union or origin is types.UnionType:
        return type(None) in typing.get_args(h)
    return False


def _hint(cls, name: str):
    import typing

    hints = typing.get_type_hints(cls)
    h = hints.get(name, str)
    origin = typing.get_origin(h)
    if origin is None:
        return h
    return object  # containers / optionals → YAML parse


def apply_jax_platform_env() -> None:
    """Pin the JAX platform from ``DF_JAX_PLATFORM`` before the first
    backend init. The container's sitecustomize registers the real-TPU
    backend for every process, so an env var alone is not enough (see
    tests/conftest.py) — and a dead TPU tunnel hangs backend init, so
    local CPU runs of any entry point need this hook. No-op when the
    variable is unset or jax is already pinned by the caller."""
    import os

    platform = os.environ.get("DF_JAX_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
