"""Shared serve/stop run loop for service binaries (reference
scheduler/scheduler.go:297-368 Serve/Stop + cmd signal handling).

A server object provides ``serve() -> address`` (bind, start background
loops, return the bound gRPC address) and ``stop()`` (graceful teardown).
``run()`` installs SIGINT/SIGTERM handlers, prints a machine-readable
``READY <name> <addr>`` line (hack/run_cluster.sh and the subprocess e2e
test wait for it), and blocks until signalled.
"""

from __future__ import annotations

import signal
import sys
import threading

from dragonfly2_tpu.utils import dflog

logger = dflog.get("cli")


def run(name: str, server) -> int:
    stop_event = threading.Event()

    def handle(signum, frame):
        logger.info("%s: received signal %s, shutting down", name, signum)
        stop_event.set()

    signal.signal(signal.SIGINT, handle)
    signal.signal(signal.SIGTERM, handle)

    try:
        addr = server.serve()
    except Exception:
        logger.exception("%s failed to start", name)
        return 1
    maddr = getattr(server, "metrics_addr", None)
    if maddr:
        print(f"METRICS {name} {maddr}", flush=True)
    raddr = getattr(server, "rest_addr", None)
    if raddr:
        print(f"REST {name} {raddr}", flush=True)
    gaddr = getattr(server, "gateway_addr", None)
    if gaddr:
        print(f"GATEWAY {name} {gaddr}", flush=True)
    kaddr = getattr(server, "kv_addr", None)
    if kaddr:
        print(f"KV {name} {kaddr}", flush=True)
    print(f"READY {name} {addr}", flush=True)
    try:
        stop_event.wait()
    finally:
        server.stop()
        logger.info("%s stopped", name)
    return 0


def main_with_config(name: str, build, argv=None) -> int:
    """Standard binary main: ``--config file.yaml`` plus ``--listen`` and
    free-form ``--set key=value`` overrides; ``build(config_path,
    overrides) -> server``."""
    import argparse

    p = argparse.ArgumentParser(prog=name)
    p.add_argument("--config", default=None, help="YAML config file")
    p.add_argument("--listen", default=None, help="gRPC listen address (host:port)")
    p.add_argument(
        "--set",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="override a config field (repeatable; value YAML-parsed)",
    )
    args = p.parse_args(argv)

    # test/e2e hook: force the JAX platform before any compute-plane
    # import (see cli/config.apply_jax_platform_env)
    from dragonfly2_tpu.cli.config import apply_jax_platform_env

    apply_jax_platform_env()

    # multi-host slice/DCN job: bring up jax.distributed before any
    # device query (no-op without DF_JAX_COORDINATOR)
    from dragonfly2_tpu.parallel.distributed import ensure_initialized

    ensure_initialized()

    import yaml

    overrides = {}
    if args.listen:
        overrides["listen"] = args.listen
    for item in args.set:
        k, _, v = item.partition("=")
        if not _:
            print(f"--set expects KEY=VALUE, got {item!r}", file=sys.stderr)
            return 2
        overrides[k] = yaml.safe_load(v)

    server = build(args.config, overrides)
    return run(name, server)
