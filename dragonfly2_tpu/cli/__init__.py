"""Service entrypoints: config loading and the serve/stop run loop shared
by `python -m dragonfly2_tpu.{manager,scheduler,trainer}` and
`python -m dragonfly2_tpu.client.daemon` (reference cmd/*/main.go)."""
