"""TopologyEngine: the device-resident probe graph and its query surface.

Lifecycle: ``NetworkTopology.enqueue_probe`` → ``enqueue`` (delta queue)
→ ``flush`` (drain, EWMA fold into the host store, staleness purge,
padded CSR build, device refresh, landmark re-selection + distance
solve) → queries (``est_rtt_ns``, ``neighbors``, ``rtt_affinity``,
``centrality``, ``stats``) served from the resident arrays, never the
KV store.

RTT inference (unprobed pairs): L landmark hosts (highest fresh degree)
keep min-plus distances to every host; est_rtt(a,b) = min over
landmarks of d(a,l)+d(l,b). Direct fresh edges win over inference.
Staleness: edges lose aggregation weight with a freshness half-life and
are purged outright past ``max_age_s`` — a departed or quiet edge fades
instead of pinning its last EWMA forever.
"""

# dfanalyze: hot — est_rtt_ns/rtt_affinity run per schedule decision
# dfanalyze: device-hot — queries dispatch the jitted kernels against
# the resident arrays; a whole-array host pull per query multiplies

from __future__ import annotations

import bisect
import threading
import time
import uuid
from dataclasses import dataclass

import numpy as np

from dragonfly2_tpu.schema import records as R
from dragonfly2_tpu.topology import metrics as TM
from dragonfly2_tpu.topology.csr import NS_PER_MS, AdjacencyStore
from dragonfly2_tpu.topology.delta import DeltaQueue, EdgeDelta
from dragonfly2_tpu.topology.kernels import INF_MS, make_kernels
from dragonfly2_tpu.trainer.serving import bucket_rows, pad_batch
from dragonfly2_tpu.utils import dflog, flight

logger = dflog.get("topology.engine")

# flight-recorder events: every flush (the device-array refresh — the
# moment a wrong RTT estimate was born), plus the non-direct inference
# outcomes (the estimates worth re-probing); direct/cache hits are too
# hot and too boring for a permanent record
EV_FLUSH = flight.event_type("topology.flush")
EV_INFERENCE = flight.event_type("topology.inference")


@dataclass
class TopologyConfig:
    backend: str = "auto"  # jax | numpy | auto
    num_landmarks: int = 8
    landmark_iters: int = 3  # min-plus relaxation rounds ≈ hop radius
    khop: int = 2
    # deltas buffered before an automatic flush (callers can flush
    # explicitly any time; the snapshot/export paths always do)
    flush_threshold: int = 256
    # staleness decay: half-life for aggregation weight, hard purge age
    half_life_s: float = 30 * 60.0
    max_age_s: float = 4 * 3600.0
    max_pending: int = 100_000
    inference_cache_size: int = 8192


class TopologyEngine:
    def __init__(self, config: TopologyConfig | None = None):
        self.cfg = config or TopologyConfig()
        self.kernels = make_kernels(self.cfg.backend)
        self.store = AdjacencyStore()
        self.deltas = DeltaQueue(self.cfg.max_pending)
        self._lock = threading.RLock()
        # serializes flushes so the kernel work can run OUTSIDE _lock
        # (queries keep reading the previous arrays meanwhile) without
        # two flushes racing the swap
        self._flush_lock = threading.Lock()
        # host-side numpy CSR/COO build (the query surface reads these
        # directly); only the COPIES _to_backend hands the kernels live
        # on device — keep it that way, or neighbors() grows a per-query
        # D2H pull back
        self._arrays: dict | None = None
        self._weights = None  # freshness weights at last flush
        self._D = None  # [node_cap, L] landmark distances (ms)
        self._khop_rtt = None  # [node_cap] aggregate (log-ms)
        self._landmark_idx: np.ndarray | None = None
        self._flush_count = 0
        self._dropped_seen = 0
        self._last_flush_at = 0.0
        # bumped on every out-of-flush store mutation (adopt,
        # delete_host): a flush whose build predates the bump must
        # rebuild instead of installing pre-mutation arrays
        self._store_version = 0
        # (src, dest) → (rtt_ns | None, provenance)
        self._cache: dict[tuple[str, str], tuple[float | None, str]] = {}
        self._cache_hits = 0
        self._cache_misses = 0
        self._query_lat_ms: list[float] = []  # sorted ring for p50/p99

    # ------------------------------------------------------------------
    # ingestion
    # ------------------------------------------------------------------
    def enqueue(
        self, src: str, dest: str, rtt_ns: int, created_at: float | None = None
    ) -> None:
        self.deltas.put(
            EdgeDelta(src, dest, rtt_ns, created_at if created_at is not None else time.time())
        )
        TM.DELTA_QUEUE_GAUGE.set(len(self.deltas))
        if len(self.deltas) >= self.cfg.flush_threshold:
            self.flush()

    def adopt(
        self, src: str, dest: str, avg_rtt_ns: float, updated_at: float
    ) -> bool:
        """Adopt an already-EWMA'd edge from the durable KV graph —
        hydration after a restart, and the merge path for edges probed
        via OTHER schedulers sharing the KV store (this process never
        saw their raw probes). Newer local state wins; the next flush
        folds adopted edges into the device arrays."""
        with self._lock:
            adopted = self.store.adopt_edge(src, dest, avg_rtt_ns, updated_at)
            if adopted:
                self._store_version += 1
            return adopted

    def delete_host(self, host_id: str) -> None:
        """Purge parity with NetworkTopology.delete_host: edges, pending
        deltas and cached inferences touching the host all go."""
        with self._lock:
            self.deltas.discard_host(host_id)
            if self.store.purge_host(host_id):
                self._store_version += 1
                self._refresh(time.time())
            self._cache.clear()

    # ------------------------------------------------------------------
    # flush: deltas → host store → device arrays
    # ------------------------------------------------------------------
    def flush(self, now: float | None = None) -> int:
        """Apply queued deltas and refresh the device arrays. Returns the
        number of deltas applied. The rebuild always runs — edge AGE
        advances between flushes, so skipping it would freeze staleness
        decay on a quiet probe plane. The kernel work runs OUTSIDE the
        query lock (``_flush_lock`` serializes flushes): est_rtt callers
        keep reading the previous arrays until the swap."""
        now = time.time() if now is None else now
        with self._flush_lock:
            t0 = time.perf_counter()
            batch = self.deltas.drain()
            with self._lock:
                for d in batch:
                    self.store.apply_probe(d.src, d.dest, d.rtt_ns, d.created_at)
                purged = self.store.purge_stale(now, self.cfg.max_age_s)
                arr = self._build_arrays(now)
                built_version = self._store_version
            computed = self._run_kernels(arr)
            with self._lock:
                if self._store_version == built_version:
                    self._swap(arr, computed)
                else:
                    # an adopt/delete_host landed mid-kernel: the built
                    # arrays are stale — rebuild from the current store
                    self._refresh(now)
                self._flush_count += 1
                self._last_flush_at = now
            if purged:
                TM.STALE_PURGED_TOTAL.inc(purged)
            TM.FLUSH_TOTAL.inc()
            EV_FLUSH(
                applied=len(batch),
                purged=purged,
                hosts=len(self.store.index),
                edges=self.store.num_edges,
                wall_ms=round((time.perf_counter() - t0) * 1e3, 3),
            )
            TM.FLUSH_LATENCY.observe(time.perf_counter() - t0)
            TM.DELTA_QUEUE_GAUGE.set(len(self.deltas))
            dropped = self.deltas.dropped
            if dropped > self._dropped_seen:
                TM.DELTA_DROPPED_TOTAL.inc(dropped - self._dropped_seen)
                self._dropped_seen = dropped
            return len(batch)

    def _refresh(self, now: float) -> None:
        """Build + kernels + swap in one step — for callers already
        holding ``_lock`` (delete_host, first-touch builds)."""
        arr = self._build_arrays(now)
        self._swap(arr, self._run_kernels(arr))

    def _build_arrays(self, now: float) -> dict:
        """Padded CSR + landmark selection from the host store (caller
        holds ``_lock``)."""
        prev_ncap = len(self._arrays["row_ptr"]) - 1 if self._arrays else 0
        prev_ecap = len(self._arrays["edge_src"]) if self._arrays else 0
        arr = self.store.build_arrays(now, prev_ncap, prev_ecap)
        ncap = len(arr["row_ptr"]) - 1

        # landmarks: highest fresh-degree hosts (deterministic: degree
        # desc, index asc), computed host-side — tiny, control-flow-y
        e = arr["num_edges"]
        deg = np.bincount(arr["edge_src"][:e], minlength=ncap) + np.bincount(
            arr["edge_dst"][:e], minlength=ncap
        )
        live = np.zeros(ncap, dtype=bool)
        for i, hid in enumerate(self.store.ids):
            live[i] = bool(hid)  # tombstoned hosts keep their slot, not their rank
        deg = np.where(live, deg, -1)
        L = self.cfg.num_landmarks
        order = np.argsort(-deg, kind="stable")[:L]
        lm_idx = np.zeros(L, dtype=np.int32)
        lm_valid = np.zeros(L, dtype=np.float32)
        n_lm = 0
        for idx in order:
            if deg[idx] >= 0 and live[idx]:
                lm_idx[n_lm] = idx
                lm_valid[n_lm] = 1.0
                n_lm += 1
        arr["landmark_idx"] = lm_idx
        arr["landmark_valid"] = lm_valid
        arr["num_landmarks"] = n_lm
        return arr

    def _run_kernels(self, arr: dict) -> dict:
        """Decay → k-hop aggregate → landmark distances over built
        arrays — pure array math, no engine state, safe outside
        ``_lock``."""
        ncap = len(arr["row_ptr"]) - 1
        xp = self.kernels
        dev = self._to_backend(arr)
        w = xp.decay_weights(dev["age_s"], dev["valid"], self.cfg.half_life_s)
        khop = xp.khop_rtt(
            dev["edge_src"], dev["edge_dst"], dev["rtt_log_ms"], w,
            num_nodes=ncap, k=self.cfg.khop,
        )

        # symmetrized edge list for distance inference: probes are
        # directed but RTT is (to first order) symmetric, and min-plus
        # needs to traverse an edge both ways
        sym_src = np.concatenate([arr["edge_src"], arr["edge_dst"]])
        sym_dst = np.concatenate([arr["edge_dst"], arr["edge_src"]])
        rtt_ms = np.expm1(arr["rtt_log_ms"]).astype(np.float32)
        sym_rtt = np.concatenate([rtt_ms, rtt_ms])
        sym_w = np.concatenate([arr["valid"], arr["valid"]])
        sd = self._to_backend(
            {"src": sym_src, "dst": sym_dst, "rtt": sym_rtt, "w": sym_w}
        )
        lm = self._to_backend(
            {"li": arr["landmark_idx"], "lv": arr["landmark_valid"]}
        )
        D = xp.landmark_distances(
            sd["src"], sd["dst"], sd["rtt"], sd["w"],
            lm["li"], lm["lv"],
            num_nodes=ncap, iters=self.cfg.landmark_iters,
        )
        return {"weights": w, "khop": khop, "D": D}

    def _swap(self, arr: dict, computed: dict) -> None:
        """Install a finished build (caller holds ``_lock``)."""
        self._arrays = arr
        self._weights = computed["weights"]
        # khop lands host-side HERE, once per flush: its only consumer
        # (khop_rtt_log_ms) reads single elements per query, and pulling
        # the whole device array back per query was a D2H round trip on
        # the schedule-decision path
        self._khop_rtt = np.asarray(computed["khop"])
        self._D = computed["D"]
        self._landmark_idx = arr["landmark_idx"][: arr["num_landmarks"]].copy()
        self._cache.clear()
        TM.EDGE_GAUGE.set(self.store.num_edges)
        TM.HOST_GAUGE.set(len(self.store.index))

    def _to_backend(self, arrays: dict) -> dict:
        """numpy → device arrays on the jax backend (HBM when an
        accelerator is attached); identity on the numpy backend."""
        if self.kernels.backend != "jax":
            return arrays
        import jax.numpy as jnp

        return {
            k: (jnp.asarray(v) if isinstance(v, np.ndarray) else v)
            for k, v in arrays.items()
        }

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------
    def est_rtt_ns(self, src: str, dest: str) -> int | None:
        """Best RTT estimate: direct fresh edge (EWMA) → landmark
        inference → None (host unknown or no path). Symmetric on input
        order for inferred pairs by construction."""
        return self.est_rtt_detail(src, dest)[0]

    def est_rtt_detail(self, src: str, dest: str) -> tuple[int | None, str]:
        """(rtt_ns, provenance) where provenance ∈ {"self", "direct",
        "inferred", "none"} — resolved under one lock so the answer and
        its provenance can't disagree (a flush or delete between two
        lookups)."""
        if src == dest:
            return 0, "self"
        t0 = time.perf_counter()
        with self._lock:
            key = (src, dest)
            if key in self._cache:
                self._cache_hits += 1
                TM.QUERY_TOTAL.labels("cache").inc()
                self._note_latency(t0)
                out, source = self._cache[key]
                return self._intify(out), source
            self._cache_misses += 1
            out, source = self._est_rtt_locked(src, dest)
            if source != "direct":
                # the inferred/no-path answers are the ones an operator
                # wants on record (an inferred estimate says "probe this
                # pair to confirm"); direct hits would flood the ring
                EV_INFERENCE(
                    src=src,
                    dest=dest,
                    provenance=source,
                    rtt_ns=self._intify(out),
                )
            if len(self._cache) >= self.cfg.inference_cache_size:
                self._cache.clear()
            self._cache[key] = (out, source)
            self._note_latency(t0)
            return self._intify(out), source

    def _est_rtt_locked(self, src: str, dest: str) -> tuple[float | None, str]:
        s = self.store.index.get(src)
        d = self.store.index.get(dest)
        if s is None or d is None:
            TM.QUERY_TOTAL.labels("unknown").inc()
            return None, "none"
        edge = self.store.edges.get((s, d)) or self.store.edges.get((d, s))
        if edge is not None:
            TM.QUERY_TOTAL.labels("direct").inc()
            return float(edge[0]), "direct"
        if self._D is None:
            return None, "none"
        est_ms = float(
            np.asarray(
                self.kernels.est_from_landmarks(
                    self._D, *self._to_backend_idx(s, d)
                )
            )[0]
        )
        if est_ms >= INF_MS / 2:
            TM.QUERY_TOTAL.labels("no_path").inc()
            return None, "none"
        TM.QUERY_TOTAL.labels("inferred").inc()
        return est_ms * NS_PER_MS, "inferred"

    def _to_backend_idx(self, s: int, d: int):
        a = np.array([s], dtype=np.int32)
        b = np.array([d], dtype=np.int32)
        out = self._to_backend({"a": a, "b": b})
        return out["a"], out["b"]

    @staticmethod
    def _intify(v: float | None) -> int | None:
        return None if v is None else int(v)

    def neighbors(self, host_id: str, limit: int = 32) -> list[dict]:
        """Fresh out-edges of ``host_id`` from the CSR rows, nearest
        first: [{host_id, avg_rtt_ns, age_s}]."""
        if self._arrays is None:
            # outside _lock: flush takes _flush_lock → _lock, so calling
            # it under _lock would invert the order (ABBA deadlock with
            # a concurrent flusher)
            self.flush()
        with self._lock:
            idx = self.store.index.get(host_id)
            if idx is None:
                return []
            # the built arrays are host numpy by construction (_swap
            # installs the build dict; only the kernel inputs go to the
            # backend) — no conversion on the query path
            arr = self._arrays
            row_ptr = arr["row_ptr"]
            lo, hi = int(row_ptr[idx]), int(row_ptr[idx + 1])
            dst = arr["edge_dst"][lo:hi]
            out = []
            for d in dst:
                e = self.store.edges.get((idx, int(d)))
                if e is None:
                    continue
                out.append(
                    {
                        "host_id": self.store.ids[int(d)],
                        "avg_rtt_ns": int(e[0]),
                        "age_s": max(time.time() - e[1], 0.0),
                    }
                )
            out.sort(key=lambda r: r["avg_rtt_ns"])
            return out[:limit]

    def rtt_affinity(self, src: str, dest: str) -> float:
        """The MLP feature: log1p(est RTT in ms)/10 — same normalization
        family as the tcp-connection features — 0.0 when unknown (the
        missing-value the schema documents, so live and trained
        distributions agree on the missing case)."""
        rtt = self.est_rtt_ns(src, dest)
        if rtt is None:
            return 0.0
        return float(np.log1p(rtt / NS_PER_MS) / 10.0)

    def rtt_affinity_pairs(self, src_ids, dst_ids) -> np.ndarray:
        """[N] src (child) host ids × [N] dst (parent) host ids → [N]
        rtt_affinity in ONE lock hold and ONE rung-padded gather
        dispatch — the wave-join form of :meth:`rtt_affinity`.
        Per-pair resolution order matches the scalar path (self →
        direct fresh edge → landmark inference → 0.0 missing-value);
        what it skips is the per-pair machinery (inference cache,
        EV_INFERENCE ring, per-query metrics) — a W×C wave would flood
        all three, and the scalar path remains the provenance story.
        The pair arrays ride the serving BUCKET_LADDER so steady-state
        waves never retrace the gather kernel."""
        n = len(src_ids)
        out = np.zeros(n, dtype=np.float32)
        if n == 0:
            return out
        need_src = np.zeros(n, dtype=np.int32)
        need_dst = np.zeros(n, dtype=np.int32)
        known = np.zeros(n, dtype=bool)
        direct_ms = np.zeros(n, dtype=np.float32)
        has_direct = np.zeros(n, dtype=bool)
        with self._lock:
            index = self.store.index
            edges = self.store.edges
            D = self._D  # immutable snapshot: _swap installs new arrays
            for i in range(n):
                src, dst = src_ids[i], dst_ids[i]
                if src == dst:
                    # self pair: a 0 ms direct edge ⇒ affinity 0.0
                    known[i] = has_direct[i] = True
                    continue
                s = index.get(src)
                d = index.get(dst)
                if s is None or d is None:
                    continue
                known[i] = True
                edge = edges.get((s, d)) or edges.get((d, s))
                if edge is not None:
                    has_direct[i] = True
                    direct_ms[i] = edge[0] / NS_PER_MS
                else:
                    need_src[i] = s
                    need_dst[i] = d
        if D is None or not bool(np.any(known & ~has_direct)):
            # nothing to infer: direct-only affinity, no kernel dispatch
            m = known & has_direct
            out[m] = np.log1p(direct_ms[m]) / np.float32(10.0)
            return out
        rows = bucket_rows(n)
        dev = self._to_backend(
            {
                "src": pad_batch(need_src, rows),
                "dst": pad_batch(need_dst, rows),
                "direct_ms": pad_batch(direct_ms, rows),
                "has_direct": pad_batch(has_direct.astype(np.float32), rows),
                "known": pad_batch(known.astype(np.float32), rows),
            }
        )
        padded = self.kernels.gather_rtt_affinity(
            D,
            dev["src"],
            dev["dst"],
            dev["direct_ms"],
            dev["has_direct"],
            dev["known"],
        )
        # whole-rung D2H then host slice (allowlisted host-pull): a
        # device [:n] would retrace a dynamic_slice per distinct n
        aff = np.asarray(padded)[:n]
        return aff.astype(np.float32, copy=False)

    def rtt_affinity_batch(
        self, child_ids: np.ndarray, parent_ids: np.ndarray
    ) -> np.ndarray:
        """[N] child host ids × [N, P] parent host ids → [N, P]
        rtt_affinity — the block-encode-time join (scheduler Storage)
        that puts the same feature distribution into the training data
        the live evaluator feeds the model. One flattened
        :meth:`rtt_affinity_pairs` gather for the whole block — the
        per-distinct-pair scalar loop paid one engine lock round-trip
        per pair; empty ids resolve to the 0.0 missing-value either
        way."""
        child_ids = np.asarray(child_ids)
        parent_ids = np.asarray(parent_ids)
        if parent_ids.size == 0:
            return np.zeros(parent_ids.shape, dtype=np.float32)
        n, p = parent_ids.shape
        src = [str(c) for c in np.repeat(child_ids, p)]
        dst = [str(q) for q in parent_ids.reshape(-1)]
        return self.rtt_affinity_pairs(src, dst).reshape(n, p)

    def centrality(self, candidates: list[str] | None = None) -> list[dict]:
        """Mean inferred RTT from every live host to each candidate,
        ascending (the seed-placement ranking): [{host_id,
        mean_rtt_ms}]. Pairs with no path are excluded from the mean;
        candidates unreachable from everywhere are dropped.

        Snapshots the store under ``_lock``, then does the O(C·H)
        array math UNLOCKED — a background seed-recommendation job must
        not stall the evaluator's est_rtt hot path. ``flush`` runs
        before taking ``_lock`` (flush takes _flush_lock → _lock; a
        flush call under _lock would invert that order and deadlock
        against a concurrent flusher)."""
        if self._arrays is None:
            self.flush()
        with self._lock:
            if self._D is None:
                return []
            D = np.asarray(self._D)
            live = list(self.store.index.items())
            index = dict(self.store.index)
            edges = [(s, d, v[0]) for (s, d), v in self.store.edges.items()]
        if not live:
            return []
        pool = (
            [(h, index[h]) for h in candidates if h in index]
            if candidates is not None
            else live
        )
        idxs = np.array([i for _, i in live], dtype=np.int32)
        pos = {int(i): p for p, i in enumerate(idxs)}
        # direct fresh edges beat inference, as in est_rtt_ns: index
        # them per node once (O(E)) instead of probing every pair
        touch: dict[int, list[tuple[int, float]]] = {}
        for s, d, rtt_ns in edges:
            touch.setdefault(s, []).append((d, rtt_ns))
            touch.setdefault(d, []).append((s, rtt_ns))
        out = []
        for hid, i in pool:
            est = np.min(D[idxs] + D[i][None, :], axis=-1)  # [H] landmark est
            for j, rtt_ns in touch.get(i, ()):
                p = pos.get(int(j))
                if p is not None:
                    est[p] = min(est[p], rtt_ns / NS_PER_MS)
            est[pos[int(i)]] = INF_MS  # self is not a fleet member to average
            finite = est[est < INF_MS / 2]
            if len(finite) == 0:
                continue
            out.append({"host_id": hid, "mean_rtt_ms": round(float(finite.mean()), 4)})
        out.sort(key=lambda r: r["mean_rtt_ms"])
        return out

    def khop_rtt_log_ms(self, host_id: str) -> float | None:
        """The k-hop EWMA-RTT aggregate for one host (log-ms)."""
        with self._lock:
            idx = self.store.index.get(host_id)
            if idx is None or self._khop_rtt is None:
                return None
            return float(self._khop_rtt[idx])  # host copy since _swap

    def stats(self) -> dict:
        with self._lock:
            total = self._cache_hits + self._cache_misses
            hit_rate = self._cache_hits / total if total else 0.0
            TM.INFERENCE_CACHE_HIT_RATE.set(hit_rate)
            return {
                "backend": self.kernels.backend,
                "hosts": len(self.store.index),
                "edges": self.store.num_edges,
                "pending_deltas": len(self.deltas),
                "dropped_deltas": self.deltas.dropped,
                "flushes": self._flush_count,
                "landmarks": int(len(self._landmark_idx))
                if self._landmark_idx is not None
                else 0,
                "cache_hit_rate": round(hit_rate, 4),
                "query_p50_ms": self.query_p50_ms(),
                "last_flush_at": self._last_flush_at,
            }

    # ------------------------------------------------------------------
    # export: the snapshot path reads the adjacency, not the KV store
    # ------------------------------------------------------------------
    def export_records(self, host_manager, dest_limit: int) -> list:
        """NetworkTopologyRecord rows straight from the resident
        adjacency — the trainer-bound GNN snapshot without a KV walk.
        Freshest ``dest_limit`` dests per source (parity with
        NetworkTopology.export_records' recency preference)."""
        # flush BEFORE taking _lock (flush's order is _flush_lock →
        # _lock; the reverse would ABBA-deadlock with a concurrent
        # flusher, e.g. the 30s GC flush task)
        self.flush()
        with self._lock:
            by_src: dict[int, list[tuple[int, list[float]]]] = {}
            for (s, d), v in self.store.edges.items():
                by_src.setdefault(s, []).append((d, [v[0], v[1]]))

            out = []
            now_ns = int(time.time() * 1e9)
            for s, dests in by_src.items():
                sh = host_manager.load(self.store.ids[s])
                if sh is None:
                    continue
                dests.sort(key=lambda t: -t[1][1])  # most recently updated first
                dest_hosts = []
                for d, v in dests[:dest_limit]:
                    dh = host_manager.load(self.store.ids[d])
                    if dh is None:
                        continue
                    dest_hosts.append(
                        R.DestHost(
                            id=dh.id,
                            type=dh.type.value,
                            hostname=dh.hostname,
                            ip=dh.ip,
                            port=dh.port,
                            network=dh.network,
                            probes=R.ProbesRecord(
                                average_rtt=int(v[0]),
                                created_at=int(v[1] * 1e9),
                                updated_at=int(v[1] * 1e9),
                            ),
                        )
                    )
                if not dest_hosts:
                    continue
                out.append(
                    R.NetworkTopologyRecord(
                        id=str(uuid.uuid4()),
                        host=R.SrcHost(
                            id=sh.id,
                            type=sh.type.value,
                            hostname=sh.hostname,
                            ip=sh.ip,
                            port=sh.port,
                            network=sh.network,
                        ),
                        dest_hosts=dest_hosts,
                        created_at=now_ns,
                    )
                )
            return out

    # ------------------------------------------------------------------
    def _note_latency(self, t0: float) -> None:
        ms = (time.perf_counter() - t0) * 1e3
        bisect.insort(self._query_lat_ms, ms)
        if len(self._query_lat_ms) > 4096:
            # drop extremes pairwise so the ring stays a sample, not a
            # monotone accumulation
            self._query_lat_ms = self._query_lat_ms[1:-1]

    def query_p50_ms(self) -> float:
        with self._lock:
            if not self._query_lat_ms:
                return 0.0
            return round(self._query_lat_ms[len(self._query_lat_ms) // 2], 6)
