"""Batching delta queue between ``NetworkTopology.enqueue_probe`` and the
device adjacency.

Probe ingestion happens per-RPC on the SyncProbes stream; refreshing
device arrays per probe would serialize scheduling on H2D transfers.
The queue absorbs updates cheaply (a lock + list append) and the engine
drains it in batches at flush time — the same shape as the record sink's
buffered writes (scheduler/storage.py).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass(frozen=True)
class EdgeDelta:
    """One probe measurement headed for the adjacency."""

    src: str
    dest: str
    rtt_ns: int
    created_at: float = field(default_factory=time.time)


class DeltaQueue:
    """Unbounded-by-default FIFO of edge deltas with a drop-oldest cap.

    A cap exists because a wedged flusher must not let the queue grow
    without bound on a busy probe plane; dropping the OLDEST deltas is
    safe — the EWMA weighting (0.9 on the newest sample) means later
    probes dominate the average anyway, so old deltas carry the least
    information.
    """

    def __init__(self, max_pending: int = 100_000):
        self._lock = threading.Lock()
        self._items: list[EdgeDelta] = []
        self._dropped = 0
        self.max_pending = max_pending

    def put(self, delta: EdgeDelta) -> None:
        with self._lock:
            self._items.append(delta)
            if len(self._items) > self.max_pending:
                overflow = len(self._items) - self.max_pending
                del self._items[:overflow]
                self._dropped += overflow

    def drain(self) -> list[EdgeDelta]:
        """Take everything queued so far (order preserved)."""
        with self._lock:
            items, self._items = self._items, []
            return items

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def discard_host(self, host_id: str) -> int:
        """Drop pending deltas touching a departed host (delete_host
        parity: a flush after the purge must not resurrect its edges)."""
        with self._lock:
            before = len(self._items)
            self._items = [
                d for d in self._items if d.src != host_id and d.dest != host_id
            ]
            return before - len(self._items)
