"""Host-side adjacency store + padded CSR build.

The store is the exact mutable truth (EWMA fold per probe, host purge);
the CSR build turns it into fixed-capacity arrays the jitted kernels
consume. Capacities only grow, by doubling — static shapes are what let
the kernels stay compiled (TPU tiling wants fixed array extents; a
per-flush shape change would recompile every flush).

Padding convention: unused edge slots carry ``src = dst = 0`` with
``weight = 0`` — in-bounds for gathers (the pallas/XLA static-bound
masking idiom), zeroed out of every reduction by the weight.
"""

from __future__ import annotations

import numpy as np

from dragonfly2_tpu.scheduler.networktopology import EWMA_OLD_WEIGHT

NS_PER_MS = 1e6


def _next_capacity(needed: int, current: int) -> int:
    cap = max(current, 8)
    while cap < needed:
        cap *= 2
    return cap


class AdjacencyStore:
    """Interned directed edge store: (src_idx, dst_idx) → EWMA RTT +
    update time, with the same EWMA the KV path applies
    (networktopology.enqueue_probe), so both views of a probe sequence
    agree exactly."""

    def __init__(self):
        self.index: dict[str, int] = {}
        self.ids: list[str] = []
        # (src_idx, dst_idx) -> [avg_rtt_ns, updated_at_s]
        self.edges: dict[tuple[int, int], list[float]] = {}

    # -- interning --------------------------------------------------------
    def intern(self, host_id: str) -> int:
        idx = self.index.get(host_id)
        if idx is None:
            idx = len(self.ids)
            self.index[host_id] = idx
            self.ids.append(host_id)
        return idx

    @property
    def num_hosts(self) -> int:
        return len(self.ids)

    @property
    def num_edges(self) -> int:
        return len(self.edges)

    # -- mutation ---------------------------------------------------------
    def apply_probe(self, src: str, dest: str, rtt_ns: float, at: float) -> None:
        s, d = self.intern(src), self.intern(dest)
        e = self.edges.get((s, d))
        if e is None or e[0] <= 0:
            self.edges[(s, d)] = [float(rtt_ns), at]
        else:
            e[0] = float(
                int(EWMA_OLD_WEIGHT * e[0] + (1 - EWMA_OLD_WEIGHT) * rtt_ns)
            )
            e[1] = max(e[1], at)

    def adopt_edge(
        self, src: str, dest: str, avg_rtt_ns: float, updated_at: float
    ) -> bool:
        """Install an already-averaged edge (KV hydration / cross-
        scheduler merge) — no EWMA fold, and never clobber a fresher
        locally-maintained value."""
        s, d = self.intern(src), self.intern(dest)
        e = self.edges.get((s, d))
        if e is not None and e[1] >= updated_at:
            return False
        self.edges[(s, d)] = [float(avg_rtt_ns), updated_at]
        return True

    def purge_host(self, host_id: str) -> bool:
        """Remove a host's node and every incident edge. The node index
        is NOT recycled (ids keep their dense slot; the id string is
        tombstoned) so edge keys of other hosts stay valid."""
        idx = self.index.pop(host_id, None)
        if idx is None:
            return False
        self.ids[idx] = ""
        self.edges = {
            (s, d): v for (s, d), v in self.edges.items() if s != idx and d != idx
        }
        return True

    def purge_stale(self, now: float, max_age_s: float) -> int:
        """Drop edges whose last update is older than ``max_age_s`` —
        the terminal stage of staleness decay: quiet edges first lose
        aggregation weight (kernels.decay_weights), then disappear."""
        stale = [k for k, v in self.edges.items() if now - v[1] > max_age_s]
        for k in stale:
            del self.edges[k]
        return len(stale)

    # -- CSR build --------------------------------------------------------
    def build_arrays(
        self, now: float, node_cap: int = 0, edge_cap: int = 0
    ) -> dict[str, np.ndarray]:
        """→ padded CSR + COO arrays (numpy; the engine ships them to the
        device).

        Keys: ``row_ptr`` [node_cap+1], ``edge_src``/``edge_dst``
        [edge_cap] (CSR order: sorted by src, so ``col_idx`` ==
        ``edge_dst``), ``rtt_log_ms`` [edge_cap], ``age_s`` [edge_cap],
        ``valid`` [edge_cap] float32 mask.
        """
        n = self.num_hosts
        node_cap = _next_capacity(max(n, 1), node_cap)
        edge_cap = _next_capacity(max(self.num_edges, 1), edge_cap)

        e = self.num_edges
        src = np.zeros(edge_cap, dtype=np.int32)
        dst = np.zeros(edge_cap, dtype=np.int32)
        rtt = np.zeros(edge_cap, dtype=np.float32)
        age = np.zeros(edge_cap, dtype=np.float32)
        valid = np.zeros(edge_cap, dtype=np.float32)
        if e:
            keys = np.array(sorted(self.edges), dtype=np.int64)  # CSR order
            vals = np.array([self.edges[(s, d)] for s, d in keys], dtype=np.float64)
            src[:e] = keys[:, 0]
            dst[:e] = keys[:, 1]
            rtt[:e] = np.log1p(np.maximum(vals[:, 0], 0.0) / NS_PER_MS)
            age[:e] = np.maximum(now - vals[:, 1], 0.0)
            valid[:e] = 1.0

        row_ptr = np.zeros(node_cap + 1, dtype=np.int32)
        if e:
            counts = np.bincount(src[:e], minlength=node_cap)
            row_ptr[1:] = np.cumsum(counts)
        return {
            "row_ptr": row_ptr,
            "edge_src": src,
            "edge_dst": dst,
            "rtt_log_ms": rtt,
            "age_s": age,
            "valid": valid,
            "num_nodes": n,
            "num_edges": e,
        }
