"""Topology-engine Prometheus series (lands in the shared
default_registry next to the scheduler's, so one /metrics endpoint
carries both)."""

from dragonfly2_tpu.utils.metrics import default_registry as _r

EDGE_GAUGE = _r.gauge(
    "topology_edges", "Edges resident in the device adjacency"
)
HOST_GAUGE = _r.gauge(
    "topology_hosts", "Hosts interned in the device adjacency"
)
DELTA_QUEUE_GAUGE = _r.gauge(
    "topology_delta_queue_depth", "Probe deltas waiting for the next flush"
)
DELTA_DROPPED_TOTAL = _r.counter(
    "topology_delta_dropped_total", "Deltas dropped by the queue cap"
)
FLUSH_TOTAL = _r.counter(
    "topology_flush_total", "Delta flushes applied to the device adjacency"
)
FLUSH_LATENCY = _r.histogram(
    "topology_flush_seconds",
    "Delta flush latency (drain + CSR build + device refresh)",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, float("inf")),
)
QUERY_TOTAL = _r.counter(
    "topology_query_total", "est_rtt queries", ("source",)
)
INFERENCE_CACHE_HIT_RATE = _r.gauge(
    "topology_inference_cache_hit_rate",
    "Fraction of est_rtt queries served from the inference cache",
)
STALE_PURGED_TOTAL = _r.counter(
    "topology_stale_edges_purged_total", "Edges dropped by staleness decay"
)
