"""Device kernels over the padded adjacency: staleness decay, k-hop
EWMA-RTT aggregation, landmark min-plus RTT inference.

Two implementations of one contract: a jitted jax path (runs in HBM on
an accelerator; XLA:CPU otherwise) and a numpy twin for deployments
with no usable jax at all. Tests assert elementwise agreement, so the
numpy path is the semantic spec (same pattern as schema/native.py).

All shapes are static: arrays arrive padded to capacity with a
``valid`` mask (csr.AdjacencyStore.build_arrays), loop trip counts
(``k`` hops, ``iters`` relaxations) are compile-time constants — the
static-bound-with-masking idiom TPU tiling requires.

Distance math is LINEAR milliseconds — min-plus composition
d(a,l)+d(l,b) adds RTTs, which log-space would silently turn into a
product. Aggregation math is log1p-ms like every other RTT feature in
schema/features.py.
"""

# dfanalyze: device-hot — these kernels run per topology flush and per
# inference query; wrapper churn or host syncs here tax every schedule

from __future__ import annotations

import numpy as np

# distances at or above this are "no path" (float32-safe headroom)
INF_MS = 1e12


def _freshness(age_s, valid, half_life_s: float, xp):
    """Staleness decay: weight = valid · 2^(−age/half-life). A quiet
    edge fades smoothly out of every aggregate instead of pinning its
    last EWMA forever; purge (csr.purge_stale) is the terminal stage."""
    return valid * xp.exp2(-age_s / half_life_s)


def _segment_sum_np(data, seg, n):
    out = np.zeros((n,) + data.shape[1:], dtype=data.dtype)
    np.add.at(out, seg, data)
    return out


def _segment_min_np(data, seg, n):
    out = np.full((n,) + data.shape[1:], np.float32(INF_MS), dtype=data.dtype)
    np.minimum.at(out, seg, data)
    return out


class NumpyKernels:
    """Reference implementation; also the no-accelerator fallback."""

    backend = "numpy"

    def decay_weights(self, age_s, valid, half_life_s: float):
        return _freshness(
            np.asarray(age_s, np.float32), np.asarray(valid, np.float32),
            half_life_s, np,
        )

    def khop_rtt(self, edge_src, edge_dst, rtt_log_ms, weights, num_nodes: int, k: int):
        """[node_cap] per-node k-hop EWMA-RTT aggregate (log-ms).

        Hop 0 is the freshness-weighted mean of a node's own out-edge
        RTTs; each further hop mixes in the neighbors' aggregate at 0.5
        (EWMA over hop distance), so a node with few probes inherits
        structure from its neighborhood. Nodes with no fresh edges → 0.
        """
        w_rtt = _segment_sum_np(weights * rtt_log_ms, edge_src, num_nodes)
        w_tot = _segment_sum_np(weights, edge_src, num_nodes)
        h0 = w_rtt / np.maximum(w_tot, 1e-9)
        has = (w_tot > 1e-9).astype(np.float32)
        h = h0 * has
        for _ in range(k):
            nbr = _segment_sum_np(weights * h[edge_dst], edge_src, num_nodes)
            nbr = nbr / np.maximum(w_tot, 1e-9)
            h = (0.5 * h0 + 0.5 * nbr) * has
        return h

    def landmark_distances(
        self, edge_src, edge_dst, rtt_ms, weights,
        landmark_idx, landmark_valid, num_nodes: int, iters: int,
    ):
        """[node_cap, L] min-plus distances to each landmark over the
        (symmetrized) fresh adjacency. ``iters`` relaxation rounds ≈
        hop radius of the inference; unreached pairs stay INF_MS."""
        L = len(landmark_idx)
        cost = np.where(weights > 0, rtt_ms, np.float32(INF_MS)).astype(np.float32)
        D = np.full((num_nodes, L), np.float32(INF_MS), dtype=np.float32)
        D[landmark_idx, np.arange(L)] = np.where(
            landmark_valid > 0, np.float32(0), np.float32(INF_MS)
        )
        for _ in range(iters):
            cand = cost[:, None] + D[edge_dst]
            relaxed = _segment_min_np(cand, edge_src, num_nodes)
            D = np.minimum(D, relaxed)
        return D

    def est_from_landmarks(self, D, src_idx, dst_idx):
        """est[i] = min_l D[src_i, l] + D[dst_i, l]  (linear ms)."""
        return np.min(D[src_idx] + D[dst_idx], axis=-1)

    def gather_rtt_affinity(
        self, D, src_idx, dst_idx, direct_ms, has_direct, known
    ):
        """[N] rtt_affinity gathered straight from the resident
        adjacency — the wave-join feature column in one dispatch:
        landmark min-plus estimate per (src, dst) index pair, direct
        probe EWMAs (``direct_ms``, linear ms, masked by
        ``has_direct``) winning over inference, log1p-ms/10 normalized
        with the schema's 0.0 missing-value for unknown hosts
        (``known`` ≤ 0) and no-path pairs. A self pair is encoded by
        the caller as a 0 ms direct edge (affinity 0.0)."""
        D = np.asarray(D, np.float32)
        est_ms = np.min(D[src_idx] + D[dst_idx], axis=-1)
        ms = np.where(has_direct > 0, direct_ms, est_ms)
        miss = (np.asarray(known) <= 0) | (
            (np.asarray(has_direct) <= 0) & (est_ms >= np.float32(INF_MS / 2))
        )
        aff = np.log1p(np.maximum(ms, np.float32(0.0))) / np.float32(10.0)
        return np.where(miss, np.float32(0.0), aff).astype(np.float32)


_jit_cache: dict = {}


def _jitted_kernels():
    """The four jitted kernels, built once per PROCESS (not per
    JaxKernels instance): every engine, bench, and test instance shares
    one compiled-executable cache per (capacity, trip-count) tuple —
    the per-instance form recompiled identical kernels on every engine
    construction. Lazy so the numpy backend never imports jax."""
    fns = _jit_cache.get("kernels")
    if fns is not None:
        return fns
    import functools

    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("half_life_s",))
    def decay(age_s, valid, half_life_s):
        return _freshness(age_s, valid, half_life_s, jnp)

    @functools.partial(jax.jit, static_argnames=("num_nodes", "k"))
    def khop(edge_src, edge_dst, rtt_log_ms, weights, num_nodes, k):
        seg = functools.partial(
            jax.ops.segment_sum, num_segments=num_nodes
        )
        w_rtt = seg(weights * rtt_log_ms, edge_src)
        w_tot = seg(weights, edge_src)
        h0 = w_rtt / jnp.maximum(w_tot, 1e-9)
        has = (w_tot > 1e-9).astype(jnp.float32)
        h0 = h0 * has

        def hop(h, _):
            nbr = seg(weights * h[edge_dst], edge_src) / jnp.maximum(w_tot, 1e-9)
            return (0.5 * h0 + 0.5 * nbr) * has, None

        h, _ = jax.lax.scan(hop, h0, None, length=k)
        return h

    @functools.partial(jax.jit, static_argnames=("num_nodes", "iters"))
    def landmarks(
        edge_src, edge_dst, rtt_ms, weights,
        landmark_idx, landmark_valid, num_nodes, iters,
    ):
        L = landmark_idx.shape[0]
        cost = jnp.where(weights > 0, rtt_ms, INF_MS).astype(jnp.float32)
        D = jnp.full((num_nodes, L), INF_MS, dtype=jnp.float32)
        D = D.at[landmark_idx, jnp.arange(L)].min(
            jnp.where(landmark_valid > 0, 0.0, INF_MS).astype(jnp.float32)
        )

        def relax(D, _):
            cand = cost[:, None] + D[edge_dst]
            relaxed = jax.ops.segment_min(cand, edge_src, num_segments=num_nodes)
            return jnp.minimum(D, relaxed), None

        D, _ = jax.lax.scan(relax, D, None, length=iters)
        return D

    @jax.jit
    def est(D, src_idx, dst_idx):
        return jnp.min(D[src_idx] + D[dst_idx], axis=-1)

    @jax.jit
    def gather_aff(D, src_idx, dst_idx, direct_ms, has_direct, known):
        est_ms = jnp.min(D[src_idx] + D[dst_idx], axis=-1)
        ms = jnp.where(has_direct > 0, direct_ms, est_ms)
        miss = (known <= 0) | ((has_direct <= 0) & (est_ms >= INF_MS / 2))
        aff = jnp.log1p(jnp.maximum(ms, 0.0)) / 10.0
        return jnp.where(miss, 0.0, aff).astype(jnp.float32)

    fns = _jit_cache["kernels"] = (decay, khop, landmarks, est, gather_aff)
    return fns


class JaxKernels:
    """jitted twins — compiled once per (capacity, trip-count) tuple,
    shared process-wide (``_jitted_kernels``)."""

    backend = "jax"

    def __init__(self):
        (
            self._decay,
            self._khop,
            self._landmarks,
            self._est,
            self._gather_aff,
        ) = _jitted_kernels()

    def decay_weights(self, age_s, valid, half_life_s: float):
        return self._decay(age_s, valid, half_life_s=float(half_life_s))

    def khop_rtt(self, edge_src, edge_dst, rtt_log_ms, weights, num_nodes: int, k: int):
        return self._khop(
            edge_src, edge_dst, rtt_log_ms, weights, num_nodes=num_nodes, k=k
        )

    def landmark_distances(
        self, edge_src, edge_dst, rtt_ms, weights,
        landmark_idx, landmark_valid, num_nodes: int, iters: int,
    ):
        return self._landmarks(
            edge_src, edge_dst, rtt_ms, weights,
            landmark_idx, landmark_valid, num_nodes=num_nodes, iters=iters,
        )

    def est_from_landmarks(self, D, src_idx, dst_idx):
        return self._est(D, src_idx, dst_idx)

    def gather_rtt_affinity(
        self, D, src_idx, dst_idx, direct_ms, has_direct, known
    ):
        import jax.numpy as jnp

        # explicit boundary conversion (no-op for resident arrays): the
        # engine hands device copies, but direct callers (tests, tools)
        # pass numpy — make the transfer visible, not implicit in jit
        return self._gather_aff(
            jnp.asarray(D),
            jnp.asarray(src_idx),
            jnp.asarray(dst_idx),
            jnp.asarray(direct_ms),
            jnp.asarray(has_direct),
            jnp.asarray(known),
        )


def make_kernels(backend: str = "auto"):
    """``jax`` | ``numpy`` | ``auto`` (jax if importable, else numpy).
    Under ``JAX_PLATFORMS=cpu`` the jax path compiles for XLA:CPU — the
    numpy twin is for environments where jax itself is unusable."""
    if backend in ("auto", "jax"):
        try:
            return JaxKernels()
        except Exception:
            if backend == "jax":
                raise
    return NumpyKernels()
