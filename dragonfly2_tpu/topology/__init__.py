"""TPU-resident topology engine: the probe graph as a device sparse
adjacency (PAPER.md:33 — "the scheduler/networktopology probe graph
lives in HBM as a sparse adjacency").

The KV store (scheduler/networktopology.py) remains the durable,
multi-scheduler-shared record of probe state; this package maintains a
*live computational replica* of that graph on the accelerator so
scheduling decisions can read RTT structure without a KV walk:

- ``delta.DeltaQueue`` — batches ``enqueue_probe`` updates so device
  array refreshes amortize over many probes instead of running per-RPC.
- ``csr.AdjacencyStore`` — host-side interned edge store + padded CSR
  build (static shapes: capacities grow by doubling, so jit recompiles
  are logarithmic in graph growth, per the TPU static-shape rule).
- ``kernels`` — the device math, jitted under jax with a numpy
  twin for accelerator-less deployments: k-hop EWMA-RTT aggregation,
  landmark min-plus RTT inference, staleness decay.
- ``engine.TopologyEngine`` — the facade consumers wire against:
  est_rtt / neighbors / stats / rtt_affinity / centrality / export.
"""

from dragonfly2_tpu.topology.delta import DeltaQueue, EdgeDelta
from dragonfly2_tpu.topology.engine import TopologyConfig, TopologyEngine

__all__ = ["DeltaQueue", "EdgeDelta", "TopologyConfig", "TopologyEngine"]
