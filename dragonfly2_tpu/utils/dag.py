"""Generic DAG (reference parity: pkg/graph/dag/dag.go, vertex.go).

Backs the per-task peer tree in the scheduler: vertices are peers, an edge
parent→child means the child downloads pieces from the parent. Cycle
prevention keeps the download graph acyclic; in/out-degree queries drive the
candidate-parent filter rules (reference scheduling.go:500-571).

Thread-safe: the scheduler mutates the tree from concurrent RPC handlers.
"""

from __future__ import annotations

import threading
from typing import Generic, Iterator, TypeVar

V = TypeVar("V")


class DAGError(Exception):
    pass


class VertexNotFoundError(DAGError):
    pass


class VertexAlreadyExistsError(DAGError):
    pass


class EdgeAlreadyExistsError(DAGError):
    pass


class CycleError(DAGError):
    pass


class Vertex(Generic[V]):
    __slots__ = ("id", "value", "parents", "children")

    def __init__(self, vid: str, value: V):
        self.id = vid
        self.value = value
        self.parents: set[str] = set()
        self.children: set[str] = set()

    @property
    def in_degree(self) -> int:
        return len(self.parents)

    @property
    def out_degree(self) -> int:
        return len(self.children)


class DAG(Generic[V]):
    def __init__(self) -> None:
        self._vertices: dict[str, Vertex[V]] = {}
        self._lock = threading.RLock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._vertices)

    def __contains__(self, vid: str) -> bool:
        with self._lock:
            return vid in self._vertices

    def add_vertex(self, vid: str, value: V) -> None:
        with self._lock:
            if vid in self._vertices:
                raise VertexAlreadyExistsError(vid)
            self._vertices[vid] = Vertex(vid, value)

    def delete_vertex(self, vid: str) -> None:
        with self._lock:
            v = self._vertices.pop(vid, None)
            if v is None:
                return
            for pid in v.parents:
                self._vertices[pid].children.discard(vid)
            for cid in v.children:
                self._vertices[cid].parents.discard(vid)

    def get_vertex(self, vid: str) -> Vertex[V]:
        with self._lock:
            try:
                return self._vertices[vid]
            except KeyError:
                raise VertexNotFoundError(vid) from None

    def vertex_ids(self) -> list[str]:
        with self._lock:
            return list(self._vertices)

    def add_edge(self, from_id: str, to_id: str) -> None:
        """Add edge from→to, refusing self-loops, duplicates and cycles."""
        with self._lock:
            if from_id == to_id:
                raise CycleError(f"self loop on {from_id}")
            f = self.get_vertex(from_id)
            t = self.get_vertex(to_id)
            if to_id in f.children:
                raise EdgeAlreadyExistsError(f"{from_id}->{to_id}")
            if self._reachable(to_id, from_id):
                raise CycleError(f"{from_id}->{to_id} would create a cycle")
            f.children.add(to_id)
            t.parents.add(from_id)

    def delete_edge(self, from_id: str, to_id: str) -> None:
        with self._lock:
            f = self.get_vertex(from_id)
            t = self.get_vertex(to_id)
            f.children.discard(to_id)
            t.parents.discard(from_id)

    def delete_vertex_in_edges(self, vid: str) -> None:
        """Drop every parent edge of ``vid`` (peer switches parents)."""
        with self._lock:
            v = self.get_vertex(vid)
            for pid in list(v.parents):
                self._vertices[pid].children.discard(vid)
            v.parents.clear()

    def delete_vertex_out_edges(self, vid: str) -> None:
        with self._lock:
            v = self.get_vertex(vid)
            for cid in list(v.children):
                self._vertices[cid].parents.discard(vid)
            v.children.clear()

    def can_add_edge(self, from_id: str, to_id: str) -> bool:
        with self._lock:
            if from_id == to_id:
                return False
            if from_id not in self._vertices or to_id not in self._vertices:
                return False
            if to_id in self._vertices[from_id].children:
                return False
            return not self._reachable(to_id, from_id)

    def lenient_random_vertices(self, n: int) -> list[Vertex[V]]:
        """Up to ``n`` vertices in arbitrary order (dict order is fine)."""
        with self._lock:
            out = []
            for v in self._vertices.values():
                if len(out) >= n:
                    break
                out.append(v)
            return out

    def source_vertices(self) -> list[Vertex[V]]:
        with self._lock:
            return [v for v in self._vertices.values() if v.in_degree == 0]

    def sink_vertices(self) -> list[Vertex[V]]:
        with self._lock:
            return [v for v in self._vertices.values() if v.out_degree == 0]

    def descendants(self, vid: str) -> Iterator[str]:
        """BFS over children, excluding ``vid`` itself."""
        with self._lock:
            seen: set[str] = set()
            frontier = list(self.get_vertex(vid).children)
            while frontier:
                nxt = frontier.pop()
                if nxt in seen:
                    continue
                seen.add(nxt)
                frontier.extend(self._vertices[nxt].children)
            return iter(seen)

    def _reachable(self, src: str, dst: str) -> bool:
        """True if dst is reachable from src following child edges."""
        if src == dst:
            return True
        seen: set[str] = set()
        frontier = [src]
        while frontier:
            cur = frontier.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            v = self._vertices.get(cur)
            if v is not None:
                frontier.extend(v.children)
        return False
